"""Cluster state introspection API (`ray list tasks/actors/objects/...`).

reference parity: python/ray/util/state/api.py — list_* entry points backed
by the GCS task sink (gcs_task_manager.h:85) and per-node queries, aggregated
like dashboard/state_aggregator.py.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ray_tpu._private import rpc as rpc_lib
from ray_tpu._private import worker as worker_mod


def _gcs():
    return worker_mod.global_worker().core_worker._gcs


def _pool():
    return worker_mod.global_worker().core_worker._pool


def list_tasks(filters: Optional[Dict[str, Any]] = None,
               limit: int = 10000) -> List[Dict[str, Any]]:
    """Task records with state transitions + timestamps."""
    # Flush this process's buffered events first so a list right after a
    # get() sees the terminal state.
    worker_mod.global_worker().core_worker.task_events.flush()
    return _gcs().call("list_tasks", filters=filters, limit=limit)


def list_actors(filters: Optional[Dict[str, Any]] = None
                ) -> List[Dict[str, Any]]:
    infos = _gcs().call("list_actors")
    out = [{
        "actor_id": a.actor_id.hex(),
        "class_name": a.class_name,
        "name": a.name,
        "namespace": a.namespace,
        "state": a.state,
        "node_id": a.node_id.hex() if a.node_id else None,
        "num_restarts": a.num_restarts,
        "death_cause": a.death_cause,
    } for a in infos]
    if filters:
        out = [r for r in out
               if all(r.get(k) == v for k, v in filters.items())]
    return out


def list_nodes() -> List[Dict[str, Any]]:
    return [{
        "node_id": n.node_id.hex(),
        "state": "ALIVE" if n.alive else "DEAD",
        "address": n.address,
        "is_head": n.is_head,
        "resources_total": dict(n.resources_total),
        "labels": dict(n.labels),
    } for n in _gcs().call("get_all_nodes")]


def list_workers() -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    for n in _gcs().call("get_all_nodes"):
        if not n.alive:
            continue
        try:
            out.extend(_pool().get(tuple(n.address)).call("nm_list_workers"))
        except Exception:  # noqa: BLE001 - node died mid-listing
            pass
    return out


def _workers_by_node() -> Dict[Any, List[Dict[str, Any]]]:
    out: Dict[Any, List[Dict[str, Any]]] = {}
    for n in _gcs().call("get_all_nodes"):
        if not n.alive:
            continue
        try:
            out[tuple(n.address)] = _pool().get(
                tuple(n.address)).call("nm_list_workers")
        except Exception:  # noqa: BLE001 - node died mid-listing; treated as absent
            pass
    return out


def profile_worker_stack(worker_id: str,
                         timeout: float = 3.0) -> Dict[str, Any]:
    """Live all-thread stack dump of one worker (reference: dashboard
    reporter module / `ray stack` CLI, scripts.py:1810): resolves the
    worker's node and asks its node manager to SIGUSR1 the process and
    return the faulthandler dump."""
    for addr, workers in _workers_by_node().items():
        if any(w["worker_id"] == worker_id for w in workers):
            return _pool().get(addr).call(
                "nm_profile_worker", worker_id_hex=worker_id,
                timeout=timeout)
    raise KeyError(f"worker {worker_id[:12]} not found on any "
                   f"alive node")


def profile_all_worker_stacks(timeout: float = 3.0
                              ) -> List[Dict[str, Any]]:
    """Stack dumps for every live worker: ONE `nm_profile_workers` RPC
    per node — each node signals and collects all its workers in
    parallel — fanned out across nodes under a single overall deadline
    (the per-worker serial round trips this replaces scaled as
    nodes x workers). Nodes that don't answer contribute an error
    entry instead of stalling the dump."""
    from ray_tpu._private import spans as spans_lib
    alive = [n for n in _gcs().call("get_all_nodes") if n.alive]
    replies = spans_lib.pull_snapshots(
        [tuple(n.address) for n in alive], "nm_profile_workers",
        timeout=timeout + 2.0, call_kwargs={"timeout": timeout})
    answered = {addr for addr, _r, _t0, _t1 in replies}
    out: List[Dict[str, Any]] = []
    for _addr, reply, _t0, _t1 in replies:
        out.extend(reply.get("dumps", ()))
    for n in alive:
        if tuple(n.address) not in answered:
            out.append({"worker_id": None, "pid": None, "stack": "",
                        "node_id": n.node_id.hex(),
                        "error": "node unreachable within deadline"})
    return out


def list_objects() -> Dict[str, Any]:
    """Objects resident in every alive node's shared-memory store:
    {"objects": [...], "unreachable": [node ids]} — like logs_query, a
    node that doesn't answer is NAMED rather than silently absent (an
    empty-looking store on an unreachable node is not an empty store)."""
    out: List[Dict[str, Any]] = []
    unreachable: List[str] = []
    for n in _gcs().call("get_all_nodes"):
        if not n.alive:
            continue
        try:
            for rec in _pool().get(tuple(n.store_address)).call("store_list"):
                rec["node_id"] = n.node_id.hex()
                out.append(rec)
        except Exception:  # noqa: BLE001 - named in the reply instead
            unreachable.append(n.node_id.hex())
    return {"objects": out, "unreachable": unreachable}


def list_placement_groups() -> List[Dict[str, Any]]:
    return [{
        "placement_group_id": pg.pg_id.hex(),
        "name": pg.name,
        "state": pg.state,
        "strategy": pg.strategy,
        "bundles": list(pg.bundles),
        "bundle_nodes": list(pg.bundle_nodes),
    } for pg in _gcs().call("list_placement_groups")]


def summarize_tasks() -> Dict[str, int]:
    """Count of tasks per state (reference `ray summary tasks`)."""
    counts: Dict[str, int] = {}
    for rec in list_tasks():
        counts[rec.get("state", "?")] = counts.get(rec.get("state", "?"), 0) + 1
    return counts


def wait_graph() -> Dict[str, Any]:
    """Live actor waits-for graph + deadlocks-detected counter (the
    runtime counterpart of graftlint's RT001: blocking gets between
    actors, detected as they happen; see _private/wait_graph.py)."""
    return _gcs().call("wait_graph_snapshot")


def spans_snapshots() -> List[Dict[str, Any]]:
    """Every process's flight-recorder ring, clock-offset annotated
    (the raw material behind `ray_tpu timeline --spans`; see
    _private/spans.py)."""
    return _gcs().call("spans_collect")


def _resolve_actor_filter(actor: Optional[str]) -> Optional[str]:
    """`ray_tpu logs --actor` accepts a name or an id (prefix): names
    resolve through the GCS actor directory (newest matching actor
    wins — restarts keep the id, re-creations get the newest)."""
    if not actor:
        return None
    for a in reversed(list_actors()):
        if a["name"] == actor:
            return a["actor_id"]
    return actor  # treat as an id (prefix)


def logs(node_id: Optional[str] = None, worker_id: Optional[str] = None,
         actor: Optional[str] = None, actor_id: Optional[str] = None,
         task_id: Optional[str] = None, trace_id: Optional[str] = None,
         level: Optional[str] = None, match: Optional[str] = None,
         tail: int = 500, timeout: Optional[float] = None
         ) -> Dict[str, Any]:
    """Cluster log query (`ray_tpu logs`, dashboard /api/logs): ONE GCS
    fan-out round — node managers serve their filtered tail indexes,
    drivers their in-process rings — under a single overall deadline.
    Filters run server-side; `actor` takes a name or id. Returns
    {"records": [...], "unreachable": [node ids]}; each record carries
    node/worker/task/actor ids + trace id + level (log_plane.py)."""
    filters: Dict[str, Any] = {}
    if node_id:
        filters["node_id"] = node_id
    if worker_id:
        filters["worker_id"] = worker_id
    resolved = _resolve_actor_filter(actor) or actor_id
    if resolved:
        filters["actor_id"] = resolved
    if task_id:
        filters["task_id"] = task_id
    if trace_id:
        filters["trace_id"] = trace_id
    if level:
        filters["level"] = level
    if match:
        filters["match"] = match
    return _gcs().call("logs_query", filters=filters or None, tail=tail,
                       timeout=timeout)


def follow_logs(node_id: Optional[str] = None,
                worker_id: Optional[str] = None,
                actor: Optional[str] = None,
                actor_id: Optional[str] = None,
                task_id: Optional[str] = None,
                trace_id: Optional[str] = None,
                level: Optional[str] = None, match: Optional[str] = None,
                duration: Optional[float] = None,
                poll_timeout: float = 0.5):
    """Generator over NEW log records as they stream off the cluster's
    `worker_logs` pubsub channel (the same feed `log_to_driver`
    prints), filtered client-side with the query plane's filter set.
    Runs until `duration` elapses (forever when None — the CLI's
    --follow mode, ended by ^C)."""
    import queue as _queue
    import time as _time

    from ray_tpu._private import log_plane
    filters: Dict[str, Any] = {}
    for k, v in (("node_id", node_id), ("worker_id", worker_id),
                 ("actor_id", _resolve_actor_filter(actor) or actor_id),
                 ("task_id", task_id), ("trace_id", trace_id),
                 ("level", level), ("match", match)):
        if v:
            filters[k] = v
    q: "_queue.Queue" = _queue.Queue()
    live = [True]

    def _on_msg(msg):
        if live[0]:
            q.put(msg)

    cw = worker_mod.global_worker().core_worker
    token = cw.subscribe("worker_logs", _on_msg)
    deadline = None if duration is None else _time.monotonic() + duration
    try:
        while deadline is None or _time.monotonic() < deadline:
            try:
                msg = q.get(timeout=poll_timeout)
            except _queue.Empty:
                continue
            for rec in log_plane.filter_records(
                    msg.get("records") or (), filters):
                yield rec
    finally:
        live[0] = False
        # tear the subscription down end to end (callback + the GCS
        # entry) so repeated follows don't multiply the publish fan-out
        try:
            cw.unsubscribe("worker_logs", token)
        except Exception:  # noqa: BLE001 - cluster gone mid-follow
            pass


def postmortems(limit: int = 50) -> List[Dict[str, Any]]:
    """Crash-postmortem summaries from the GCS's bounded ring, newest
    last (worker/actor deaths bundled by the node manager, task
    failures by the executor). Fetch one bundle — last log lines, span
    tail, gauges — with get_postmortem(id)."""
    return _gcs().call("postmortem_list", limit=limit)


def get_postmortem(postmortem_id: str) -> Optional[Dict[str, Any]]:
    """One full postmortem bundle (log_tail + span_tail included), or
    None if it aged out of the ring."""
    return _gcs().call("postmortem_get", postmortem_id=postmortem_id)


def serve_requests(deployment: Optional[str] = None,
                   errors: bool = False,
                   slowest: Optional[int] = None,
                   timeout: float = 10.0) -> Dict[str, Any]:
    """Captured serve requests from every ingress proxy's bounded ring
    (`ray_tpu serve requests`, dashboard /api/serve/requests): the
    slowest and all errored requests, each with its trace id,
    deployment, status code, per-stage latency breakdown, and error.
    Proxies self-register as named actors (SERVE_PROXY_*, namespace
    "serve"); ones that don't answer are named in `unreachable` — an
    empty capture from an unreachable proxy is not an empty capture.
    `errors=True` restricts to errored requests; `slowest=N` returns
    the N slowest across all proxies; `deployment` filters either
    view."""
    import ray_tpu
    entries: List[Dict[str, Any]] = []
    proxies = 0
    unreachable: List[str] = []
    pending: List[tuple] = []  # (proxy name, snapshot ref)
    for a in list_actors():
        name = a.get("name") or ""
        if a.get("state") == "DEAD" or \
                not name.startswith("SERVE_PROXY_"):
            continue
        try:
            h = ray_tpu.get_actor(name, namespace=a.get("namespace")
                                  or "serve")
            pending.append((name, h.requests_snapshot.remote(
                deployment=deployment, errors=errors,
                slowest=slowest)))
        except Exception:  # noqa: BLE001 - named in the reply instead
            unreachable.append(name)
    if pending:
        # one batched wait bounds the whole fan-out by `timeout`
        # instead of timeout x proxies
        ready, _ = ray_tpu.wait([r for _n, r in pending],
                                num_returns=len(pending),
                                timeout=timeout)
        ready_set = {r.hex() for r in ready}
        for name, ref in pending:
            if ref.hex() not in ready_set:
                unreachable.append(name)
                continue
            try:
                # ready refs: the get is a local materialize, zero
                # extra round trips
                entries.extend(  # graftlint: disable=RT002
                    ray_tpu.get(ref, timeout=timeout))
                proxies += 1
            except Exception:  # noqa: BLE001 - named in the reply instead
                unreachable.append(name)
    if slowest is not None:
        # composes with errors=True: the N slowest ERRORED requests
        entries.sort(key=lambda e: e.get("total_s") or 0.0,
                     reverse=True)
        entries = entries[:slowest]
    else:
        entries.sort(key=lambda e: e.get("ts") or 0.0)
    return {"requests": entries, "proxies": proxies,
            "unreachable": unreachable}


def serve_fleet() -> Dict[str, Any]:
    """Ingress fleet state (serve/_private/proxy_fleet/): per-node
    proxies with ports, health, drain flags, plus each live proxy's
    admission snapshot (in-flight counts, limits, shed totals). CLI:
    `ray_tpu serve fleet`; dashboard: /api/serve/fleet."""
    import ray_tpu
    from ray_tpu.serve._private.proxy_fleet.fleet import (
        PROXY_NAME_PREFIX)
    try:
        controller = ray_tpu.get_actor("SERVE_CONTROLLER",
                                       namespace="serve")
    except Exception:  # noqa: BLE001 - serve not running
        return {"enabled": False, "proxies": []}
    status = ray_tpu.get(controller.fleet_status.remote(), timeout=30)
    # enrich with live admission snapshots, one batched wait
    pending = []
    for p in status.get("proxies", ()):
        try:
            h = ray_tpu.get_actor(
                f"{PROXY_NAME_PREFIX}{p['node_id'][:12]}",
                namespace="serve")
            pending.append((p, h.status.remote()))
        except Exception:  # noqa: BLE001 - proxy mid-replacement
            p["admission"] = None
    if pending:
        ready, _ = ray_tpu.wait([r for _p, r in pending],
                                num_returns=len(pending), timeout=10)
        ready_set = {r.hex() for r in ready}
        for p, ref in pending:
            if ref.hex() in ready_set:
                try:
                    # ready refs: local materialize, zero extra RPCs
                    live = ray_tpu.get(ref, timeout=10)  # graftlint: disable=RT002
                    p["admission"] = live.get("admission")
                    p["inflight"] = live.get("inflight")
                    p["shed_total"] = live.get("shed_total")
                except Exception:  # noqa: BLE001 - died mid-query
                    p["admission"] = None
            else:
                p["admission"] = None
    return status


def replay_shards() -> Dict[str, Any]:
    """Distributed replay plane state (rllib/utils/replay/): every live
    ReplayShardActor found in the actor registry, enriched with each
    shard's own stats() snapshot (size, added, evicted, priority
    updates, unmatched tickets). CLI: `ray_tpu replay`; dashboard:
    /api/replay."""
    import ray_tpu
    from ray_tpu.rllib.utils.replay import REPLAY_NAMESPACE

    records = list_actors(filters={"class_name": "ReplayShardActor"})
    shards: List[Dict[str, Any]] = []
    pending = []
    for rec in records:
        row: Dict[str, Any] = {
            "actor_id": rec["actor_id"],
            "name": rec["name"],
            "state": rec["state"],
            "node_id": rec["node_id"],
            "num_restarts": rec["num_restarts"],
            "stats": None,
        }
        shards.append(row)
        if rec["state"] != "ALIVE" or not rec["name"]:
            continue
        try:
            h = ray_tpu.get_actor(rec["name"],
                                  namespace=REPLAY_NAMESPACE)
            pending.append((row, h.stats.remote()))
        except Exception:  # noqa: BLE001 - died mid-listing
            pass
    if pending:
        ready, _ = ray_tpu.wait([r for _row, r in pending],
                                num_returns=len(pending), timeout=10)
        ready_set = {r.hex() for r in ready}
        for row, ref in pending:
            if ref.hex() in ready_set:
                try:
                    # ready refs: local materialize, zero extra RPCs
                    row["stats"] = ray_tpu.get(ref, timeout=10)  # graftlint: disable=RT002
                except Exception:  # noqa: BLE001 - died mid-query
                    pass
    live = [s["stats"] for s in shards if s["stats"]]
    return {
        "num_shards": len(shards),
        "num_alive": sum(1 for s in shards if s["state"] == "ALIVE"),
        "total_size": sum(s["size"] for s in live),
        "total_added": sum(s["added"] for s in live),
        "total_unmatched_priority_updates": sum(
            s["unmatched_priority_updates"] for s in live),
        "shards": shards,
    }


def chaos_rules() -> Dict[str, Any]:
    """Installed chaos rules + cluster-wide fired counts (the runtime
    view behind `ray_tpu chaos list` and the dashboard /api/chaos)."""
    return _gcs().call("chaos_list")


def cluster_metrics(fresh: bool = False) -> Dict[str, Any]:
    """Cluster-wide metrics: per-process registry snapshots (harvested
    GCS → node managers → workers, plus drivers) and the cluster-merged
    series/wire views (_private/metrics_plane.py), all from ONE harvest
    round so the views are mutually consistent. Backs the dashboard
    /api/metrics route and `ray_tpu metrics dump --format=json`;
    `fresh=True` forces a harvest-NOW fan-out first, like
    cluster_metrics_text(fresh=True)."""
    return _gcs().call("metrics_merged", fresh=fresh)


def cluster_metrics_text(fresh: bool = False) -> str:
    """The cluster-merged registry in Prometheus exposition format —
    what the dashboard /metrics endpoint serves: every harvested series
    labeled by proc + node, histogram buckets cumulative. Scrapes ride
    the GCS sampler's last round (at most one sample interval stale);
    `fresh=True` forces a harvest-NOW fan-out first — for operators
    and tests that just induced the state they want to see."""
    return _gcs().call("metrics_prometheus", force=fresh)


def metrics_history(names: Optional[List[str]] = None,
                    limit: Optional[int] = None) -> Dict[str, Any]:
    """Recent samples from the GCS's in-memory time-series ring
    ({"interval_s", "samples": [(wall_ts, {series: value}), ...]}) —
    rates/deltas/sparklines for `ray_tpu top` without an external
    Prometheus."""
    return _gcs().call("metrics_history", names=names, limit=limit)


def metrics_history_range(names: Optional[List[str]] = None,
                          since_s: float = 600.0,
                          tier: str = "raw") -> Dict[str, Any]:
    """Lookback-window read of the GCS's durable tiered history
    (_private/metrics_history.py): samples with wall ts within the last
    `since_s` seconds from `tier` ("raw" | "30s" | "5min"), reaching
    through the on-disk segments — including ones replayed from before
    a GCS restart. Downsampled tiers carry counters as per-window
    deltas and gauges as [min, mean, max]."""
    return _gcs().call("metrics_history_range", names=names,
                       since_s=since_s, tier=tier)


def goodput(job: Optional[str] = None,
            window_s: Optional[float] = None,
            fresh: bool = False) -> Dict[str, Any]:
    """Per-job goodput/badput ledger view (_private/goodput.py):
    lifetime bucket totals from the harvested
    `ray_tpu_goodput_seconds_total{job,bucket}` series plus each live
    ledger's in-flight snapshot (current bucket + age), with
    productive fraction per job. `window_s` restricts the totals to
    the recent window by diffing the durable raw history tier instead
    of lifetime counters. `fresh=True` harvests NOW first (sub-second
    view for tests/CLI)."""
    from ray_tpu._private.goodput import METRIC, SNAPSHOT_KEY
    merged = cluster_metrics(fresh=fresh)
    prefix = METRIC + "{"

    def _tags(key: str) -> Dict[str, str]:
        out: Dict[str, str] = {}
        for part in key[len(prefix):-1].split(","):
            if "=" in part:
                k, v = part.split("=", 1)
                out[k] = v
        return out

    def _collect(series: Dict[str, Any]) -> Dict[str, Dict[str, float]]:
        jobs: Dict[str, Dict[str, float]] = {}
        for key, v in series.items():
            if not (key.startswith(prefix) and key.endswith("}")):
                continue
            if isinstance(v, (list, tuple)):
                v = v[1]  # downsampled gauge artifact; counters are flat
            tags = _tags(key)
            j, b = tags.get("job"), tags.get("bucket")
            if j and b:
                jobs.setdefault(j, {})[b] = \
                    jobs.get(j, {}).get(b, 0.0) + float(v)
        return jobs

    totals = _collect(merged.get("series", {}))
    if window_s is not None:
        hist = metrics_history_range(names=[METRIC],
                                     since_s=float(window_s),
                                     tier="raw")
        samples = hist.get("samples") or []
        if samples:
            base = _collect(samples[0][1])
            for j, buckets in totals.items():
                jb = base.get(j, {})
                for b in list(buckets):
                    buckets[b] = max(0.0,
                                     buckets[b] - jb.get(b, 0.0))
    # live in-flight snapshots ride the harvest as a snapshot extra
    inflight: Dict[str, Any] = {}
    for snap in merged.get("procs", ()):
        extra = snap.get(SNAPSHOT_KEY)
        if extra:
            for j, view in (extra.get("jobs") or {}).items():
                inflight[j] = {"bucket": view.get("bucket"),
                               "bucket_age_s": view.get("bucket_age_s"),
                               "uptime_s": view.get("uptime_s"),
                               "proc": snap.get("proc")}
    jobs_out: Dict[str, Any] = {}
    names = set(totals) | set(inflight)
    for j in sorted(names):
        if job is not None and j != job:
            continue
        buckets = totals.get(j, {})
        accounted = sum(buckets.values())
        productive = buckets.get("productive_step", 0.0)
        jobs_out[j] = {
            "buckets": {b: round(v, 3)
                        for b, v in sorted(buckets.items())},
            "accounted_s": round(accounted, 3),
            "productive_s": round(productive, 3),
            "productive_frac": round(productive / accounted, 4)
            if accounted else None,
            "in_flight": inflight.get(j),
        }
    return {"ts": merged.get("ts"),
            "window_s": window_s,
            "jobs": jobs_out}


def metrics_configure(**knobs: Any) -> Dict[str, Any]:
    """Tune the GCS metrics plane + watchdog live, no restart
    (_private/metrics_plane.py configure): `interval_s`, `cooldown_s`,
    probe thresholds (`gang_heartbeat_stale_s`, `wait_edge_age_s`,
    ...), and the runtime `step_deadline_s` override every gang
    supervisor picks up on its next heartbeat query (<= 0 clears it,
    back to ScalingConfig / auto-calibration). Returns the effective
    settings."""
    return _gcs().call("metrics_configure", **knobs)


def health_alerts(limit: int = 100) -> List[Dict[str, Any]]:
    """HEALTH_ALERT events the metrics watchdog emitted (invariant
    probes over the harvested series; see _private/metrics_plane.py)."""
    return list_cluster_events(event_type="HEALTH_ALERT", limit=limit)


def emit_event(event_type: str, message: str = "",
               severity: str = "INFO", **fields: Any) -> None:
    """Application-level structured event into the cluster event table
    (reference util/event.h RayEvent / python event_logger). Best
    effort — telemetry must never break the caller."""
    from ray_tpu._private.events import emit_via
    emit_via(_gcs().call, "app", event_type, message, severity, **fields)


def list_cluster_events(event_type: Optional[str] = None,
                        severity: Optional[str] = None,
                        limit: int = 1000) -> List[Dict[str, Any]]:
    """Structured lifecycle events (reference dashboard event module):
    node deaths, actor restarts, OOM kills, autoscaling actions."""
    return _gcs().call("list_events", event_type=event_type,
                       severity=severity, limit=limit)


def object_store_stats() -> Dict[str, Any]:
    """Per-node store stats incl. spill/restore counters (`ray memory`):
    {"stats": [...], "unreachable": [node ids]} — unreachable nodes are
    named, matching logs_query semantics."""
    out = []
    unreachable: List[str] = []
    for n in _gcs().call("get_all_nodes"):
        if not n.alive:
            continue
        try:
            stats = _pool().get(tuple(n.store_address)).call("store_stats")
            stats["node_id"] = n.node_id.hex()
            out.append(stats)
        except Exception:  # noqa: BLE001 - named in the reply instead
            unreachable.append(n.node_id.hex())
    return {"stats": out, "unreachable": unreachable}


def profile(duration: float = 5.0, hz: Optional[float] = None,
            device: bool = False,
            node_id: Optional[str] = None,
            worker_id: Optional[str] = None,
            actor: Optional[str] = None,
            trace_id: Optional[str] = None) -> Dict[str, Any]:
    """Cluster CPU profile (`ray_tpu profile`, dashboard /api/profile):
    one GCS fan-out samples every process's threads for `duration`
    seconds at `hz`, task/actor/trace-attributed, merged clock-free.
    Returns {"profiles": [per-process folded-stack profiles],
    "unreachable": [node ids], ...} — render with
    profiler.to_speedscope / to_folded. Filters select processes by
    node/worker/actor id prefix (actor also takes a name) and stacks by
    trace id. device=True instead runs jax profiler traces on
    jax-initialized workers and reports xplane dirs."""
    from ray_tpu._private import profiler as profiler_lib
    from ray_tpu._private.config import Config
    out = _gcs().call("profile_collect",
                      duration_s=duration,
                      hz=float(hz if hz is not None
                               else Config.profile_default_hz),
                      device=device)
    if not device and (node_id or worker_id or actor or trace_id):
        out["profiles"] = profiler_lib.filter_profiles(
            out["profiles"], node_id=node_id, worker_id=worker_id,
            actor_id=_resolve_actor_filter(actor),
            trace_id=trace_id)
    return out


def ownership(object_id: Optional[str] = None, limit: int = 200,
              timeout: Optional[float] = None) -> Dict[str, Any]:
    """Cluster ownership-protocol view (`ray_tpu ownership`, dashboard
    /api/ownership; _private/ownership.py): every process's live
    RefState rows (what holds each object alive — local refs, arg/
    transit pins, borrower registrations, replica reader leases),
    per-scheduling-key LeaseState summaries (request slots, parked
    counts, held leases, pipeline depth), node managers' held leases +
    store reader-lease/pin residency, and each process's bounded
    transition-ring tail — so a stuck object explains itself.
    `object_id` (hex prefix) restricts rows and transitions to one
    object. Anomaly counts (`unmatched:*` / `illegal:*` transitions)
    are aggregated cluster-wide; unreachable nodes are named."""
    return _gcs().call("ownership_collect", object_id=object_id,
                       limit=limit, timeout=timeout)


def autoscaler_instances(limit: int = 200) -> Dict[str, Any]:
    """Autoscaler v2 lifecycle view (`ray_tpu autoscaler`, dashboard
    /api/autoscaler; autoscaler/v2.py): the latest instance table
    (instance id, node type, lifecycle status QUEUED/REQUESTED/
    ALLOCATED/RAY_RUNNING/TERMINATING/TERMINATED, retries, age in
    state) plus the most recent `limit` lifecycle transitions the
    autoscaler reported. Live subscribers use the
    "autoscaler_lifecycle" pubsub channel instead of polling this."""
    return _gcs().call("autoscaler_v2_state", limit=limit)


def locks(timeout: Optional[float] = None) -> Dict[str, Any]:
    """Cluster lockdep snapshot (`ray_tpu locks`, dashboard
    /api/locks): every process's traced locks (hold counts/times,
    current holders, threads waiting) and its acquisition-order edge
    graph, with any observed order-inversion cycle called out per
    process. Unreachable nodes are named — an empty lock list is only
    meaningful when coverage was complete."""
    return _gcs().call("locks_collect", timeout=timeout)


def memory_table(group_by: Optional[str] = None,
                 top: Optional[int] = None,
                 timeout: Optional[float] = None) -> Dict[str, Any]:
    """Cluster object table (`ray_tpu memory`): every object joined
    across its owner's reference table and the stores where bytes are
    resident — owner identity, local refs, borrower pins, reader
    leases, creation callsite (when RAY_TPU_memory_callsite_capture=1),
    and per-node residency (size/pinned/leases/age, primary vs
    replica). group_by aggregates rows by callsite|actor|node|owner;
    `top` keeps the N largest. Unreachable nodes are named."""
    from ray_tpu._private import memory_plane as memory_plane_lib
    out = _gcs().call("memory_collect", timeout=timeout)
    out["total_objects"] = len(out["objects"])
    if top and not group_by:
        out["objects"] = out["objects"][:int(top)]
    if group_by:
        out["groups"] = memory_plane_lib.group_rows(
            out["objects"], group_by, top=top)
    return out
