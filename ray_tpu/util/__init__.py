"""ray_tpu.util: public utility APIs (placement groups, scheduling
strategies, host-side collectives, state introspection)."""

from ray_tpu.util.placement_group import (  # noqa: F401
    PlacementGroup,
    get_current_placement_group,
    placement_group,
    placement_group_table,
    remove_placement_group,
)
from ray_tpu.util.actor_pool import ActorPool  # noqa: F401
from ray_tpu.util.queue import Queue  # noqa: F401
from ray_tpu.util import tpu_profiler  # noqa: F401
from ray_tpu.util.scheduling_strategies import (  # noqa: F401
    NodeAffinitySchedulingStrategy,
    PlacementGroupSchedulingStrategy,
    SpreadSchedulingStrategy,
)

__all__ = [
    "PlacementGroup", "placement_group", "remove_placement_group",
    "get_current_placement_group", "placement_group_table",
    "PlacementGroupSchedulingStrategy", "NodeAffinitySchedulingStrategy",
    "SpreadSchedulingStrategy", "Queue", "ActorPool", "tpu_profiler",
]
