"""Distributed tracing: trace-context propagation + trace queries.

reference parity: python/ray/util/tracing/tracing_helper.py — the trace
context rides inside the task spec (_DictPropagator) so every task an
operation fans out to shares one trace id, with parent task links. No
OpenTelemetry dependency: spans ARE the task-event records (state API /
timeline), queried by trace id.
"""

from __future__ import annotations

import contextlib
import uuid
from typing import Any, Dict, List, Optional

from ray_tpu._private import worker as worker_mod


def get_current_trace_id() -> Optional[str]:
    """The trace id of the currently-executing task (None in a driver
    outside any start_trace block)."""
    w = worker_mod.global_worker_or_none()
    if w is None:
        return None
    return w.core_worker.current_trace_id()


@contextlib.contextmanager
def use_trace(trace_id: str, name: Optional[str] = None):
    """Adopt an EXTERNALLY-minted trace id for the current thread:
    every task submitted inside the block (and transitively, their
    children) carries it. This is the ingress half of request tracing —
    the Serve HTTP/gRPC proxies wrap each request in use_trace(<the
    X-Request-Id header, or a minted id>) so one id links proxy →
    handle → replica → nested deployment calls in `ray_tpu timeline
    --trace-id` (see README "Serve request telemetry")."""
    w = worker_mod.global_worker()
    cw = w.core_worker
    prev_id = cw.current_trace_id()
    prev_name = cw.current_trace_name()
    cw.set_current_trace(trace_id, name=name)
    try:
        yield trace_id
    finally:
        cw.set_current_trace(prev_id, name=prev_name)


@contextlib.contextmanager
def start_trace(name: str = ""):
    """Group every task submitted in this block (and transitively, their
    children) under one trace id; yields the id. `name` labels the
    block's directly-submitted task records (field `trace_name`)."""
    w = worker_mod.global_worker()
    cw = w.core_worker
    prev_id = cw.current_trace_id()
    prev_name = cw.current_trace_name()
    trace_id = uuid.uuid4().hex[:16]
    cw.set_current_trace(trace_id, name=name or None)
    try:
        yield trace_id
    finally:
        cw.set_current_trace(prev_id, name=prev_name)


def get_trace(trace_id: str) -> List[Dict[str, Any]]:
    """All task records of one trace, submission-ordered (reference:
    `ray timeline` filtered to a trace)."""
    from ray_tpu.util import state as state_api
    records = state_api.list_tasks(filters={"trace_id": trace_id})
    return sorted(records, key=lambda r: r.get("ts_submitted", 0.0))


def trace_tree(trace_id: str) -> Dict[str, List[Dict[str, Any]]]:
    """parent task id (or 'root') -> child task records."""
    tree: Dict[str, List[Dict[str, Any]]] = {}
    for rec in get_trace(trace_id):
        parent = rec.get("parent_task_id") or "root"
        tree.setdefault(parent, []).append(rec)
    return tree
