"""Placement groups: gang reservation of resource bundles.

reference parity: python/ray/util/placement_group.py:41,146,257,312 —
`PlacementGroup` handle, `placement_group()` factory, `remove_placement_group`,
`get_current_placement_group`; strategies PACK/SPREAD/STRICT_PACK/
STRICT_SPREAD scheduled by the GCS with 2-phase prepare/commit across node
managers (reference gcs_placement_group_scheduler.h:274).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ray_tpu._private.ids import PlacementGroupID

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


def _core():
    from ray_tpu._private.worker import global_worker
    return global_worker().core_worker


@dataclass
class PlacementGroup:
    """Handle to a placement group (reference placement_group.py:41)."""

    id: PlacementGroupID
    bundle_specs: List[Dict[str, float]] = field(default_factory=list)

    @property
    def bundle_count(self) -> int:
        return len(self.bundle_specs)

    def _info(self):
        return _core()._gcs.call(
            "get_placement_group", pg_id_hex=self.id.hex())

    def ready(self):
        """ObjectRef that resolves when the group is committed — schedules
        a trivial task inside bundle 0 (reference placement_group.py:90:
        ready() is implemented as a 0-CPU task in the group)."""
        import ray_tpu
        from ray_tpu.util.scheduling_strategies import (
            PlacementGroupSchedulingStrategy)

        @ray_tpu.remote
        def _pg_ready():
            return True

        return _pg_ready.options(
            num_cpus=0,
            scheduling_strategy=PlacementGroupSchedulingStrategy(
                placement_group=self,
                placement_group_bundle_index=0)).remote()

    def wait(self, timeout_seconds: float = 30.0) -> bool:
        """Block until CREATED (or timeout). reference
        placement_group.py:111."""
        deadline = time.monotonic() + timeout_seconds
        while time.monotonic() < deadline:
            info = self._info()
            if info is not None and info.state == "CREATED":
                return True
            if info is not None and info.state in ("REMOVED", "INFEASIBLE"):
                return False
            time.sleep(0.05)
        return False

    def is_ready(self) -> bool:
        info = self._info()
        return info is not None and info.state == "CREATED"


def placement_group(bundles: List[Dict[str, float]],
                    strategy: str = "PACK",
                    name: str = "",
                    lifetime: Optional[str] = None) -> PlacementGroup:
    """Create a placement group asynchronously (reference
    placement_group.py:146)."""
    if strategy not in VALID_STRATEGIES:
        raise ValueError(
            f"strategy must be one of {VALID_STRATEGIES}, got {strategy!r}")
    if not bundles:
        raise ValueError("placement group requires at least one bundle")
    for b in bundles:
        if not b or any(v < 0 for v in b.values()):
            raise ValueError(f"invalid bundle {b!r}")

    cw = _core()
    pg_id = PlacementGroupID.from_random()
    cw._gcs.call(
        "create_placement_group", pg_id_hex=pg_id.hex(),
        bundles=[dict(b) for b in bundles], strategy=strategy, name=name,
        detached=(lifetime == "detached"),
        creator_job_id=cw.job_id.hex())
    return PlacementGroup(id=pg_id, bundle_specs=[dict(b) for b in bundles])


def remove_placement_group(pg: PlacementGroup) -> None:
    """reference placement_group.py:257."""
    _core()._gcs.call("remove_placement_group", pg_id_hex=pg.id.hex())


def placement_group_table() -> Dict[str, Dict]:
    """All placement groups (reference placement_group.py:285)."""
    infos = _core()._gcs.call("list_placement_groups")
    return {
        info.pg_id.hex(): {
            "placement_group_id": info.pg_id.hex(),
            "name": info.name,
            "bundles": {i: b for i, b in enumerate(info.bundles)},
            "strategy": info.strategy,
            "state": info.state,
            "bundle_nodes": list(info.bundle_nodes),
        }
        for info in infos
    }


def get_current_placement_group() -> Optional[PlacementGroup]:
    """The placement group of the current task/actor, if it was scheduled
    into one (reference placement_group.py:312)."""
    cw = _core()
    pg_id = getattr(cw, "current_placement_group_id", None)
    if pg_id is None:
        return None
    info = cw._gcs.call("get_placement_group", pg_id_hex=pg_id.hex())
    if info is None:
        return None
    return PlacementGroup(id=pg_id, bundle_specs=list(info.bundles))
