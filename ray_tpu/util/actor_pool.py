"""ActorPool: map work over a fixed set of actors.

reference parity: python/ray/util/actor_pool.py — submit(fn, value) /
get_next() / get_next_unordered() / map() / map_unordered() over a pool,
keeping every actor busy with at most one in-flight item each and
handing free actors the next pending value.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, List

import ray_tpu


class ActorPool:
    def __init__(self, actors: List[Any]):
        self._idle: List[Any] = list(actors)
        self._in_flight: dict = {}          # ref -> actor
        self._pending: List[tuple] = []     # (fn, value)
        self._order: List[Any] = []         # submission-ordered refs

    def submit(self, fn: Callable[[Any, Any], Any], value: Any) -> None:
        """fn(actor, value) -> ObjectRef (e.g. lambda a, v:
        a.work.remote(v))."""
        if self._idle:
            actor = self._idle.pop()
            ref = fn(actor, value)
            self._in_flight[ref] = actor
            self._order.append(ref)
        else:
            self._pending.append((fn, value))

    def _reclaim(self, ref: Any) -> None:
        actor = self._in_flight.pop(ref)
        if self._pending:
            fn, value = self._pending.pop(0)
            nxt = fn(actor, value)
            self._in_flight[nxt] = actor
            self._order.append(nxt)
        else:
            self._idle.append(actor)

    def has_next(self) -> bool:
        return bool(self._order)

    def get_next(self, timeout: float = None) -> Any:
        """Next result in SUBMISSION order. On timeout the result stays
        retrievable and the actor stays tracked; on a task error the
        actor still returns to the pool (the error re-raises)."""
        if not self._order:
            raise StopIteration("no pending results")
        ref = self._order[0]
        try:
            value = ray_tpu.get(ref, timeout=timeout)
        except ray_tpu.exceptions.GetTimeoutError:
            raise  # nothing consumed; call again later
        except Exception:
            self._order.pop(0)
            self._reclaim(ref)
            raise
        self._order.pop(0)
        self._reclaim(ref)
        return value

    def get_next_unordered(self, timeout: float = None) -> Any:
        """Whichever pending result finishes first."""
        if not self._order:
            raise StopIteration("no pending results")
        ready, _ = ray_tpu.wait(list(self._in_flight),
                                num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("no result within timeout")
        ref = ready[0]
        self._order.remove(ref)
        try:
            value = ray_tpu.get(ref)
        except Exception:
            self._reclaim(ref)  # failed task must not strand its actor
            raise
        self._reclaim(ref)
        return value

    def map(self, fn: Callable[[Any, Any], Any],
            values: Iterable[Any]) -> Iterator[Any]:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable[[Any, Any], Any],
                      values: Iterable[Any]) -> Iterator[Any]:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    def has_free(self) -> bool:
        return bool(self._idle)

    def pop_idle(self) -> Any:
        return self._idle.pop() if self._idle else None

    def push(self, actor: Any) -> None:
        self._idle.append(actor)
