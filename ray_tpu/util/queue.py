"""Distributed FIFO queue backed by an actor.

reference parity: python/ray/util/queue.py — Queue wraps a _QueueActor
with put/get (blocking with timeout), qsize/empty/full, put_nowait/
get_nowait and batch variants; usable from any process in the cluster
(pass the Queue object into tasks/actors).
"""

from __future__ import annotations

from typing import Any, List, Optional

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    """The server side. Blocking semantics are implemented with
    condition variables inside the actor (it runs with max_concurrency
    so parked gets don't stall puts)."""

    def __init__(self, maxsize: int):
        import collections
        import threading
        self._maxsize = maxsize
        self._items: "collections.deque" = collections.deque()
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._not_full = threading.Condition(self._lock)

    def qsize(self) -> int:
        with self._lock:
            return len(self._items)

    def put(self, item: Any, timeout: Optional[float] = None) -> bool:
        with self._not_full:
            if self._maxsize > 0:
                if not self._not_full.wait_for(
                        lambda: len(self._items) < self._maxsize,
                        timeout=timeout):
                    return False
            self._items.append(item)
            self._not_empty.notify()
            return True

    def get(self, timeout: Optional[float] = None):
        with self._not_empty:
            if not self._not_empty.wait_for(lambda: self._items,
                                            timeout=timeout):
                return (False, None)
            item = self._items.popleft()
            self._not_full.notify()
            return (True, item)

    def put_batch(self, items: List[Any],
                  timeout: Optional[float] = None) -> bool:
        """All-or-nothing: waits for capacity for the WHOLE batch, so a
        timeout never leaves a partial insertion for the client to
        retry-and-duplicate. A batch larger than maxsize can never fit."""
        with self._not_full:
            if self._maxsize > 0:
                need = len(items)
                if need > self._maxsize:
                    return False
                if not self._not_full.wait_for(
                        lambda: self._maxsize - len(self._items) >= need,
                        timeout=timeout):
                    return False
            self._items.extend(items)
            self._not_empty.notify_all()
            return True

    def get_batch(self, max_items: int) -> List[Any]:
        with self._lock:
            out = []
            while self._items and len(out) < max_items:
                out.append(self._items.popleft())
            self._not_full.notify_all()
            return out


class Queue:
    """Client handle; picklable (travels into tasks/actors)."""

    def __init__(self, maxsize: int = 0, *, _actor: Any = None):
        self.maxsize = maxsize
        if _actor is not None:
            self._actor = _actor
            return
        cls = ray_tpu.remote(_QueueActor)
        # parked blocking gets/puts each occupy an executor thread
        self._actor = cls.options(num_cpus=0,
                                  max_concurrency=16).remote(maxsize)

    def __reduce__(self):
        # ship the handle, not a fresh queue: all holders share the actor
        return (_rebuild_queue, (self.maxsize, self._actor))

    # Blocking calls loop over SHORT server-side waits (≤ this slice):
    # a call that parked indefinitely would pin one of the actor's
    # max_concurrency executor threads — with all threads parked, the
    # put that would wake them could never run (hard deadlock).
    _WAIT_SLICE_S = 0.5

    def _blocking_loop(self, submit, block: bool,
                       timeout: Optional[float]):
        import time
        if not block:
            return ray_tpu.get(submit(0.0))
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            remaining = None if deadline is None \
                else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                # blocking-queue emulation: ONE server-parked call per
                # wait slice by design # graftlint: disable=RT002
                return ray_tpu.get(submit(0.0))
            wait = self._WAIT_SLICE_S if remaining is None \
                else min(self._WAIT_SLICE_S, remaining)
            result = ray_tpu.get(submit(wait))  # graftlint: disable=RT002
            ok = result[0] if isinstance(result, tuple) else result
            if ok:
                return result

    def put(self, item: Any, block: bool = True,
            timeout: Optional[float] = None) -> None:
        ok = self._blocking_loop(
            lambda t: self._actor.put.remote(item, timeout=t),
            block, timeout)
        if not ok:
            raise Full("queue full")

    def put_nowait(self, item: Any) -> None:
        self.put(item, block=False)

    def get(self, block: bool = True,
            timeout: Optional[float] = None) -> Any:
        ok, item = self._blocking_loop(
            lambda t: self._actor.get.remote(timeout=t), block, timeout)
        if not ok:
            raise Empty("queue empty")
        return item

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def put_batch(self, items: List[Any],
                  timeout: Optional[float] = None) -> None:
        items = list(items)
        if self.maxsize > 0 and len(items) > self.maxsize:
            raise Full(f"batch of {len(items)} can never fit "
                       f"maxsize={self.maxsize}")
        ok = self._blocking_loop(
            lambda t: self._actor.put_batch.remote(items, timeout=t),
            True, timeout)
        if not ok:
            raise Full("queue full")

    def get_batch(self, max_items: int) -> List[Any]:
        return ray_tpu.get(self._actor.get_batch.remote(max_items))

    def qsize(self) -> int:
        return ray_tpu.get(self._actor.qsize.remote())

    def empty(self) -> bool:
        return self.qsize() == 0

    def full(self) -> bool:
        return self.maxsize > 0 and self.qsize() >= self.maxsize

    def shutdown(self) -> None:
        try:
            ray_tpu.kill(self._actor)
        except Exception:  # noqa: BLE001 - queue actor already dead
            pass


def _rebuild_queue(maxsize: int, actor: Any) -> Queue:
    return Queue(maxsize, _actor=actor)
