"""Always-on JAX recompile/transfer sentinel.

Runtime half of the graftlint XLA hot-path pairing (lint/jaxrules.py is
the static half): the lint rules catch the hazards visible in source —
recompile-prone call shapes (RT020), hidden device→host syncs (RT021),
donation misuse (RT022) — and this module catches the ones only the
live process can see, exporting them through the per-process metrics
registry so they ride the cluster harvest onto /metrics and the
watchdog's `jit_recompile_storm` / `unexpected_host_transfer` probes.

Two signals:

  - **compiles** — `jax.monitoring`'s backend-compile duration event
    fires exactly once per real XLA compilation (silent on cache-warm
    dispatches), so counting it per step-region label splits clean
    warmup (`kind="first"`) from the steady-state recompiles that mean
    a shape/static-arg hazard slipped through (`kind="recompile"`):
        ray_tpu_jit_compiles_total{fn=<region>, kind=first|recompile}
  - **host transfers** — the Python-level forcing points on jax arrays
    (`.item()`, `__array__`/np coercion, `__float__`/`__int__`/
    `__bool__`) and `jax.device_get` are patched to account the bytes
    they pull across, tagged by step region:
        ray_tpu_host_transfer_bytes_total{region=<region>}
    Inside a region each forcing point also records a flight-recorder
    span (`host_sync.<via>`) whose duration is the actual blocked wall
    time, so `tools/perf_report.py` can attribute step time stalled on
    syncs. As an escalation, RAY_TPU_JAX_SENTINEL_GUARD=log|disallow
    additionally applies jax's device→host transfer guard for the
    region scope — "log" names every transfer source C++-side,
    "disallow" turns hidden syncs into hard errors at the offending
    line. Off by default: the guard logs the *sanctioned* forcing
    points too, and one warning per update is operator spam.

Scoping: training loops wrap their step in `step_region(name)` —
Learner.update, IMPALA's learner loop, and the sharded train_step
factory already do. Transfers outside any region account under
region="untracked" and are never judged by the watchdog; transfers
INSIDE a region are presumed-bad (the lint rules enforce that hot
paths sync at one sanctioned forcing point) and alert once their
per-harvest delta crosses `Config.watchdog_host_transfer_bytes`.

Off switch: RAY_TPU_JAX_SENTINEL=0 makes install() refuse and
step_region() return a shared no-op — nothing is patched, no listener
registered, call sites pay one flag check. Installation is lazy and
idempotent; importing this module never imports jax.
"""

from __future__ import annotations

import os
import threading
from time import perf_counter
from typing import Any, Dict, Optional

COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

SNAPSHOT_KEY = "jax_sentinel"

_lock = threading.Lock()
_tls = threading.local()

_installed = False
_listener_registered = False

# region label -> lifetime compile count (splits first vs recompile)
_compiles: Dict[str, int] = {}

_compile_counter: Any = None
_xfer_counter: Any = None

_orig: Dict[str, Any] = {}


def enabled() -> bool:
    return os.environ.get("RAY_TPU_JAX_SENTINEL", "1").lower() not in (
        "0", "false", "no", "off")


def installed() -> bool:
    return _installed


def current_region() -> Optional[str]:
    stack = getattr(_tls, "regions", None)
    return stack[-1] if stack else None


# ---------------------------------------------------------------------
# Accounting funnel
# ---------------------------------------------------------------------


def _account(nbytes: int, via: str, t0: float) -> None:
    """One observed device→host transfer: count the bytes against the
    current step region, and inside a region also record the blocked
    wall time as a host_sync span for perf_report's stall buckets."""
    if not _installed:
        return
    try:
        region = current_region()
        _xfer_counter.inc(float(max(0, nbytes)),
                          tags={"region": region or "untracked"})
        if region is not None:
            from ray_tpu._private import spans as _spans
            _spans.end(f"host_sync.{via}", t0,
                       bytes=int(nbytes), region=region)
    except Exception:  # noqa: BLE001 - accounting must never break the
        pass           # transfer it observes


def _in_xfer() -> bool:
    return getattr(_tls, "in_xfer", False)


def _on_event_duration(event: str, duration: float,
                       **_kw: Any) -> None:
    """jax.monitoring listener: fires once per real backend compile
    (warm cache hits are silent), on the dispatching thread — so the
    thread-local region label attributes it. The listener stays
    registered for the process lifetime; _installed gates its body."""
    if event != COMPILE_EVENT or not _installed:
        return
    try:
        fn = current_region() or "untracked"
        with _lock:
            n = _compiles.get(fn, 0)
            _compiles[fn] = n + 1
        _compile_counter.inc(
            1.0, tags={"fn": fn,
                       "kind": "first" if n == 0 else "recompile"})
        # goodput: the event fires synchronously on the jit-calling
        # thread with the compile's wall duration — re-attribute it out
        # of whatever ledger bucket is open there (typically
        # productive_step) into `compile`
        from ray_tpu._private import goodput
        goodput.charge("compile", float(duration))
    except Exception:  # noqa: BLE001 - telemetry is best-effort
        pass


def _snapshot_extra() -> Dict[str, Any]:
    """Rides every metrics harvest: which regions this process has
    compiled under (the watchdog's storm probe names them; operators
    grep it from `ray_tpu metrics dump`)."""
    with _lock:
        return {"installed": _installed, "compiles": dict(_compiles)}


# ---------------------------------------------------------------------
# Install / uninstall
# ---------------------------------------------------------------------


def install() -> bool:
    """Idempotent lazy install: metrics, compile listener, and the
    ArrayImpl/device_get transfer funnel. Returns False (and patches
    nothing) when RAY_TPU_JAX_SENTINEL=0 or jax is unavailable."""
    global _installed, _listener_registered
    global _compile_counter, _xfer_counter
    if _installed:
        return True
    if not enabled():
        return False
    with _lock:
        if _installed:
            return True
        try:
            import jax
            import jax.monitoring
            from jaxlib.xla_extension import ArrayImpl
        except Exception:  # noqa: BLE001 - no jax in this process
            return False
        from ray_tpu._private import metrics_plane
        from ray_tpu.util.metrics import Counter, get_or_create
        _compile_counter = get_or_create(
            Counter, "ray_tpu_jit_compiles_total",
            description="XLA backend compiles by step-region label; "
                        "kind=first is warmup, kind=recompile means a "
                        "recompile hazard (see graftlint RT020)",
            tag_keys=("fn", "kind"))
        _xfer_counter = get_or_create(
            Counter, "ray_tpu_host_transfer_bytes_total",
            description="device->host bytes forced through jax array "
                        "coercions and jax.device_get, by step region "
                        "(region=untracked outside step_region scopes; "
                        "see graftlint RT021)",
            tag_keys=("region",))
        if not _listener_registered:
            jax.monitoring.register_event_duration_secs_listener(
                _on_event_duration)
            _listener_registered = True
        metrics_plane.register_snapshot_extra(
            SNAPSHOT_KEY, _snapshot_extra)

        # -- transfer funnel: ArrayImpl coercions + jax.device_get.
        # block_until_ready and the buffer protocol live in C++ and
        # can't be wrapped from Python; every *coercing* forcing point
        # goes through one of these.
        _orig["item"] = ArrayImpl.item
        _orig["__array__"] = ArrayImpl.__array__
        _orig["__float__"] = ArrayImpl.__float__
        _orig["__int__"] = ArrayImpl.__int__
        _orig["__bool__"] = ArrayImpl.__bool__
        _orig["device_get"] = jax.device_get

        def item(self, *a):
            t0 = perf_counter()
            out = _orig["item"](self, *a)
            if not _in_xfer():
                _account(getattr(self, "nbytes", 0), "item", t0)
            return out

        def __array__(self, *a, **kw):
            t0 = perf_counter()
            out = _orig["__array__"](self, *a, **kw)
            if not _in_xfer():
                _account(getattr(self, "nbytes", 0), "asarray", t0)
            return out

        def _scalar(name: str):
            orig = _orig[name]

            def coerce(self):
                t0 = perf_counter()
                out = orig(self)
                if not _in_xfer():
                    _account(getattr(self, "nbytes", 0),
                             name.strip("_"), t0)
                return out
            coerce.__name__ = name
            return coerce

        def device_get(x):
            # reentrancy guard: device_get coerces each leaf through
            # __array__ — one accounted transfer, not two
            if _in_xfer():
                return _orig["device_get"](x)
            _tls.in_xfer = True
            t0 = perf_counter()
            try:
                out = _orig["device_get"](x)
            finally:
                _tls.in_xfer = False
            try:
                total = sum(getattr(leaf, "nbytes", 0)
                            for leaf in jax.tree_util.tree_leaves(x))
            except Exception:  # noqa: BLE001 - odd pytree
                total = 0
            _account(total, "device_get", t0)
            return out

        ArrayImpl.item = item
        ArrayImpl.__array__ = __array__
        ArrayImpl.__float__ = _scalar("__float__")
        ArrayImpl.__int__ = _scalar("__int__")
        ArrayImpl.__bool__ = _scalar("__bool__")
        jax.device_get = device_get
        _installed = True
        return True


def uninstall() -> None:
    """Restore the patched forcing points (tests). The monitoring
    listener stays registered — _installed gates its body — so a later
    install() never double-registers."""
    global _installed
    with _lock:
        if not _installed:
            return
        import jax
        from jaxlib.xla_extension import ArrayImpl
        from ray_tpu._private import metrics_plane
        ArrayImpl.item = _orig["item"]
        ArrayImpl.__array__ = _orig["__array__"]
        ArrayImpl.__float__ = _orig["__float__"]
        ArrayImpl.__int__ = _orig["__int__"]
        ArrayImpl.__bool__ = _orig["__bool__"]
        jax.device_get = _orig["device_get"]
        metrics_plane.unregister_snapshot_extra(SNAPSHOT_KEY)
        _installed = False


# ---------------------------------------------------------------------
# Step regions
# ---------------------------------------------------------------------


class _NoopRegion:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return None


NOOP = _NoopRegion()


def _guard_mode() -> Optional[str]:
    mode = os.environ.get("RAY_TPU_JAX_SENTINEL_GUARD", "").lower()
    return mode if mode in ("log", "disallow") else None


class _StepRegion:
    """Labels compiles/transfers on this thread with `name`; with
    RAY_TPU_JAX_SENTINEL_GUARD set, also applies jax's device→host
    transfer guard for the scope. Regions nest; the innermost label
    wins (a learner.update inside an IMPALA learner.step attributes
    to learner.update)."""

    __slots__ = ("name", "_tg")

    def __init__(self, name: str):
        self.name = name
        self._tg = None

    def __enter__(self):
        stack = getattr(_tls, "regions", None)
        if stack is None:
            stack = _tls.regions = []
        stack.append(self.name)
        mode = _guard_mode()
        if mode is not None:
            try:
                import jax
                self._tg = jax.transfer_guard_device_to_host(mode)
                self._tg.__enter__()
            except Exception:  # noqa: BLE001 - the guard is advisory:
                # a jax too old for per-direction guards still gets
                # the Python-side accounting, just not the XLA log
                self._tg = None
        return self

    def __exit__(self, *exc):
        if self._tg is not None:
            try:
                self._tg.__exit__(*exc if exc else (None, None, None))
            except Exception:  # noqa: BLE001 - a failed guard restore
                pass           # must not mask the region body's result
        stack = getattr(_tls, "regions", None)
        if stack:
            stack.pop()
        return None


def step_region(name: str):
    """Context manager marking a hot training-step scope. First use
    installs the sentinel (lazy); with RAY_TPU_JAX_SENTINEL=0 this is
    a shared no-op and nothing is ever patched."""
    if not install():
        return NOOP
    return _StepRegion(name)
