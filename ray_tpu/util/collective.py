"""Host-RAM collective communication between actors/processes.

reference parity: python/ray/util/collective/collective.py:120-651 —
init_collective_group / allreduce / allgather / reducescatter /
broadcast / reduce / barrier / send / recv over NCCL (GPU) or Gloo
(CPU) groups, with rendezvous through a named store actor
(collective_group/nccl_collective_group.py:28 Rendezvous).

TPU-native split (SURVEY.md §5.8): device arrays NEVER use this — they
live in HBM and reduce over ICI via XLA collectives inside jit. This
module is the HOST plane: numpy weight broadcast to sampler actors,
checkpoint resharding, metric reduction. Ranks rendezvous at a named
coordinator actor; every rank must issue the same collective ops in the
same order (standard collective-group contract).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

_GROUP_STATE: Dict[str, "_LocalGroup"] = {}


class _LocalGroup:
    def __init__(self, coordinator: Any, world_size: int, rank: int,
                 group_name: str):
        self.coordinator = coordinator
        self.world_size = world_size
        self.rank = rank
        self.group_name = group_name
        self.seq = 0
        # p2p rounds are tracked per (src, dst) pair, NOT on the shared
        # collective sequence: a send/recv only advances the two
        # participants, and mixing it into the collective counter would
        # desynchronize round ids for everyone else.
        self.p2p_seq: Dict[Any, int] = {}

    def next_round(self) -> int:
        self.seq += 1
        return self.seq

    def next_p2p_round(self, src: int, dst: int) -> int:
        key = (src, dst)
        self.p2p_seq[key] = self.p2p_seq.get(key, 0) + 1
        return self.p2p_seq[key]


class CollectiveCoordinator:
    """Named rendezvous + reduction actor (reference Rendezvous /
    the named store actor). Runs with max_concurrency >= world_size so
    every rank's blocking contribute() can park concurrently."""

    def __init__(self, world_size: int):
        self.world_size = world_size
        self._lock = threading.Lock()
        # (round, op) -> {"data": {rank: array}, "event": Event, "result": _}
        self._rounds: Dict[Any, Dict[str, Any]] = {}
        self._mailbox: Dict[Any, Any] = {}   # (round, dst) -> payload
        self._mailbox_cv = threading.Condition(self._lock)

    def ping(self) -> str:
        return "pong"

    def contribute(self, round_id: int, op: str, rank: int,
                   data: Any, timeout: float = 300.0) -> Any:
        key = (round_id, op)
        with self._lock:
            st = self._rounds.get(key)
            if st is None:
                st = {"data": {}, "event": threading.Event(),
                      "result": None}
                self._rounds[key] = st
            st["data"][rank] = data
            complete = len(st["data"]) == self.world_size
            if complete:
                st["result"] = self._combine(op, st["data"])
                st["event"].set()
        if not st["event"].wait(timeout=timeout):
            raise TimeoutError(
                f"collective {op} round {round_id}: only "
                f"{len(st['data'])}/{self.world_size} ranks arrived")
        result = st["result"]
        with self._lock:
            # last reader cleans up
            st.setdefault("readers", 0)
            st["readers"] += 1
            if st["readers"] == self.world_size:
                self._rounds.pop(key, None)
        if op == "allgather":
            return result
        if op in ("sum", "mean", "max", "min", "barrier"):
            return result
        if op == "reducescatter":
            return result[rank]
        if op == "broadcast":
            return result
        raise ValueError(f"unknown op {op}")

    def _combine(self, op: str, data: Dict[int, Any]) -> Any:
        ordered = [data[r] for r in sorted(data)]
        if op == "barrier":
            return True
        if op == "allgather":
            return ordered
        if op == "broadcast":
            # exactly one rank supplied a non-None payload (the src)
            payload = [d for d in ordered if d is not None]
            return payload[0]
        arrays = [np.asarray(d) for d in ordered]
        if op == "sum":
            return sum(arrays[1:], arrays[0].copy())
        if op == "mean":
            return sum(arrays[1:], arrays[0].copy()) / len(arrays)
        if op == "max":
            return np.maximum.reduce(arrays)
        if op == "min":
            return np.minimum.reduce(arrays)
        if op == "reducescatter":
            total = sum(arrays[1:], arrays[0].copy())
            return np.array_split(total, self.world_size)
        raise ValueError(f"unknown op {op}")

    # -- point to point ------------------------------------------------

    def put_p2p(self, tag: Any, payload: Any) -> None:
        with self._mailbox_cv:
            self._mailbox[tag] = payload
            self._mailbox_cv.notify_all()

    def get_p2p(self, tag: Any, timeout: float = 300.0) -> Any:
        deadline = time.monotonic() + timeout
        with self._mailbox_cv:
            while tag not in self._mailbox:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(f"recv timed out for {tag}")
                self._mailbox_cv.wait(timeout=min(remaining, 1.0))
            return self._mailbox.pop(tag)


def _coordinator_name(group_name: str) -> str:
    return f"COLLECTIVE_GROUP::{group_name}"


def init_collective_group(world_size: int, rank: int, *,
                          group_name: str = "default") -> None:
    """Join a collective group (reference collective.py:120). Call once
    per participating process/actor; rank 0's call may create the
    coordinator, every call rendezvouses on the same named actor."""
    import ray_tpu

    if group_name in _GROUP_STATE:
        raise ValueError(f"group {group_name!r} already initialized here")
    name = _coordinator_name(group_name)
    coordinator = None
    try:
        coordinator = ray_tpu.get_actor(name, namespace="collective")
    except Exception:  # noqa: BLE001 - first joiner creates it
        pass
    if coordinator is None:
        cls = ray_tpu.remote(CollectiveCoordinator)
        try:
            coordinator = cls.options(
                name=name, namespace="collective", num_cpus=0,
                max_concurrency=max(4, world_size * 2)).remote(world_size)
        except ValueError:  # raced another creator
            coordinator = ray_tpu.get_actor(name, namespace="collective")
    ray_tpu.get(coordinator.ping.remote(), timeout=120)
    _GROUP_STATE[group_name] = _LocalGroup(coordinator, world_size, rank,
                                           group_name)


def destroy_collective_group(group_name: str = "default") -> None:
    state = _GROUP_STATE.pop(group_name, None)
    if state is not None and state.rank == 0:
        import ray_tpu
        try:
            ray_tpu.kill(state.coordinator)
        except Exception:  # noqa: BLE001 - coordinator already dead
            pass


def _group(group_name: str) -> _LocalGroup:
    if group_name not in _GROUP_STATE:
        raise RuntimeError(
            f"collective group {group_name!r} not initialized in this "
            "process; call init_collective_group first")
    return _GROUP_STATE[group_name]


def _collective(op: str, data: Any, group_name: str) -> Any:
    import ray_tpu
    g = _group(group_name)
    round_id = g.next_round()
    return ray_tpu.get(
        g.coordinator.contribute.remote(round_id, op, g.rank, data),
        timeout=600)


def allreduce(array: np.ndarray, *, op: str = "sum",
              group_name: str = "default") -> np.ndarray:
    """reference collective.py:258."""
    assert op in ("sum", "mean", "max", "min")
    return _collective(op, np.asarray(array), group_name)


def allgather(array: np.ndarray, *,
              group_name: str = "default") -> List[np.ndarray]:
    return _collective("allgather", np.asarray(array), group_name)


def reducescatter(array: np.ndarray, *,
                  group_name: str = "default") -> np.ndarray:
    """reference collective.py:472: sum-reduce then return this rank's
    1/world chunk (split along axis 0)."""
    return _collective("reducescatter", np.asarray(array), group_name)


def broadcast(array: Optional[np.ndarray], src_rank: int = 0, *,
              group_name: str = "default") -> np.ndarray:
    g = _group(group_name)
    payload = np.asarray(array) if g.rank == src_rank else None
    return _collective("broadcast", payload, group_name)


def reduce(array: np.ndarray, dst_rank: int = 0, *, op: str = "sum",
           group_name: str = "default") -> Optional[np.ndarray]:
    """Reduction delivered to dst only (others get None)."""
    g = _group(group_name)
    out = _collective(op, np.asarray(array), group_name)
    return out if g.rank == dst_rank else None


def barrier(group_name: str = "default") -> None:
    _collective("barrier", None, group_name)


def send(array: np.ndarray, dst_rank: int, *,
         group_name: str = "default") -> None:
    """reference collective.py:531. Pair each send with exactly one recv
    on the destination; rounds count per (src, dst) pair."""
    import ray_tpu
    g = _group(group_name)
    round_id = g.next_p2p_round(g.rank, dst_rank)
    ray_tpu.get(g.coordinator.put_p2p.remote(
        (round_id, g.rank, dst_rank), np.asarray(array)), timeout=600)


def recv(src_rank: int, *, group_name: str = "default") -> np.ndarray:
    import ray_tpu
    g = _group(group_name)
    round_id = g.next_p2p_round(src_rank, g.rank)
    return ray_tpu.get(g.coordinator.get_p2p.remote(
        (round_id, src_rank, g.rank)), timeout=600)
