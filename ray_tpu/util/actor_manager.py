"""Fault-tolerant actor pool with async in-flight requests + health probing.

reference parity: python/ray/rllib/utils/actor_manager.py:193
(FaultTolerantActorManager) — the generic async actor-pool used by RLlib's
WorkerSet and LearnerGroup: fan out calls, tolerate actor failures by
marking actors unhealthy, keep sampling from the healthy subset, and
periodically probe/restore the unhealthy ones (probe_unhealthy_actors,
actor_manager.py:781).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import ray_tpu
from ray_tpu import exceptions as exc


@dataclass
class CallResult:
    actor_id: int              # manager-local index, stable across restarts
    ok: bool
    value: Any = None          # result when ok, exception when not
    tag: Any = None


def _is_actor_failure(e: BaseException) -> bool:
    """Only actor-death-shaped errors demote an actor to unhealthy; an
    application-level exception (a bad input raising ValueError) must not
    silently shrink the pool (reference actor_manager.py marks unhealthy
    only on RayActorError)."""
    return isinstance(e, (exc.RayActorError, exc.WorkerCrashedError,
                          exc.OwnerDiedError, exc.RaySystemError))


class FaultTolerantActorManager:
    """Manages a set of actor handles with per-actor health state.

    `foreach_actor` fans a call out to healthy actors and returns
    `CallResult`s instead of raising: an actor failure marks it unhealthy
    and yields ok=False for that actor only. `foreach_actor_async` +
    `fetch_ready_async_reqs` give the IMPALA-style async pipeline with a
    bounded number of in-flight calls per actor.
    """

    def __init__(self, actors: Optional[Sequence[Any]] = None, *,
                 max_remote_requests_in_flight_per_actor: int = 2,
                 health_probe_method: str = "ping"):
        self._lock = threading.Lock()
        self._actors: Dict[int, Any] = {}
        self._healthy: Dict[int, bool] = {}
        self._next_id = 0
        self._max_in_flight = max_remote_requests_in_flight_per_actor
        self._health_probe_method = health_probe_method
        # in-flight: ref -> (actor_id, tag)
        self._in_flight: Dict[Any, Tuple[int, Any]] = {}
        for a in (actors or []):
            self.add_actor(a)

    # -- membership --------------------------------------------------------

    def add_actor(self, actor: Any) -> int:
        with self._lock:
            aid = self._next_id
            self._next_id += 1
            self._actors[aid] = actor
            self._healthy[aid] = True
            return aid

    def remove_actor(self, actor_id: int) -> None:
        with self._lock:
            self._actors.pop(actor_id, None)
            self._healthy.pop(actor_id, None)
            self._in_flight = {r: (i, t) for r, (i, t)
                               in self._in_flight.items() if i != actor_id}

    def actors(self) -> Dict[int, Any]:
        with self._lock:
            return dict(self._actors)

    def num_actors(self) -> int:
        with self._lock:
            return len(self._actors)

    def num_healthy_actors(self) -> int:
        with self._lock:
            return sum(1 for h in self._healthy.values() if h)

    def healthy_actor_ids(self) -> List[int]:
        with self._lock:
            return [i for i, h in self._healthy.items() if h]

    def is_actor_healthy(self, actor_id: int) -> bool:
        with self._lock:
            return self._healthy.get(actor_id, False)

    def set_actor_state(self, actor_id: int, healthy: bool) -> None:
        with self._lock:
            if actor_id in self._healthy:
                self._healthy[actor_id] = healthy

    # -- sync fan-out ------------------------------------------------------

    def _call(self, actor: Any, fn: Any) -> Any:
        """Submit fn to one actor; fn is a method name (str, called with no
        args), a (method, args, kwargs) tuple, or a callable applied via the
        actor's `apply` method if it has one."""
        if isinstance(fn, str):
            return getattr(actor, fn).remote()
        if isinstance(fn, tuple):
            method, args, kwargs = fn
            return getattr(actor, method).remote(*args, **(kwargs or {}))
        return actor.apply.remote(fn)

    def foreach_actor(self, fn: Any, *, healthy_only: bool = True,
                      remote_actor_ids: Optional[Sequence[int]] = None,
                      timeout_seconds: Optional[float] = 60.0
                      ) -> List[CallResult]:
        with self._lock:
            targets = [(i, a) for i, a in self._actors.items()
                       if (not healthy_only or self._healthy.get(i))
                       and (remote_actor_ids is None or i in remote_actor_ids)]
        refs = []
        for i, a in targets:
            try:
                refs.append((i, self._call(a, fn)))
            except Exception as e:  # noqa: BLE001 - submission itself failed
                if _is_actor_failure(e):
                    self.set_actor_state(i, False)
                refs.append((i, e))
        # Resolve the whole fan-out in parallel: one wait bounds it by
        # timeout_seconds TOTAL instead of timeout per actor (found by
        # graftlint RT002), while the per-ref gets below keep per-actor
        # failure isolation.
        real = [r for _, r in refs if not isinstance(r, Exception)]
        ready_set = set()
        if real:
            ready, _ = ray_tpu.wait(real, num_returns=len(real),
                                    timeout=timeout_seconds)
            ready_set = set(ready)
        out: List[CallResult] = []
        for i, ref in refs:
            if isinstance(ref, Exception):
                out.append(CallResult(i, False, ref))
                continue
            if ref not in ready_set:
                out.append(CallResult(i, False, exc.GetTimeoutError(
                    f"actor {i} did not answer within "
                    f"{timeout_seconds}s")))
                continue
            try:
                # ready refs resolve instantly # graftlint: disable=RT002
                out.append(CallResult(i, True, ray_tpu.get(ref)))
            except Exception as e:  # noqa: BLE001
                if _is_actor_failure(e):
                    self.set_actor_state(i, False)
                out.append(CallResult(i, False, e))
        return out

    # -- async pipeline ----------------------------------------------------

    def foreach_actor_async(self, fn: Any, *, tag: Any = None,
                            healthy_only: bool = True) -> int:
        """Fire fn at every (healthy) actor with in-flight budget left;
        returns the number of calls actually submitted."""
        submitted = 0
        with self._lock:
            targets = [(i, a) for i, a in self._actors.items()
                       if not healthy_only or self._healthy.get(i)]
            in_flight_by_actor: Dict[int, int] = {}
            for _, (i, _t) in self._in_flight.items():
                in_flight_by_actor[i] = in_flight_by_actor.get(i, 0) + 1
        for i, a in targets:
            if in_flight_by_actor.get(i, 0) >= self._max_in_flight:
                continue
            try:
                ref = self._call(a, fn)
            except Exception as e:  # noqa: BLE001
                if _is_actor_failure(e):
                    self.set_actor_state(i, False)
                continue
            with self._lock:
                self._in_flight[ref] = (i, tag)
            submitted += 1
        return submitted

    def fetch_ready_async_reqs(self, *, timeout_seconds: float = 0.1
                               ) -> List[CallResult]:
        with self._lock:
            refs = list(self._in_flight.keys())
        if not refs:
            return []
        ready, _ = ray_tpu.wait(refs, num_returns=len(refs),
                                timeout=timeout_seconds)
        claimed: List[Tuple[Any, int, Any]] = []
        for ref in ready:
            with self._lock:
                meta = self._in_flight.pop(ref, None)
            if meta is not None:
                claimed.append((ref, meta[0], meta[1]))
        if not claimed:
            return []
        # One batched get for the whole ready set (a single store_wait
        # RPC for local results) — the per-ref path below only runs when
        # some result is an error, to keep per-actor failure isolation.
        try:
            values = ray_tpu.get([ref for ref, _, _ in claimed])
            return [CallResult(i, True, v, tag)
                    for (_, i, tag), v in zip(claimed, values)]
        except Exception:  # noqa: BLE001 - isolate the failing actor(s)
            pass
        out: List[CallResult] = []
        for ref, i, tag in claimed:
            try:
                # ready refs resolve instantly # graftlint: disable=RT002
                out.append(CallResult(i, True, ray_tpu.get(ref), tag))
            except Exception as e:  # noqa: BLE001
                if _is_actor_failure(e):
                    self.set_actor_state(i, False)
                out.append(CallResult(i, False, e, tag))
        return out

    def num_in_flight_async_reqs(self) -> int:
        with self._lock:
            return len(self._in_flight)

    # -- health ------------------------------------------------------------

    def probe_unhealthy_actors(self, *, timeout_seconds: float = 10.0,
                               mark_healthy: bool = True) -> List[int]:
        """Probe unhealthy actors; return ids of those that responded (a
        restarted actor answering its probe is marked healthy again)."""
        with self._lock:
            unhealthy = [(i, a) for i, a in self._actors.items()
                         if not self._healthy.get(i)]
        # Probe every unhealthy actor concurrently: submitting + getting
        # one probe at a time cost timeout_seconds per dead actor (found
        # by graftlint RT002).
        probes: List[Tuple[int, Any]] = []
        for i, a in unhealthy:
            try:
                probes.append(
                    (i, getattr(a, self._health_probe_method).remote()))
            except Exception:  # noqa: BLE001 - still dead
                continue
        refs = [r for _, r in probes]
        ready_set: set = set()
        if refs:
            ready, _ = ray_tpu.wait(refs, num_returns=len(refs),
                                    timeout=timeout_seconds)
            ready_set = set(ready)
        restored: List[int] = []
        for i, ref in probes:
            if ref not in ready_set:
                continue
            try:
                # ready refs resolve instantly # graftlint: disable=RT002
                ray_tpu.get(ref)
            except Exception:  # noqa: BLE001 - probe answered with error
                continue
            restored.append(i)
            if mark_healthy:
                self.set_actor_state(i, True)
        return restored

    def clear(self) -> None:
        with self._lock:
            actors = list(self._actors.values())
            self._actors.clear()
            self._healthy.clear()
            self._in_flight.clear()
        for a in actors:
            try:
                ray_tpu.kill(a)
            except Exception:  # noqa: BLE001 - actor already dead
                pass
