"""Application metrics: Counter / Gauge / Histogram.

reference parity: python/ray/util/metrics.py (Counter/Gauge/Histogram over
the OpenCensus-based native registry, src/ray/stats/metric.h). Here metrics
live in a per-process registry; `collect()` snapshots them, and node-level
aggregation rides the existing state API instead of a Prometheus agent.
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Optional, Sequence, Tuple

_REGISTRY: Dict[str, "Metric"] = {}
_REGISTRY_LOCK = threading.Lock()


class Metric:
    """Base: named metric with optional tag keys; values kept per tag-set."""

    kind = "metric"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Optional[Sequence[str]] = None):
        if not name:
            raise ValueError("metric name must be non-empty")
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys or ())
        self._default_tags: Dict[str, str] = {}
        self._lock = threading.Lock()
        self._values: Dict[Tuple[Tuple[str, str], ...], float] = {}
        with _REGISTRY_LOCK:
            if name in _REGISTRY:
                # Silent replacement would orphan the earlier instance:
                # increments through it would vanish from collect().
                raise ValueError(
                    f"metric {name!r} already registered in this process")
            _REGISTRY[name] = self

    def set_default_tags(self, tags: Dict[str, str]) -> "Metric":
        self._default_tags = dict(tags)
        return self

    def _key(self, tags: Optional[Dict[str, str]]
             ) -> Tuple[Tuple[str, str], ...]:
        merged = dict(self._default_tags)
        if tags:
            merged.update(tags)
        extra = set(merged) - set(self.tag_keys)
        if extra:
            raise ValueError(f"undeclared tag keys {sorted(extra)} for "
                             f"metric {self.name} (declared: {self.tag_keys})")
        return tuple(sorted(merged.items()))

    def snapshot(self) -> Dict:
        with self._lock:
            return {"name": self.name, "kind": self.kind,
                    "description": self.description,
                    "values": {k: v for k, v in self._values.items()}}


class Counter(Metric):
    kind = "counter"

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None) -> None:
        if value < 0:
            raise ValueError("counters only increase")
        k = self._key(tags)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + value


class Gauge(Metric):
    kind = "gauge"

    def set(self, value: float, tags: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._values[self._key(tags)] = float(value)

    def reset(self) -> None:
        """Drop every tagged series. For gauges whose tag population is
        dynamic (e.g. per-gang heartbeat ages): a rebuild-per-sample
        exporter resets then re-sets the live series so series for
        departed members stop exporting stale values forever."""
        with self._lock:
            self._values.clear()


class Histogram(Metric):
    kind = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[Sequence[float]] = None,
                 tag_keys: Optional[Sequence[str]] = None):
        super().__init__(name, description, tag_keys)
        self.boundaries = sorted(boundaries or
                                 [0.001, 0.01, 0.1, 1, 10, 100, 1000])
        self._buckets: Dict[Tuple, List[int]] = {}
        self._sums: Dict[Tuple, float] = {}
        self._counts: Dict[Tuple, int] = {}

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None) -> None:
        k = self._key(tags)
        with self._lock:
            buckets = self._buckets.setdefault(
                k, [0] * (len(self.boundaries) + 1))
            buckets[bisect.bisect_left(self.boundaries, value)] += 1
            self._sums[k] = self._sums.get(k, 0.0) + value
            self._counts[k] = self._counts.get(k, 0) + 1

    def snapshot(self) -> Dict:
        with self._lock:
            return {"name": self.name, "kind": self.kind,
                    "description": self.description,
                    "boundaries": list(self.boundaries),
                    "buckets": {k: list(v) for k, v in self._buckets.items()},
                    "sum": dict(self._sums), "count": dict(self._counts)}


def get_or_create(cls, name: str, **kwargs) -> "Metric":
    """Idempotent registration for library-internal metrics (the
    transport plane's counters are created on first use from whichever
    hot path runs first): returns the existing instance when `name` is
    already registered — raising TypeError if its kind differs — and
    constructs it otherwise. User code should construct metrics directly
    so accidental name collisions still fail loudly."""
    with _REGISTRY_LOCK:
        existing = _REGISTRY.get(name)
    if existing is not None:
        if not isinstance(existing, cls):
            raise TypeError(
                f"metric {name!r} already registered as "
                f"{type(existing).__name__}, not {cls.__name__}")
        return existing
    try:
        return cls(name, **kwargs)
    except ValueError:
        # a lost registration race leaves the winner in the registry;
        # any other ValueError (bad kwargs) must propagate untouched
        with _REGISTRY_LOCK:
            winner = _REGISTRY.get(name)
        if winner is None:
            raise
        return winner


def collect() -> List[Dict]:
    """Snapshot every metric registered in this process."""
    with _REGISTRY_LOCK:
        metrics = list(_REGISTRY.values())
    return [m.snapshot() for m in metrics]


def _to_wire(snap: Dict) -> Dict:
    """Convert one snapshot() record to the JSON-safe wire format the
    cluster metrics plane ships over RPC: tag tuples become plain dicts
    so snapshots survive json.dumps on the dashboard routes."""
    out = {"name": snap["name"], "kind": snap["kind"],
           "description": snap.get("description", "")}
    if snap["kind"] == "histogram":
        out["boundaries"] = list(snap["boundaries"])
        out["series"] = [{"tags": dict(k), "buckets": list(b),
                          "sum": snap["sum"][k], "count": snap["count"][k]}
                         for k, b in snap["buckets"].items()]
    else:
        out["series"] = [{"tags": dict(k), "value": v}
                         for k, v in snap["values"].items()]
    return out


def collect_wire() -> List[Dict]:
    """collect() in wire format (see _to_wire)."""
    return [_to_wire(s) for s in collect()]


def _esc_label(v: str) -> str:
    """Prometheus exposition label escaping (\\ " and newline): one bad
    label value would otherwise abort the entire scrape."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _fmt_tags(key: Tuple[Tuple[str, str], ...]) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{_esc_label(v)}"' for k, v in key)
    return "{" + inner + "}"


def render_prometheus(metrics: List[Dict]) -> str:
    """Prometheus exposition text from wire-format metric snapshots
    (collect_wire()-shaped). The cluster metrics plane concatenates many
    processes' snapshots, each carrying an ``extra_tags`` dict (proc/node
    labels), so HELP/TYPE are emitted once per metric NAME while series
    of the same name from different processes stay adjacent — Prometheus
    rejects exposition with a repeated TYPE line for one metric."""
    by_name: Dict[str, List[Dict]] = {}
    order: List[str] = []
    for m in metrics:
        if m["name"] not in by_name:
            order.append(m["name"])
        by_name.setdefault(m["name"], []).append(m)
    lines: List[str] = []
    for name in order:
        group = by_name[name]
        kind = group[0]["kind"]
        desc = next((g["description"] for g in group
                     if g.get("description")), "")
        if desc:
            desc = str(desc).replace("\n", " ")
            lines.append(f"# HELP {name} {desc}")
        lines.append(f"# TYPE {name} {kind}")

        def bucket_line(tags: Dict[str, str], le: str, cum: int) -> str:
            key = tuple(sorted({**tags, "le": le}.items()))
            return f"{name}_bucket{_fmt_tags(key)} {cum}"

        for m in group:
            if m["kind"] != kind:
                continue  # conflicting registration; first kind wins
            extra = m.get("extra_tags") or {}
            if kind == "histogram":
                for s in m["series"]:
                    tags = {**s["tags"], **extra}
                    base = tuple(sorted(tags.items()))
                    cum = 0
                    for bound, count in zip(m["boundaries"],
                                            s["buckets"]):
                        cum += count
                        lines.append(bucket_line(tags, str(bound), cum))
                    cum += s["buckets"][-1]
                    lines.append(bucket_line(tags, "+Inf", cum))
                    lines.append(f"{name}_sum{_fmt_tags(base)} "
                                 f"{s['sum']}")
                    lines.append(f"{name}_count{_fmt_tags(base)} "
                                 f"{s['count']}")
            else:
                for s in m["series"]:
                    tags = tuple(sorted({**s["tags"], **extra}.items()))
                    lines.append(f"{name}{_fmt_tags(tags)} {s['value']}")
    return "\n".join(lines) + "\n"


def prometheus_text() -> str:
    """This process's metrics in Prometheus exposition format (reference:
    the per-node metrics agent exporting to Prometheus,
    _private/metrics_agent.py + prometheus_exporter.py). The cluster-wide
    equivalent is the dashboard /metrics endpoint, which serves the
    harvested-and-merged registry of every process (_private/
    metrics_plane.py)."""
    return render_prometheus(collect_wire())


def clear() -> None:
    with _REGISTRY_LOCK:
        _REGISTRY.clear()
