"""Scheduling strategies (reference: python/ray/util/scheduling_strategies.py
:15,41,135 — PlacementGroupSchedulingStrategy / NodeAffinitySchedulingStrategy
/ NodeLabelSchedulingStrategy). The dataclasses live in
ray_tpu._private.state so the scheduler can depend on them without a cycle;
this module is the public import path."""

from ray_tpu._private.state import (  # noqa: F401
    DefaultSchedulingStrategy,
    NodeAffinitySchedulingStrategy,
    NodeLabelSchedulingStrategy,
    PlacementGroupSchedulingStrategy,
    SchedulingStrategy,
    SpreadSchedulingStrategy,
)

__all__ = [
    "SchedulingStrategy", "DefaultSchedulingStrategy",
    "SpreadSchedulingStrategy", "NodeAffinitySchedulingStrategy",
    "PlacementGroupSchedulingStrategy", "NodeLabelSchedulingStrategy",
]
