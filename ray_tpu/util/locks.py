"""TracedLock: lockdep-style runtime lock instrumentation.

The static concurrency rules (lint RT014-RT016) see lock *names* in
source; this module is their runtime twin for the lock *objects* those
names become. Every ``TracedLock`` records, always-on:

  - **acquisition-order edges** in a per-process graph: when a thread
    acquires lock B while holding lock A, the edge A->B is recorded
    (first occurrence under a side lock, later ones a racy counter
    bump). A cycle in this graph means two code paths acquire the same
    locks in opposite orders — the classic deadlock-in-waiting that
    only fires under the right interleaving. The metrics watchdog
    walks each process's edge graph every harvest and raises a
    HEALTH_ALERT on the first observed inversion (lockdep semantics:
    the *order* is the bug, no actual deadlock needs to happen).
  - **hold times**: 1-in-8 sampled at release (bucket counts and sums
    scaled back up at export; the hold COUNT stays exact), exported as
    the ``ray_tpu_lock_held_seconds`` histogram per lock name. The
    hold start is stamped on EVERY acquire, so in-progress hold age —
    what the long-hold watchdog probe reads — is always exact.
  - **waiters**: threads blocked in acquire(), exported as the
    ``ray_tpu_lock_waiters`` gauge and shipped with in-progress hold
    age in the harvest digest so the watchdog can flag
    long-hold-with-waiters (a stalled critical section starving a
    queue of threads).

Design constraints mirror the span plane: the uncontended fast path is
a handful of plain attribute/dict operations — no allocation, no
locking, no metrics calls (export happens pull-based at harvest time).
Bookkeeping counters tolerate lost updates under races; the lock
SEMANTICS are exactly the inner ``threading.Lock``/``RLock``'s.

Ownership is *derived*, not stored: each thread's innermost held
traced lock lives in ``_TOPS[thread_ident]`` and locks chain via
``_prev`` (safe: only the exclusive holder writes its own ``_prev``),
so snapshot() reconstructs holder attribution by walking the chains
and the fast path saves two attribute writes. Exits verify the chain
top before restoring it (a method-form ``b.acquire()`` inside a
``with a:`` block leaves ``b`` above ``a``; the splice fallback keeps
``b``'s ownership intact). ``threading.Condition``
works over a TracedLock (it only needs acquire/release/_is_owned);
a Condition.wait() releases the lock through ``release()``, so hold
time correctly ends at the wait and restarts at wakeup.
"""

from __future__ import annotations

import os
import threading
import weakref
from _thread import get_ident as _get_ident
from time import monotonic as _monotonic
from time import perf_counter as _perf
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["TracedLock", "TracedRLock", "snapshot", "digest",
           "find_cycle", "edges", "reset_edges"]

# thread ident -> innermost held TracedLock (chained via ._prev)
_TOPS: Dict[int, Optional["TracedLock"]] = {}
# (outer lock name, inner lock name) -> occurrence count
_EDGES: Dict[Tuple[str, str], int] = {}
_EDGES_LOCK = threading.Lock()
_REGISTRY: "weakref.WeakSet[TracedLock]" = weakref.WeakSet()
_REGISTRY_LOCK = threading.Lock()
_registered_export = False

# ray_tpu_lock_held_seconds boundaries; _slow buckets cover (>1ms) so
# bucket 0 (<=1ms) is holds - sum(_slow)
_BOUNDARIES = [0.001, 0.01, 0.1, 1.0, 10.0]


class TracedLock:
    """Drop-in ``threading.Lock`` with lockdep instrumentation.

    ``name`` keys every export (edges, histogram series, digests);
    instances sharing a name aggregate (e.g. one lock per connection).
    """

    _reentrant = False

    __slots__ = ("name", "_acq", "_rel", "_is_locked", "_t0", "_prev",
                 "_waiters", "_holds", "_hold_total", "_slow",
                 "__weakref__")

    def __init__(self, name: str):
        self.name = name
        inner = self._make_inner()
        self._acq = inner.acquire
        self._rel = inner.release
        self._is_locked = getattr(inner, "locked", None)
        self._t0 = 0.0
        self._prev: Optional["TracedLock"] = None
        self._waiters = 0
        self._holds = 0
        self._hold_total = 0.0   # 1-in-8 sampled sum (x8 at export)
        self._slow = [0, 0, 0, 0, 0]  # 1-in-8 sampled >1ms buckets
        with _REGISTRY_LOCK:
            _REGISTRY.add(self)
        _ensure_export_registered()

    @staticmethod
    def _make_inner():
        return threading.Lock()

    # -- fast paths (the `with` statement) ----------------------------
    # __enter__/__exit__ and acquire/release duplicate the bookkeeping
    # on purpose: the with-path is the hot one and must not pay an
    # extra Python call into acquire().

    def __enter__(self) -> "TracedLock":
        if not self._acq(False):
            self._waiters += 1
            try:
                self._acq()
            finally:
                self._waiters -= 1
        # stamp FIRST: a concurrent harvest that sees locked() must
        # never read the previous hold's start (a stale _t0 would fake
        # an hours-long hold into the long-hold watchdog probe)
        self._t0 = _perf()
        i = _get_ident()
        tops = _TOPS
        top = tops.get(i)
        if top is not None:
            k = (top.name, self.name)
            n = _EDGES.get(k)
            if n is None:
                _record_edge(k)
            else:
                _EDGES[k] = n + 1
        self._prev = top
        tops[i] = self
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        n = self._holds + 1
        self._holds = n
        if not (n & 7):
            dur = _perf() - self._t0
            self._hold_total += dur
            if dur > 0.001:
                s = self._slow
                if dur < 0.01:
                    s[0] += 1
                elif dur < 0.1:
                    s[1] += 1
                elif dur < 1.0:
                    s[2] += 1
                else:
                    s[3 if dur < 10.0 else 4] += 1
        # `with` blocks release LIFO per thread, so this lock is
        # usually the chain top — but a method-form b.acquire() inside
        # the block (still held at exit) would sit above us, and a
        # blind restore would silently unlink it (breaking its
        # Condition._is_owned and holder attribution). One dict read
        # verifies; the splice fallback handles the rare non-top case.
        i = _get_ident()
        tops = _TOPS
        if tops.get(i) is self:
            tops[i] = self._prev
        else:
            _unlink_slow(self, i)
        self._rel()

    # -- method forms (Condition compatibility, direct callers) -------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if not self._acq(False):
            if not blocking:
                return False
            self._waiters += 1
            try:
                if not self._acq(True, timeout):
                    return False
            finally:
                self._waiters -= 1
        self._t0 = _perf()  # before bookkeeping; see __enter__
        i = _get_ident()
        top = _TOPS.get(i)
        if top is not None:
            k = (top.name, self.name)
            n = _EDGES.get(k)
            if n is None:
                _record_edge(k)
            else:
                _EDGES[k] = n + 1
        self._prev = top
        _TOPS[i] = self
        return True

    def release(self) -> None:
        # same bookkeeping as __exit__, but with the safe chain unlink:
        # direct callers (Condition.wait's release_save, hand-written
        # acquire/release pairs) may release out of LIFO order
        n = self._holds + 1
        self._holds = n
        if not (n & 7):
            dur = _perf() - self._t0
            self._hold_total += dur
            if dur > 0.001:
                s = self._slow
                if dur < 0.01:
                    s[0] += 1
                elif dur < 0.1:
                    s[1] += 1
                elif dur < 1.0:
                    s[2] += 1
                else:
                    s[3 if dur < 10.0 else 4] += 1
        _unlink_slow(self, _get_ident())
        self._rel()

    def locked(self) -> bool:
        fn = self._is_locked
        return bool(fn()) if fn is not None else self._held_anywhere()

    # -- introspection ------------------------------------------------

    def _held_anywhere(self) -> bool:
        for top in list(_TOPS.values()):
            node, depth = top, 0
            while node is not None and depth < 64:
                if node is self:
                    return True
                node = node._prev
                depth += 1
        return False

    def _is_owned(self) -> bool:
        """threading.Condition protocol: is THIS thread the holder."""
        node = _TOPS.get(_get_ident())
        depth = 0
        while node is not None and depth < 64:
            if node is self:
                return True
            node = node._prev
            depth += 1
        return False

    def held_seconds(self) -> float:
        """Age of the in-progress hold (0.0 when unheld)."""
        return (_perf() - self._t0) if self.locked() else 0.0

    def __repr__(self) -> str:
        return f"<{type(self).__name__} {self.name!r}>"


class TracedRLock(TracedLock):
    """Reentrant variant. Only the outermost acquire/release pair does
    lockdep bookkeeping; inner levels bump a depth counter the owner
    thread exclusively touches."""

    _reentrant = True

    __slots__ = ("_depth",)

    def __init__(self, name: str):
        super().__init__(name)
        self._depth = 0

    @staticmethod
    def _make_inner():
        return threading.RLock()

    def __enter__(self) -> "TracedRLock":
        if self._acq(False):
            # success = fresh acquire OR reentrant (we already own it)
            if self._depth:
                self._depth += 1
                return self
        else:
            self._waiters += 1
            try:
                self._acq()
            finally:
                self._waiters -= 1
        self._t0 = _perf()
        self._depth = 1
        i = _get_ident()
        top = _TOPS.get(i)
        if top is not None and top is not self:
            k = (top.name, self.name)
            n = _EDGES.get(k)
            if n is None:
                _record_edge(k)
            else:
                _EDGES[k] = n + 1
        self._prev = top
        _TOPS[i] = self
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        d = self._depth - 1
        if d:
            self._depth = d
            self._rel()
            return
        self._depth = 0
        TracedLock.__exit__(self, exc_type, exc, tb)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if self._acq(False):
            if self._depth:
                self._depth += 1
                return True
        else:
            if not blocking:
                return False
            self._waiters += 1
            try:
                if not self._acq(True, timeout):
                    return False
            finally:
                self._waiters -= 1
        self._t0 = _perf()
        self._depth = 1
        i = _get_ident()
        top = _TOPS.get(i)
        if top is not None and top is not self:
            k = (top.name, self.name)
            n = _EDGES.get(k)
            if n is None:
                _record_edge(k)
            else:
                _EDGES[k] = n + 1
        self._prev = top
        _TOPS[i] = self
        return True

    def release(self) -> None:
        d = self._depth - 1
        if d:
            self._depth = d
            self._rel()
            return
        self._depth = 0
        TracedLock.release(self)

    def locked(self) -> bool:
        # RLock has no locked(); acquire(False) would succeed for the
        # owner, so derive from the holder chains instead.
        return self._held_anywhere()

    # Condition-over-RLock protocol: fully release however deep we are,
    # then restore the depth on wakeup.
    def _release_save(self) -> int:
        d = self._depth
        self._depth = 0
        TracedLock.release(self)
        for _ in range(d - 1):
            self._rel()
        return d

    def _acquire_restore(self, d: int) -> None:
        self.acquire()
        for _ in range(d - 1):
            self._acq()
        self._depth = d


def _record_edge(key: Tuple[str, str]) -> None:
    with _EDGES_LOCK:
        if key not in _EDGES:
            _EDGES[key] = 1


def _unlink_slow(lock: TracedLock, ident: int) -> None:
    """Out-of-LIFO release (e.g. Condition.wait on a non-top lock):
    splice the lock out of this thread's holder chain."""
    node = _TOPS.get(ident)
    if node is lock:
        _TOPS[ident] = lock._prev
        return
    depth = 0
    while node is not None and depth < 64:
        nxt = node._prev
        if nxt is lock:
            node._prev = lock._prev
            return
        node = nxt
        depth += 1


# ---------------------------------------------------------------------
# Snapshot / digest / export
# ---------------------------------------------------------------------


def edges() -> Dict[Tuple[str, str], int]:
    """Copy of this process's acquisition-order edge graph."""
    return dict(_EDGES)


def reset_edges() -> None:
    """Test hook: clear the per-process order graph (a stale edge from
    an earlier test would otherwise read as a fresh inversion)."""
    with _EDGES_LOCK:
        _EDGES.clear()


def find_cycle(edge_pairs) -> Optional[List[str]]:
    """First lock-order cycle in the edge set, as the node path
    [a, b, ..., a]; None when the graph is acyclic. Self-edges are
    reentrant re-acquisitions (TracedRLock), not inversions, and are
    ignored. Deterministic: adjacency is scanned in sorted order."""
    adj: Dict[str, List[str]] = {}
    for a, b in edge_pairs:
        if a != b:
            adj.setdefault(a, []).append(b)
    for k in adj:
        adj[k].sort()
    state: Dict[str, int] = {}  # 1 = on stack, 2 = done

    def dfs(node: str, path: List[str]) -> Optional[List[str]]:
        state[node] = 1
        path.append(node)
        for nxt in adj.get(node, ()):
            s = state.get(nxt)
            if s == 1:
                return path[path.index(nxt):] + [nxt]
            if s is None:
                found = dfs(nxt, path)
                if found:
                    return found
        path.pop()
        state[node] = 2
        return None

    for start in sorted(adj):
        if state.get(start) is None:
            found = dfs(start, [])
            if found:
                return found
    return None


def _owner_map() -> Dict[int, List[str]]:
    """thread ident -> names of traced locks it holds (innermost
    first), reconstructed from the holder chains. Best-effort under
    concurrent mutation: a chain is walked bounded and a torn read
    costs one stale entry, never a crash."""
    out: Dict[int, List[str]] = {}
    for ident, top in list(_TOPS.items()):
        names: List[str] = []
        node, depth = top, 0
        while node is not None and depth < 64:
            names.append(node.name)
            node = node._prev
            depth += 1
        if names:
            out[ident] = names
    return out


def _aggregate() -> Dict[str, Dict[str, Any]]:
    """Per-name aggregation over all live instances."""
    with _REGISTRY_LOCK:
        locks = list(_REGISTRY)
    now = _perf()
    agg: Dict[str, Dict[str, Any]] = {}
    for lk in locks:
        a = agg.setdefault(lk.name, {
            "name": lk.name, "instances": 0, "holds": 0,
            "hold_total_s": 0.0, "slow": [0, 0, 0, 0, 0],
            "waiters": 0, "held_now": 0, "held_s": 0.0,
        })
        a["instances"] += 1
        a["holds"] += lk._holds
        # releases are 1-in-8 sampled; scale sums/buckets back up
        a["hold_total_s"] += 8.0 * lk._hold_total
        for j, v in enumerate(lk._slow):
            a["slow"][j] += 8 * v
        a["waiters"] += lk._waiters
        if lk.locked():
            a["held_now"] += 1
            a["held_s"] = max(a["held_s"], now - lk._t0)
    return agg


def snapshot() -> Dict[str, Any]:
    """This process's full lock-plane state for `ray_tpu locks` /
    /api/locks: per-name aggregates, holder attribution, and the
    acquisition-order edge graph (with its cycle, if one exists)."""
    from ray_tpu._private import spans as spans_lib
    agg = _aggregate()
    owners = _owner_map()
    thread_names = {t.ident: t.name for t in threading.enumerate()}
    held_by: Dict[str, List[Dict[str, Any]]] = {}
    for ident, names in owners.items():
        for nm in names:
            held_by.setdefault(nm, []).append(
                {"thread": ident,
                 "thread_name": thread_names.get(ident)})
    for a in agg.values():
        a["held_by"] = held_by.get(a["name"], [])
    edge_list = sorted((a, b, n) for (a, b), n in _EDGES.items())
    return {
        "proc_uid": spans_lib.PROC_UID,
        "pid": os.getpid(),
        "proc": spans_lib.process_label(),
        "node_id": spans_lib.process_node_id(),
        "ts_mono": _monotonic(),
        "locks": sorted(agg.values(), key=lambda a: a["name"]),
        "edges": [[a, b, n] for a, b, n in edge_list],
        "cycle": find_cycle((a, b) for a, b, _n in edge_list),
    }


DIGEST_KEY = "locks"
_DIGEST_EDGE_CAP = 256


def digest() -> Dict[str, Any]:
    """Compact lock digest riding every metrics harvest (the watchdog's
    inversion + long-hold probes read this; see
    metrics_plane.Watchdog._probe_locks). Long-hold candidates are
    pre-filtered loosely here (>0.5s held) — the watchdog applies the
    configured threshold so runtime tuning needs no worker restart."""
    with _REGISTRY_LOCK:
        locks = list(_REGISTRY)
    now = _perf()
    long_holds: List[Dict[str, Any]] = []
    for lk in locks:
        if lk.locked():
            held = now - lk._t0
            if held > 0.5:
                long_holds.append({"name": lk.name,
                                   "held_s": held,
                                   "waiters": lk._waiters})
    edge_list = sorted(_EDGES)
    return {"edges": [[a, b] for a, b in edge_list[:_DIGEST_EDGE_CAP]],
            "edges_dropped": max(0, len(edge_list) - _DIGEST_EDGE_CAP),
            # cycle computed HERE over the FULL edge set: the capped
            # edge list alone could slice a cycle among later-sorted
            # names out of every harvest and blind the watchdog
            "cycle": find_cycle(edge_list),
            "long_holds": long_holds[:64]}


def _export_metrics() -> None:
    """Harvest-time sampler: fold per-lock counters into the process
    metrics registry. The histogram buckets are WRITTEN, not observed
    — the lock fast path keeps its own counts so it never pays a
    metrics call; this runs only on the pull-based harvest cadence."""
    from ray_tpu.util.metrics import Gauge, Histogram, get_or_create
    agg = _aggregate()
    if not agg:
        return
    hist = get_or_create(
        Histogram, "ray_tpu_lock_held_seconds",
        description="traced-lock hold durations, 1-in-8 sampled at "
                    "release and rescaled x8 (bucket counts and sums "
                    "are estimates; the hold COUNT is exact)",
        boundaries=list(_BOUNDARIES), tag_keys=("lock",))
    gauge = get_or_create(
        Gauge, "ray_tpu_lock_waiters",
        description="threads currently blocked waiting on each traced "
                    "lock", tag_keys=("lock",))
    for name, a in agg.items():
        # scaled slow counts may overshoot the exact total on unlucky
        # sampling; clamp so bucket 0 never goes negative
        slow_sum = min(sum(a["slow"]), a["holds"])
        buckets = [max(0, a["holds"] - slow_sum)] + list(a["slow"])
        key = hist._key({"lock": name})
        with hist._lock:
            hist._buckets[key] = buckets
            hist._sums[key] = a["hold_total_s"]
            hist._counts[key] = a["holds"]
        gauge.set(float(a["waiters"]), tags={"lock": name})


def _ensure_export_registered() -> None:
    """First TracedLock in a process wires the lock plane into the
    metrics harvest: the sampler exports histogram/gauge series and
    the snapshot extra ships the watchdog digest."""
    global _registered_export
    if _registered_export:
        return
    with _REGISTRY_LOCK:
        if _registered_export:
            return
        _registered_export = True
    try:
        from ray_tpu._private import metrics_plane
        metrics_plane.register_sampler("locks", _export_metrics)
        metrics_plane.register_snapshot_extra(DIGEST_KEY, digest)
    except Exception:  # noqa: BLE001 - a metrics-less embedder still
        pass           # gets working locks; telemetry is best-effort
