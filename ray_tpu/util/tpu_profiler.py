"""Device-side profiling: jax.profiler wrappers for TPU traces.

reference parity: the reference's profiling surface is host-side
(py-spy stack dumps / memray via dashboard reporter, `ray timeline`
Chrome traces of task events — dashboard/modules/reporter/
profile_manager.py:11-19, scripts.py:1856). On TPU the interesting
trace is the DEVICE one: XLA op timelines, HBM usage, ICI collectives.
This module exposes jax.profiler with the framework's ergonomics:

    with ray_tpu.util.tpu_profiler.trace("/tmp/prof"):
        train_step(...)

    ray_tpu.util.tpu_profiler.start_server(9012)   # live tensorboard

Traces are TensorBoard-compatible (xplane) directories.
"""

from __future__ import annotations

import contextlib
import glob
import os
import time
from typing import Iterator, Optional


@contextlib.contextmanager
def trace(log_dir: str,
          create_perfetto_link: bool = False) -> Iterator[str]:
    """Capture a device trace for the with-block into log_dir."""
    import jax
    os.makedirs(log_dir, exist_ok=True)
    jax.profiler.start_trace(log_dir,
                             create_perfetto_link=create_perfetto_link)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()


def start_server(port: int = 9012):
    """Expose the live profiler (connect TensorBoard's profile plugin
    or `jax.profiler.trace_remote`)."""
    import jax
    return jax.profiler.start_server(port)


def annotate(name: str):
    """Named region inside a trace (jax.profiler.TraceAnnotation)."""
    import jax
    return jax.profiler.TraceAnnotation(name)


def latest_trace_dir(log_dir: str) -> Optional[str]:
    """The newest xplane capture under log_dir, if any."""
    pattern = os.path.join(log_dir, "plugins", "profile", "*")
    runs = sorted(glob.glob(pattern), key=os.path.getmtime)
    return runs[-1] if runs else None


def device_memory_profile(path: Optional[str] = None) -> bytes:
    """Current HBM allocation profile (pprof format); written to
    `path` when given (jax.profiler.device_memory_profile)."""
    import jax
    blob = jax.profiler.device_memory_profile()
    if path:
        with open(path, "wb") as f:
            f.write(blob)
    return blob


def profile_step(fn, *args, log_dir: Optional[str] = None, **kwargs):
    """One-shot: run fn under a trace, return (result, trace_dir)."""
    log_dir = log_dir or os.path.join(
        "/tmp", f"ray_tpu_prof_{int(time.time())}")
    with trace(log_dir):
        out = fn(*args, **kwargs)
        # intentional barrier: the trace window must include device
        # completion, or the profile under-reports the step
        import jax
        jax.block_until_ready(out)  # graftlint: disable=RT021
    return out, log_dir
