"""Public core API: init/remote/get/put/wait/kill/cancel and cluster info.

reference parity: python/ray/_private/worker.py — ray.get (:2506), ray.put
(:2621), ray.wait (:2684), ray.kill (:2850), ray.cancel (:2881), @ray.remote
(:3157); cluster info helpers from python/ray/_private/state.py.
"""

from __future__ import annotations

import inspect
from typing import Any, Dict, List, Optional, Sequence, Union

from ray_tpu._private import worker as worker_mod
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu.actor import ActorClass, ActorHandle, get_actor  # noqa: F401
from ray_tpu.remote_function import RemoteFunction


def init(address: Optional[str] = None, **kwargs: Any):
    """Start/connect the runtime (reference worker.py:1165)."""
    return worker_mod.init(address, **kwargs)


def shutdown() -> None:
    worker_mod.shutdown()


def is_initialized() -> bool:
    return worker_mod.is_initialized()


def remote(*args: Any, **options: Any):
    """@remote decorator for functions and classes (reference worker.py:3157)."""
    def make(target: Any):
        # Always build the local wrappers: they defer client-vs-direct
        # routing to CALL time, so modules may decorate at import before
        # init("ray://...") connects.
        if inspect.isclass(target):
            return ActorClass(target, options)
        return RemoteFunction(target, options)

    if len(args) == 1 and not options and callable(args[0]):
        return make(args[0])
    if args:
        raise TypeError("@remote takes keyword options only, e.g. "
                        "@remote(num_cpus=2)")
    return make


def put(value: Any) -> ObjectRef:
    ctx = worker_mod.client_context()
    if ctx is not None:
        return ctx.put(value)
    return worker_mod.global_worker().core_worker.put(value)


def get(refs: Union[ObjectRef, Sequence[ObjectRef]],
        *, timeout: Optional[float] = None) -> Any:
    ctx = worker_mod.client_context()
    if ctx is not None:
        return ctx.get(refs, timeout=timeout)
    cw = worker_mod.global_worker().core_worker
    if isinstance(refs, ObjectRef):
        return cw.get([refs], timeout=timeout)[0]
    if not isinstance(refs, (list, tuple)):
        raise TypeError(f"ray_tpu.get takes an ObjectRef or a list of "
                        f"ObjectRefs, got {type(refs).__name__}")
    _check_refs(refs, "get")
    return cw.get(list(refs), timeout=timeout)


def _check_refs(refs: Sequence[Any], api: str) -> None:
    for i, r in enumerate(refs):
        if not isinstance(r, ObjectRef):
            raise TypeError(
                f"ray_tpu.{api} takes ObjectRefs; element {i} is "
                f"{type(r).__name__} ({r!r})")


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None):
    if isinstance(refs, ObjectRef):
        raise TypeError("ray_tpu.wait takes a list of ObjectRefs, got a "
                        "bare ObjectRef (wrap it in a list)")
    if not isinstance(refs, (list, tuple)):
        raise TypeError(f"ray_tpu.wait takes a list of ObjectRefs, got "
                        f"{type(refs).__name__}")
    if num_returns <= 0:
        if num_returns == 0 and not refs:
            # wait([], num_returns=len([])) is a common drain pattern
            return [], []
        # returning ([], refs) for num_returns=0 on real refs looks like
        # "nothing ready yet" and silently disables the caller's
        # backpressure
        raise ValueError(
            f"ray_tpu.wait needs num_returns >= 1, got {num_returns}")
    ctx = worker_mod.client_context()
    if ctx is not None:
        # client mode carries ClientObjectRefs; the server side
        # re-validates element types against the real ObjectRef
        return ctx.wait(list(refs), num_returns=num_returns,
                        timeout=timeout)
    _check_refs(refs, "wait")
    cw = worker_mod.global_worker().core_worker
    return cw.wait(list(refs), num_returns=num_returns, timeout=timeout)


def kill(actor: ActorHandle, *, no_restart: bool = True) -> None:
    ctx = worker_mod.client_context()
    if ctx is not None:
        ctx.kill(actor, no_restart=no_restart)
        return
    cw = worker_mod.global_worker().core_worker
    cw.kill_actor(actor._actor_id, no_restart=no_restart)


def cancel(ref: ObjectRef, *, force: bool = False,
           recursive: bool = True) -> None:
    cw = worker_mod.global_worker().core_worker
    cw.cancel_task(ref)


def free(refs: Sequence[ObjectRef]) -> None:
    worker_mod.global_worker().core_worker.free(list(refs))


# ---- cluster introspection ------------------------------------------------

def nodes() -> List[Dict[str, Any]]:
    w = worker_mod.global_worker()
    infos = w.core_worker._gcs.call("get_all_nodes")
    return [{
        "NodeID": n.node_id.hex(), "Alive": n.alive,
        "NodeManagerAddress": n.address[0], "NodeManagerPort": n.address[1],
        "Resources": dict(n.resources_total), "Labels": dict(n.labels),
        "IsHead": n.is_head,
    } for n in infos]


def cluster_resources() -> Dict[str, float]:
    w = worker_mod.global_worker()
    view = w.core_worker._gcs.call("get_cluster_resources")
    total: Dict[str, float] = {}
    for entry in view.values():
        for k, v in entry["total"].items():
            total[k] = total.get(k, 0.0) + v
    return total


def available_resources() -> Dict[str, float]:
    w = worker_mod.global_worker()
    view = w.core_worker._gcs.call("get_cluster_resources")
    avail: Dict[str, float] = {}
    for entry in view.values():
        for k, v in entry["available"].items():
            avail[k] = avail.get(k, 0.0) + v
    return avail


def timeline(filename: Optional[str] = None, *, spans: bool = False,
             trace_id: Optional[str] = None) -> List[Dict[str, Any]]:
    """Dump cluster execution as Chrome-trace JSON (reference `ray
    timeline`, scripts/scripts.py:1856; load via chrome://tracing or
    Perfetto).

    spans=True additionally gathers every process's flight-recorder ring
    (microsecond spans on the RPC/store/serialization/task/feed hot
    paths — see _private/spans.py), aligns per-process clocks, and
    interleaves them with the task events plus CHAOS_FAULT_INJECTED
    cluster events. trace_id filters the dump to one `start_trace`
    block's task records and span records."""
    import json

    from ray_tpu._private.task_events import timeline_events
    from ray_tpu.util import state as state_api
    records = state_api.list_tasks(
        filters={"trace_id": trace_id} if trace_id else None)
    events = timeline_events(records)
    if spans:
        from ray_tpu._private import spans as spans_mod
        w = worker_mod.global_worker()
        snaps = w.core_worker._gcs.call("spans_collect")
        events.extend(spans_mod.merge_snapshots(snaps, trace_id=trace_id))
        # chaos faults as instant events on a synthetic row, so injected
        # failures line up visually with the latency they caused
        if not trace_id:
            for ev in state_api.list_cluster_events(
                    event_type="CHAOS_FAULT_INJECTED"):
                events.append({
                    "ph": "i", "cat": "chaos",
                    "name": "CHAOS_FAULT_INJECTED",
                    "pid": "chaos", "tid": ev.get("fault") or "fault",
                    "ts": float(ev.get("ts", 0.0)) * 1e6, "s": "g",
                    "args": {"rule_id": ev.get("rule_id"),
                             "fault": ev.get("fault"),
                             "message": ev.get("message")},
                })
        events.sort(key=lambda e: e.get("ts", 0.0))
    if filename:
        with open(filename, "w") as f:
            json.dump(events, f)
    return events


def get_gcs_address() -> str:
    w = worker_mod.global_worker()
    host, port = w.gcs_address
    return f"{host}:{port}"


class _RuntimeContext:
    """reference parity: ray.runtime_context.RuntimeContext."""

    @property
    def worker(self):
        return worker_mod.global_worker()

    def get_job_id(self) -> str:
        return self.worker.core_worker.job_id.hex()

    def get_node_id(self) -> str:
        return self.worker.core_worker.node_id_hex

    def get_worker_id(self) -> str:
        return self.worker.core_worker.worker_id.hex()

    def get_task_id(self) -> str:
        return self.worker.core_worker.current_task_id().hex()

    def get_actor_id(self) -> Optional[str]:
        cw = self.worker.core_worker
        if cw.executor is not None and cw.executor.actor_id is not None:
            return cw.executor.actor_id.hex()
        return None

    def get_task_queue_depth(self, group: str = "") -> int:
        """Queued + running tasks on this worker's executor for one
        concurrency group — the server-side ongoing-request count serve
        replicas report to the router (reference: replica queue-length
        probes behind PowerOfTwoChoicesReplicaScheduler,
        serve/_private/router.py:893)."""
        ex = self.worker.core_worker.executor
        return ex.queue_depth(group) if ex is not None else 0

    @property
    def was_current_actor_reconstructed(self) -> bool:
        return False


def get_runtime_context() -> _RuntimeContext:
    return _RuntimeContext()
