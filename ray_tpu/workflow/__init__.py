"""ray_tpu.workflow: durable DAG execution with per-step checkpoints.

reference parity: python/ray/workflow — workflow_executor.py /
workflow_state.py: each step's result persists to storage as it
completes, so a crashed workflow resumes from its last finished step
instead of recomputing. Function DAGs only (actor nodes are stateful and
not safely replayable — the reference imposes the same contract via
workflow options).

Round-4 additions (VERDICT r3 #10):
- per-step retries: `workflow.options(node, max_retries=N)` — retried
  by the runtime's task-retry machinery, so downstream refs stay valid
  across attempts (reference: workflow step options max_retries).
- continuations: a step may RETURN `workflow.continuation(sub_dag)`;
  the executor then runs that dynamically-built DAG and records its
  result as the step's durable value (reference:
  workflow_executor.py continuation handling).
- resume after driver kill: run()/resume() replay from the step
  checkpoints a killed driver left behind (kill-and-resume test in
  tests/test_workflow_round4.py).
"""

from __future__ import annotations

import hashlib
import os
import pickle
from typing import Any, Dict, Optional

import ray_tpu
from ray_tpu.dag import DAGNode, FunctionNode, InputNode

DEFAULT_STORAGE = "/tmp/ray_tpu_workflows"


class Continuation:
    """Marker a step returns to hand the workflow off to a new DAG."""

    def __init__(self, dag: DAGNode, dag_input: Any = None):
        if not isinstance(dag, DAGNode):
            raise TypeError(
                f"continuation needs a DAG node, got {type(dag)}")
        self.dag = dag
        self.dag_input = dag_input


def continuation(dag: DAGNode, *, dag_input: Any = None) -> Continuation:
    """Return from inside a step to continue the workflow with `dag`."""
    return Continuation(dag, dag_input)


class EventNode(DAGNode):
    """A workflow step that resolves when an external event named
    `name` is delivered via `send_event` (reference: workflow events —
    api.wait_for_event / event listeners). Durable like any step: once
    satisfied, the payload checkpoints and resume never waits again."""

    def __init__(self, name: str, timeout_s: float = 300.0):
        super().__init__((), {})
        self.event_name = name
        self.timeout_s = timeout_s


def wait_for_event(name: str, *, timeout_s: float = 300.0) -> EventNode:
    """DAG node that blocks the workflow until `send_event(workflow_id,
    name, payload)` delivers; resolves to the payload."""
    return EventNode(name, timeout_s)


def send_event(workflow_id: str, name: str, payload: Any = None, *,
               storage: str = DEFAULT_STORAGE) -> None:
    """Deliver an event to a (possibly running, possibly resumed-later)
    workflow; payloads persist durably in the workflow's storage."""
    events_dir = os.path.join(storage, workflow_id, "events")
    os.makedirs(events_dir, exist_ok=True)
    path = os.path.join(events_dir, f"{name}.pkl")
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        pickle.dump(payload, f)
    os.replace(tmp, path)


def _await_event(events_dir: str, name: str, timeout_s: float) -> Any:
    """Worker-side: poll the durable event file until delivered."""
    import time as _time
    path = os.path.join(events_dir, f"{name}.pkl")
    deadline = _time.monotonic() + timeout_s
    while _time.monotonic() < deadline:
        if os.path.exists(path):
            with open(path, "rb") as f:
                return pickle.load(f)
        _time.sleep(0.1)
    raise TimeoutError(
        f"workflow event {name!r} not delivered within {timeout_s}s")


def options(node: DAGNode, *, max_retries: int = 0,
            retry_exceptions: bool = True) -> DAGNode:
    """Attach per-step durability options to a DAG node (reference:
    workflow step options). Retries run through the runtime's task
    retry machinery, so refs held by downstream steps survive the
    retry."""
    node._workflow_options = {  # type: ignore[attr-defined]
        "max_retries": int(max_retries),
        "retry_exceptions": bool(retry_exceptions)}
    return node


def _step_id(node: DAGNode, memo: Dict[int, str],
             input_token: str) -> str:
    """Stable structural id: function name + child step ids + literal
    args + the run's input, each field framed with an explicit tag and
    terminator (unframed concatenation collides: f(1, 23) vs f(12, 3)).
    Deterministic across runs of the same DAG + input, so resume matches
    completed steps to their checkpoints."""
    if node._id in memo:
        return memo[node._id]
    h = hashlib.sha1()
    if isinstance(node, EventNode):
        h.update(b"event:" + node.event_name.encode() + b";")
    elif isinstance(node, FunctionNode):
        h.update(b"fn:" + node.name.encode() + b";")
    elif isinstance(node, InputNode):
        # the input value is part of step identity: a different input
        # must not restore checkpoints computed from the old one
        h.update(b"input:" + input_token.encode() + b";")
    else:
        raise TypeError(
            f"workflows support function DAGs only, got {type(node)}")
    for a in node._bound_args:
        if isinstance(a, DAGNode):
            h.update(b"dep:" + _step_id(a, memo, input_token).encode()
                     + b";")
        else:
            h.update(b"arg:" + repr(a).encode() + b";")
    for k in sorted(node._bound_kwargs):
        v = node._bound_kwargs[k]
        if isinstance(v, DAGNode):
            h.update(b"kdep:" + k.encode() + b"="
                     + _step_id(v, memo, input_token).encode() + b";")
        else:
            h.update(b"kwarg:" + k.encode() + b"="
                     + repr(v).encode() + b";")
    memo[node._id] = h.hexdigest()[:16]
    return memo[node._id]


class _DurableExecutor:
    """Two-phase durable execution: submit every non-checkpointed step as
    a task (refs flow between steps, so independent branches run
    CONCURRENTLY), then harvest results in submission order, persisting
    each step's value as it completes. A mid-run failure still leaves
    every finished step checkpointed for resume."""

    def __init__(self, workflow_dir: str, dag_input: Any):
        self.steps_dir = os.path.join(workflow_dir, "steps")
        self.events_dir = os.path.join(workflow_dir, "events")
        os.makedirs(self.steps_dir, exist_ok=True)
        self.dag_input = dag_input
        self._input_token = hashlib.sha1(
            repr(dag_input).encode()).hexdigest()[:16]
        self._ids: Dict[int, str] = {}
        self._memo: Dict[int, Any] = {}       # node id -> ref or value
        self._pending: list = []              # (step_id, ref) to harvest
        # id(ref)s passed as args into OTHER steps: such steps must not
        # return continuations (terminal-only; see run())
        self._consumed_refs: set = set()
        self.steps_executed = 0
        self.steps_restored = 0

    def _ckpt_path(self, step_id: str) -> str:
        return os.path.join(self.steps_dir, f"{step_id}.pkl")

    def _submit(self, node: DAGNode) -> Any:
        """Ref (running) or value (checkpointed/input) for a node."""
        if node._id in self._memo:
            return self._memo[node._id]
        if isinstance(node, InputNode):
            value: Any = self.dag_input
        else:
            step_id = _step_id(node, self._ids, self._input_token)
            path = self._ckpt_path(step_id)
            if os.path.exists(path):
                with open(path, "rb") as f:
                    value = pickle.load(f)
                self.steps_restored += 1
            elif isinstance(node, EventNode):
                value = ray_tpu.remote(_await_event).remote(
                    self.events_dir, node.event_name, node.timeout_s)
                self._pending.append((step_id, value))
                self.steps_executed += 1
            else:
                args = tuple(self._submit(a) if isinstance(a, DAGNode)
                             else a for a in node._bound_args)
                kwargs = {k: self._submit(v) if isinstance(v, DAGNode)
                          else v
                          for k, v in node._bound_kwargs.items()}
                for dep in (*args, *kwargs.values()):
                    if isinstance(dep, ray_tpu.ObjectRef):
                        self._consumed_refs.add(id(dep))
                wf_opts = getattr(node, "_workflow_options", None)
                fn = node._remote_fn
                if wf_opts and wf_opts.get("max_retries"):
                    fn = fn.options(
                        max_retries=wf_opts["max_retries"],
                        retry_exceptions=wf_opts.get(
                            "retry_exceptions", True))
                value = fn.remote(*args, **kwargs)
                self._pending.append((step_id, value))
                self.steps_executed += 1
        self._memo[node._id] = value
        return value

    def run(self, node: DAGNode) -> Any:
        result = self._submit(node)
        # Harvest + checkpoint every submitted step; keep going past a
        # failure so completed siblings persist, then raise the first.
        first_error: Any = None
        values: Dict[str, Any] = {}
        for step_id, ref in self._pending:
            try:
                # ordered durable harvest: steps run concurrently
                # regardless; each result checkpoints before the next is
                # examined # graftlint: disable=RT002
                value = ray_tpu.get(ref)
            except Exception as e:  # noqa: BLE001
                if first_error is None:
                    first_error = e
                continue
            if isinstance(value, Continuation):
                # the step handed the workflow off to a dynamic DAG:
                # execute it durably under a sub-directory keyed by this
                # step's id, and record ITS result as the step's value.
                # Only TERMINAL steps may continue — a downstream step
                # submitted in the same run would have received the raw
                # Continuation marker through its ref (and a resumed run
                # would see the unwrapped value: divergent results).
                if id(ref) in self._consumed_refs:
                    raise RuntimeError(
                        f"step {step_id} returned a continuation but "
                        "another step consumes its output; "
                        "continuations are only supported on the "
                        "workflow's final step")
                sub_dir = os.path.join(self.steps_dir,
                                       f"cont-{step_id}")
                sub = _DurableExecutor(sub_dir, value.dag_input)
                value = sub.run(value.dag)
                self.steps_executed += sub.steps_executed
                self.steps_restored += sub.steps_restored
            values[id(ref)] = value
            path = self._ckpt_path(step_id)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                pickle.dump(value, f)
            os.replace(tmp, path)
        if first_error is not None:
            raise first_error
        if isinstance(result, ray_tpu.ObjectRef):
            return values[id(result)]
        return result


def run(dag: DAGNode, *, workflow_id: str,
        storage: str = DEFAULT_STORAGE, dag_input: Any = None) -> Any:
    """Execute (or continue) a workflow; completed steps load from their
    checkpoints instead of re-executing."""
    wf_dir = os.path.join(storage, workflow_id)
    os.makedirs(wf_dir, exist_ok=True)
    ex = _DurableExecutor(wf_dir, dag_input)
    result = ex.run(dag)
    with open(os.path.join(wf_dir, "result.pkl"), "wb") as f:
        pickle.dump(result, f)
    return result


def resume(dag: DAGNode, *, workflow_id: str,
           storage: str = DEFAULT_STORAGE, dag_input: Any = None) -> Any:
    """Alias of run(): durability makes resumption the same operation."""
    return run(dag, workflow_id=workflow_id, storage=storage,
               dag_input=dag_input)


def get_output(workflow_id: str, *,
               storage: str = DEFAULT_STORAGE) -> Optional[Any]:
    path = os.path.join(storage, workflow_id, "result.pkl")
    if not os.path.exists(path):
        return None
    with open(path, "rb") as f:
        return pickle.load(f)


__all__ = ["run", "resume", "get_output", "options", "continuation",
           "Continuation", "wait_for_event", "send_event", "EventNode",
           "DEFAULT_STORAGE"]
