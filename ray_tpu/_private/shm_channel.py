"""Same-node shared-memory task channel: SPSC mmap byte-rings.

A task pushed to a worker on the owner's own node pays a full loopback
RPC today: pickle → sendall → kernel → recv → unpickle, with two
syscalls and a thread wakeup per message — hundreds of µs on a busy or
syscall-filtered box. This module replaces that hop with a shared-
memory ring: the producer memcpy's the framed payload straight into an
mmap'd ring file, and a doorbell one-way RPC fires only when the
consumer is parked. While the ring is hot, N messages cost zero
syscalls.

Topology: one directed ring per (producer process → consumer process)
pair, created by the PRODUCER (a file next to the node's object-store
arena), advertised to the consumer by the first doorbell
(`shm_doorbell(path=...)` on the consumer's ordinary RpcServer). The
consumer attaches and dispatches each message into its normal RPC
handler table, so shm and socket deliveries of the same method are
indistinguishable to the handler.

Payloads are self-contained records IN the ring (no external arena
block to allocate or free — an earlier design rode the store arena's
process-shared allocator and spent more time in alloc() than in the
copy it saved). Wire form: the PR 3 envelope (serialization.pack) of
the (method, kwargs) pair.

Ring layout (u64 monotonic counters; all records 8-byte aligned):

  header (64B): magic | capacity | head (consumer-owned) |
                tail (producer-owned) | idle
  records:      size u32 | pad u32 | payload (padded to 8)
  wrap marker:  size == 0xFFFFFFFF → skip to the ring's start

Idle protocol: producer bumps tail, then reads idle — 1 means the
consumer parked, so set idle=0 and send the doorbell. The consumer
grace-polls ~2ms before parking (a doorbell is a full one-way RPC, the
very syscall this channel avoids; staying awake through the
inter-message gaps of a steady stream keeps the channel doorbell-free)
and re-checks tail after setting idle=1, with a 0.2s poll backstop:
x86-TSO permits the producer's idle LOAD to complete before its tail
STORE is globally visible, so a doorbell can theoretically be skipped
— the poll bounds that window.

Failure semantics: ring full or message too big → ShmUnavailable, the
caller falls back to the plain RPC one-way (same message, same
handler; the message was NOT enqueued). A doorbell send failure
propagates — the consumer process is unreachable, which is the same
dead-peer signal the socket path raises. A consumer that dies with
messages in its ring loses them exactly like messages buffered in a
dead peer's socket: the out-of-band failure paths (NM worker-death
report, actor-death pubsub) own recovery.
"""

from __future__ import annotations

import logging
import mmap
import os
import struct
import threading
import time
from typing import Any, Callable, Dict, Tuple

from ray_tpu._private import serialization as ser

logger = logging.getLogger(__name__)

_MAGIC = 0x52545348  # "RTSH"
_HDR = struct.Struct(">QQQQQ")          # magic, capacity, head, tail, idle
_HDR_SIZE = 64                          # one cache line for the header
_OFF_HEAD = 16
_OFF_TAIL = 24
_OFF_IDLE = 32
_REC = struct.Struct("<I")              # record: size u32, 4B pad, payload
_WRAP = 0xFFFFFFFF

_SHM_COUNTER = None


def _count_msg(site: str, n: int = 1) -> None:
    global _SHM_COUNTER
    c = _SHM_COUNTER
    if c is None:
        try:
            from ray_tpu.util.metrics import Counter, get_or_create
            c = get_or_create(
                Counter, "ray_tpu_shm_msgs_total",
                description="messages over same-node shm task rings, "
                            "by site",
                tag_keys=("site",))
        except Exception:  # noqa: BLE001 - metrics are best-effort
            return
        _SHM_COUNTER = c
    try:
        c.inc(n, tags={"site": site})
    except Exception:  # noqa: BLE001 - metrics are best-effort
        pass


class ShmUnavailable(Exception):
    """Ring full / payload too big — the caller should use the RPC
    path for THIS message."""


def _pad8(n: int) -> int:
    return (n + 7) & ~7


class _Ring:
    """mmap'd ring file; Sender creates, Receiver attaches."""

    def __init__(self, path: str, capacity: int = 0, create: bool = False):
        self.path = path
        if create:
            size = _HDR_SIZE + capacity
            fd = os.open(path, os.O_CREAT | os.O_RDWR, 0o600)
            try:
                os.ftruncate(fd, size)
                self.mm = mmap.mmap(fd, size)
            finally:
                os.close(fd)
            _HDR.pack_into(self.mm, 0, _MAGIC, capacity, 0, 0, 1)
        else:
            fd = os.open(path, os.O_RDWR)
            try:
                self.mm = mmap.mmap(fd, 0)
            finally:
                os.close(fd)
            magic, capacity, _h, _t, _i = _HDR.unpack_from(self.mm, 0)
            if magic != _MAGIC:
                raise ValueError(f"not a shm ring: {path}")
        self.capacity = capacity

    def _u64(self, off: int) -> int:
        return struct.unpack_from(">Q", self.mm, off)[0]

    def _set_u64(self, off: int, v: int) -> None:
        struct.pack_into(">Q", self.mm, off, v)

    @property
    def head(self) -> int:
        return self._u64(_OFF_HEAD)

    @head.setter
    def head(self, v: int) -> None:
        self._set_u64(_OFF_HEAD, v)

    @property
    def tail(self) -> int:
        return self._u64(_OFF_TAIL)

    @tail.setter
    def tail(self, v: int) -> None:
        self._set_u64(_OFF_TAIL, v)

    @property
    def idle(self) -> int:
        return self._u64(_OFF_IDLE)

    @idle.setter
    def idle(self, v: int) -> None:
        self._set_u64(_OFF_IDLE, v)

    def close(self) -> None:
        try:
            self.mm.close()
        except (BufferError, ValueError):
            pass


class Sender:
    """Producer half of one directed ring. Thread-safe (one lock per
    sender: sends from many submitter threads serialize here, exactly
    like the RpcClient lock they replace — minus the syscalls)."""

    def __init__(self, ring_dir: str, tag: str, capacity: int,
                 doorbell: Callable[[str], None]):
        capacity = max(_pad8(capacity), 1 << 12)
        self.path = os.path.join(ring_dir, f"shmring-{tag}.ring")
        self.ring = _Ring(self.path, capacity=capacity, create=True)
        self._doorbell = doorbell
        self._lock = threading.Lock()
        self.sent = 0

    def send(self, method: str, kwargs: Dict[str, Any]) -> None:
        """Enqueue one message. Raises ShmUnavailable when it doesn't
        fit (caller falls back to RPC — the message was NOT enqueued)
        and propagates doorbell failures (consumer unreachable — same
        signal as a dead-socket one-way)."""
        payload = ser.pack((method, kwargs))
        size = len(payload)
        need = 8 + _pad8(size)
        ring = self.ring
        cap = ring.capacity
        if need > cap // 2:
            raise ShmUnavailable(f"message too big for ring ({size}B)")
        with self._lock:
            tail = ring.tail
            pos = tail % cap
            spend = need
            if pos + need > cap:
                # record must be contiguous: mark the rest of the lap
                # as a wrap and restart at offset 0
                spend += cap - pos
            if spend > cap - (tail - ring.head):
                raise ShmUnavailable("ring full")
            if pos + need > cap:
                _REC.pack_into(ring.mm, _HDR_SIZE + pos, _WRAP)
                tail += cap - pos
                pos = 0
            base = _HDR_SIZE + pos
            _REC.pack_into(ring.mm, base, size)
            ring.mm[base + 8:base + 8 + size] = payload
            ring.tail = tail + need
            ding = ring.idle == 1
            if ding:
                ring.idle = 0
            self.sent += 1
        _count_msg("send")
        if ding:
            self._doorbell(self.path)

    def close(self) -> None:
        self.ring.close()
        try:
            os.unlink(self.path)
        except OSError:
            pass


class Receiver:
    """Consumer side: one drain thread per attached ring, dispatching
    into the process's ordinary RPC handler table."""

    def __init__(self, dispatch: Callable[[str, Dict[str, Any]], None]):
        self._dispatch = dispatch
        self._lock = threading.Lock()
        self._rings: Dict[str, threading.Event] = {}
        self._shutdown = False
        self.received = 0

    def on_doorbell(self, path: str) -> None:
        """RPC handler body for `shm_doorbell`: the first ring for a
        path attaches it and spawns its drainer; later rings wake it."""
        with self._lock:
            ev = self._rings.get(path)
            if ev is None:
                ev = threading.Event()
                self._rings[path] = ev
                threading.Thread(
                    target=self._drain_loop, args=(path, ev), daemon=True,
                    name=f"shm-drain-{os.path.basename(path)[:24]}").start()
        ev.set()

    def stop(self) -> None:
        self._shutdown = True
        with self._lock:
            for ev in self._rings.values():
                ev.set()

    def _drain_loop(self, path: str, ev: threading.Event) -> None:
        try:
            ring = _Ring(path)
        except Exception:  # noqa: BLE001 - producer falls back to RPC
            logger.exception("cannot attach shm ring %s", path)
            with self._lock:
                self._rings.pop(path, None)
            return
        cap = ring.capacity
        while not self._shutdown:
            head, tail = ring.head, ring.tail
            if head < tail:
                pos = head % cap
                base = _HDR_SIZE + pos
                (size,) = _REC.unpack_from(ring.mm, base)
                if size == _WRAP:
                    ring.head = head + (cap - pos)
                    continue
                # copy out BEFORE advancing head: once head moves the
                # producer may overwrite the record, and unpack is
                # zero-copy over the buffer it is handed
                data = bytes(ring.mm[base + 8:base + 8 + size])
                ring.head = head + 8 + _pad8(size)
                self.received += 1
                _count_msg("recv")
                try:
                    method, kwargs = ser.unpack(memoryview(data))
                    self._dispatch(method, kwargs)
                except Exception:  # noqa: BLE001 - mirrors the oneway
                    # RPC contract: handler errors are logged, the
                    # channel lives on
                    logger.exception("shm message dispatch failed (%s)",
                                     path)
                continue
            # grace poll before parking (see module docstring)
            for _ in range(4):
                time.sleep(0.0005)
                if ring.tail > ring.head or self._shutdown:
                    break
            if ring.tail > ring.head:
                continue
            ring.idle = 1
            if ring.tail > ring.head:
                # producer raced the park: it may have read idle==0 and
                # skipped the doorbell — drain what it wrote
                ring.idle = 0
                continue
            ev.wait(timeout=0.2)  # poll backstop for the TSO window
            ev.clear()
            ring.idle = 0
        ring.close()
