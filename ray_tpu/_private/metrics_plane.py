"""Cluster metrics plane: harvest fan-out, merge math, history, watchdog.

reference parity: _private/metrics_agent.py + dashboard/modules/metrics/
(the reference runs an OpenCensus agent per node and lets an external
Prometheus pull-aggregate, Monarch-style). Here the GCS itself is the
aggregation point so a cluster is observable with zero external infra:

  - **harvest**: `metrics_collect` fans out GCS → node managers → each
    node's workers in one RPC hop (plus pubsub-subscribed drivers),
    mirroring the flight recorder's spans_collect; every process ships
    its `util.metrics.collect_wire()` snapshot tagged with
    node_id/proc/pid and deduped by proc uid.
  - **merge**: ClusterAggregator folds per-process series into cluster
    series with counter-reset detection — a restarted worker (new proc
    uid starting at 0) or an in-place reset folds the vanished
    contribution into a retained base, so merged counters never go
    backwards and rates never go negative.
  - **history**: a bounded in-memory ring of merged samples on the GCS
    (`metrics_history`) powers `ray_tpu top` and dashboard sparklines
    without an external Prometheus.
  - **watchdog**: an always-on evaluator over the harvested series runs
    invariant probes (lease-slot balance, store occupancy vs pinned
    bytes, wait-graph edge age, drop-counter growth, executor queue
    depth, harvest coverage) and emits HEALTH_ALERT cluster events
    naming the offending series and process.

Recording stays pull-based: hot paths pay nothing for this plane beyond
the metrics they already increment; all aggregation cost sits on the
GCS sampler thread at `Config.metrics_sample_interval_s` cadence.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple
from ray_tpu.util.locks import TracedLock

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------
# Per-process snapshot + samplers
# ---------------------------------------------------------------------

# name -> callable run (best-effort) right before this process snapshots
# its registry: components export point-in-time gauges (lease slots,
# store occupancy, wait-graph size) here instead of instrumenting their
# hot paths. Keyed by component name so a re-init replaces, not stacks.
_SAMPLERS: Dict[str, Callable[[], None]] = {}
_SAMPLERS_LOCK = threading.Lock()


def register_sampler(name: str, fn: Callable[[], None]) -> None:
    with _SAMPLERS_LOCK:
        _SAMPLERS[name] = fn


def unregister_sampler(name: str) -> None:
    with _SAMPLERS_LOCK:
        _SAMPLERS.pop(name, None)


# key -> provider returning a JSON-able value attached to this process's
# harvest snapshot under that key. Non-metric payloads that must ride
# the SAME round as the gauges they are judged against (the memory
# plane's leak-probe digests) register here; keyed so a re-init
# replaces. Providers must be small — this ships every harvest.
_SNAPSHOT_EXTRAS: Dict[str, Callable[[], Any]] = {}


def register_snapshot_extra(key: str, fn: Callable[[], Any]) -> None:
    with _SAMPLERS_LOCK:
        _SNAPSHOT_EXTRAS[key] = fn


def unregister_snapshot_extra(key: str) -> None:
    with _SAMPLERS_LOCK:
        _SNAPSHOT_EXTRAS.pop(key, None)


def snapshot_process() -> Dict[str, Any]:
    """This process's full registry in wire format, identity-tagged for
    the harvest (proc uid for dedupe, label/node/pid for exposition)."""
    from ray_tpu._private import spans as spans_lib
    from ray_tpu.util import metrics as metrics_mod
    with _SAMPLERS_LOCK:
        samplers = list(_SAMPLERS.values())
        extras = list(_SNAPSHOT_EXTRAS.items())
    for fn in samplers:
        try:
            fn()
        except Exception:  # noqa: BLE001 - a dead component's sampler
            pass           # must not break the whole snapshot
    snap = {
        "proc_uid": spans_lib.PROC_UID,
        "pid": os.getpid(),
        "proc": spans_lib.process_label(),
        "node_id": spans_lib.process_node_id(),
        "wall_time": time.time(),
        "metrics": metrics_mod.collect_wire(),
    }
    for key, fn in extras:
        try:
            snap[key] = fn()
        except Exception:  # noqa: BLE001 - one broken provider must not
            pass           # blank the whole snapshot
    return snap


# ---------------------------------------------------------------------
# Cross-process merge math
# ---------------------------------------------------------------------


def _series_key(name: str, tags: Dict[str, str]) -> str:
    if not tags:
        return name
    inner = ",".join(f"{k}={v}" for k, v in sorted(tags.items()))
    return f"{name}{{{inner}}}"


def merge_histograms(entries: List[Dict[str, Any]]
                     ) -> Optional[Dict[str, Any]]:
    """Merge same-tag histogram contributions from several processes:
    per-bucket counts sum elementwise when boundaries agree; differing
    boundary sets merge onto their sorted union, each source bucket's
    count landing in the union bucket whose upper edge equals the
    source bucket's upper edge. Cumulative counts are exact at every
    edge shared by ALL sources — in particular everywhere in the
    normal case of one binary, one boundary config — and at +Inf. At
    an edge some source lacks, that source's mass sits at its own
    next-higher edge (its overflow mass at +Inf), so the merged
    cumulative there is a LOWER bound and quantile estimates over
    heterogeneous configs bias conservatively HIGH, never low. Each
    entry: {"boundaries": [...], "buckets": [...], "sum": s,
    "count": n}."""
    if not entries:
        return None
    union: List[float] = sorted({b for e in entries
                                 for b in e["boundaries"]})
    buckets = [0] * (len(union) + 1)
    total_sum = 0.0
    total_count = 0
    for e in entries:
        idx = {b: union.index(b) for b in e["boundaries"]}
        for i, count in enumerate(e["buckets"]):
            if i < len(e["boundaries"]):
                buckets[idx[e["boundaries"][i]]] += count
            else:
                buckets[-1] += count  # overflow (+Inf) bucket
        total_sum += e["sum"]
        total_count += e["count"]
    return {"boundaries": union, "buckets": buckets,
            "sum": total_sum, "count": total_count}


class ClusterAggregator:
    """Stateful merge of successive harvests into cluster series.

    Counter-reset handling: each proc's last-seen contribution is
    remembered per series. When a series vanishes from a harvest —
    its whole proc gone (worker died / unreachable), or just that
    series gone from a still-reporting proc (util.metrics.clear()
    removes series outright rather than zeroing them) — its last
    value folds into a retained base, so the merged cumulative total
    holds steady instead of dropping. The fold is decided reversible
    PER SERIES on reappearance: back at >= its folded value means the
    counter actually continued (a transient blip) and the fold
    reverses to avoid double-counting; back below it means a real
    reset and the base stays. A counter that goes BACKWARDS under an
    unchanged proc uid without vanishing (in-place reset) folds its
    previous value the same way. Gauges are point-in-time: summed
    over live procs only, no retention."""

    # Harvest rounds a proc uid may stay absent before its fold
    # records become permanent and are dropped. A dead worker's
    # restart arrives under a NEW uid, so its records can never
    # unfold — without eviction the always-on GCS would grow one
    # record per series per worker EVER started. A uid that does
    # return later than this is treated as a fresh proc: its counts
    # stack on the retained base (a one-time overcount by the folded
    # amount, never a drop — monotonicity holds either way).
    FOLD_EVICT_ROUNDS = 30

    def __init__(self) -> None:
        # (uid, series_key) -> last counter value seen from that proc
        self._last: Dict[Tuple[str, str], float] = {}
        # series_key -> folded-in base from vanished/reset contributions
        self._retained: Dict[str, float] = {}
        # (uid, series_key) -> value folded when the series vanished
        # (blip vs reset is decided if/when it reappears)
        self._series_folded: Dict[Tuple[str, str], float] = {}
        # uid -> consecutive rounds absent from the harvest (fold
        # eviction clock; reset the round the uid reappears)
        self._uid_absent_rounds: Dict[str, int] = {}

    def update(self, snaps: List[Dict[str, Any]]) -> Dict[str, float]:
        """Ingest one harvest; returns the merged flat series map
        {series_key: value}. Histograms contribute `<name>_sum` and
        `<name>_count` series (cumulative, retained like counters)."""
        live: Dict[Tuple[str, str], float] = {}
        gauges: Dict[str, float] = {}
        uids = set()
        for snap in snaps:
            uid = snap["proc_uid"]
            uids.add(uid)
            for m in snap.get("metrics", ()):
                for s in m["series"]:
                    key = _series_key(m["name"], s["tags"])
                    if m["kind"] == "gauge":
                        gauges[key] = gauges.get(key, 0.0) + s["value"]
                    elif m["kind"] == "histogram":
                        for suffix, v in (("_sum", s["sum"]),
                                          ("_count", float(s["count"]))):
                            k2 = _series_key(m["name"] + suffix,
                                             s["tags"])
                            live[(uid, k2)] = \
                                live.get((uid, k2), 0.0) + v
                    else:
                        live[(uid, key)] = \
                            live.get((uid, key), 0.0) + s["value"]
        # vanished series — proc gone from the harvest OR the series
        # gone from a live proc's snapshot — fold into the retained
        # base so the merged total holds instead of dropping
        for (uid, key) in list(self._last):
            if (uid, key) not in live:
                v = self._last.pop((uid, key))
                self._retained[key] = self._retained.get(key, 0.0) + v
                self._series_folded[(uid, key)] = \
                    self._series_folded.get((uid, key), 0.0) + v
        # in-place resets: value regressed under the same uid
        out: Dict[str, float] = {}
        for (uid, key), v in live.items():
            folded = self._series_folded.pop((uid, key), None)
            if folded is not None and v >= folded:
                # the counter continued past its folded value — a
                # transient blip, not a reset: unfold it
                self._retained[key] = \
                    self._retained.get(key, 0.0) - folded
            prev = self._last.get((uid, key))
            if prev is not None and v < prev:
                self._retained[key] = self._retained.get(key, 0.0) + prev
            self._last[(uid, key)] = v
            out[key] = out.get(key, 0.0) + v
        # age out fold records of long-gone procs so the always-on GCS
        # stays bounded under worker churn (their values remain in
        # _retained — only the per-uid unfold bookkeeping is dropped)
        folded_uids = {uid for (uid, _k) in self._series_folded}
        for uid in list(self._uid_absent_rounds):
            if uid in uids or uid not in folded_uids:
                del self._uid_absent_rounds[uid]
        for uid in folded_uids - uids:
            rounds = self._uid_absent_rounds.get(uid, 0) + 1
            if rounds >= self.FOLD_EVICT_ROUNDS:
                self._uid_absent_rounds.pop(uid, None)
                for fk in [fk for fk in self._series_folded
                           if fk[0] == uid]:
                    del self._series_folded[fk]
            else:
                self._uid_absent_rounds[uid] = rounds
        for key, base in self._retained.items():
            if base:
                out[key] = out.get(key, 0.0) + base
        out.update(gauges)
        return out

    def merged_wire(self, snaps: List[Dict[str, Any]]
                    ) -> List[Dict[str, Any]]:
        """Cluster-merged wire metrics (tags preserved, procs summed) —
        the JSON payload behind /api/metrics `merged`. Stateless: reset
        retention only applies to the flat series from update()."""
        merged: Dict[Tuple[str, str], Dict[str, Any]] = {}
        hist_parts: Dict[Tuple[str, str], List[Dict[str, Any]]] = {}
        for snap in snaps:
            for m in snap.get("metrics", ()):
                for s in m["series"]:
                    gk = (m["name"],
                          _series_key(m["name"], s["tags"]))
                    rec = merged.setdefault(gk, {
                        "name": m["name"], "kind": m["kind"],
                        "description": m.get("description", ""),
                        "tags": s["tags"]})
                    if m["kind"] == "histogram":
                        hist_parts.setdefault(gk, []).append(
                            {"boundaries": m["boundaries"],
                             "buckets": s["buckets"], "sum": s["sum"],
                             "count": s["count"]})
                    else:
                        rec["value"] = rec.get("value", 0.0) + s["value"]
        for gk, parts in hist_parts.items():
            merged[gk].update(merge_histograms(parts))
        return list(merged.values())


# ---------------------------------------------------------------------
# History ring
# ---------------------------------------------------------------------


class SeriesHistory:
    """Bounded ring of (wall_ts, merged flat series) samples."""

    def __init__(self, max_samples: int) -> None:
        self._samples: "deque" = deque(maxlen=max(2, int(max_samples)))
        self._lock = threading.Lock()

    def append(self, ts: float, series: Dict[str, float]) -> None:
        with self._lock:
            self._samples.append((ts, series))

    def query(self, names: Optional[List[str]] = None,
              limit: Optional[int] = None) -> List[Tuple[float, Dict]]:
        with self._lock:
            samples = list(self._samples)
        if limit is not None:
            samples = samples[-limit:]
        if names:
            # prefix match so "ray_tpu_tasks" selects every tagged
            # variant of the family
            samples = [
                (ts, {k: v for k, v in sample.items()
                      if any(k.startswith(n) for n in names)})
                for ts, sample in samples]
        return samples


# ---------------------------------------------------------------------
# Watchdog
# ---------------------------------------------------------------------


class Watchdog:
    """Invariant probes over successive harvests. Each probe returns
    alert dicts {key, message, severity, **fields}; emission is
    cooldown-deduped per (probe, key) so a persistent violation alerts
    once per cooldown window, not once per harvest."""

    # minimum stuck window for the lease probe's backlog variant
    # (leaked slots WITH queued work) — must outlive the owner's NM
    # connection-retry transient, which holds a slot un-parked for up
    # to ~10s of backoff
    LEASE_BACKLOG_FLOOR_S = 15.0

    # minimum requests in a harvest window before the serve SLO probes
    # judge it — a p99 or error-rate over 1-2 requests is noise
    SERVE_MIN_REQUESTS = 5

    def __init__(self, emit: Callable[..., None],
                 cooldown_s: float, wait_edge_age_s: float,
                 store_occupancy_frac: float, queue_depth: int,
                 lock_hold_s: float = 5.0,
                 lock_waiters: int = 1,
                 serve_p99_s: float = 2.0,
                 serve_error_rate: float = 0.1,
                 serve_shed_rate: float = 0.5,
                 elastic_reconfig_s: float = 120.0,
                 gang_heartbeat_stale_s: float = 10.0,
                 jit_recompiles: int = 3,
                 jit_recompile_warmup_s: float = 60.0,
                 host_transfer_bytes: float = float(1 << 20),
                 goodput_floor: float = 0.5,
                 goodput_window_s: float = 120.0) -> None:
        self._emit = emit
        self.cooldown_s = cooldown_s
        self.wait_edge_age_s = wait_edge_age_s
        self.store_occupancy_frac = store_occupancy_frac
        self.queue_depth = queue_depth
        self.lock_hold_s = lock_hold_s
        self.lock_waiters = lock_waiters
        self.serve_p99_s = serve_p99_s
        self.serve_error_rate = serve_error_rate
        self.serve_shed_rate = serve_shed_rate
        self.elastic_reconfig_s = elastic_reconfig_s
        self.gang_heartbeat_stale_s = gang_heartbeat_stale_s
        self.jit_recompiles = jit_recompiles
        self.jit_recompile_warmup_s = jit_recompile_warmup_s
        self.host_transfer_bytes = host_transfer_bytes
        self.goodput_floor = goodput_floor
        self.goodput_window_s = goodput_window_s
        # goodput probe: job -> deque of (monotonic ts, {bucket: total})
        # snapshots spanning the sliding window
        self._goodput_window: Dict[str, "deque"] = {}
        # jax sentinel storm probe: step-region label -> monotonic ts
        # its first compile series appeared (warmup grace clock)
        self._jit_first_seen: Dict[str, float] = {}
        # serve SLO probes: last cumulative per-deployment request
        # histogram / per-(deployment, code) request counts (and shed
        # counts, for the shed-burn probe); the probe judges
        # per-harvest DELTAS so an old breach can't alert forever
        self._prev_serve_hist: Dict[str, Dict[str, Any]] = {}
        self._prev_serve_req: Dict[Tuple[str, str], float] = {}
        self._prev_serve_shed: Dict[str, float] = {}
        self._prev_serve_admitted: Dict[str, float] = {}
        self._last_alert: Dict[Tuple[str, str], float] = {}
        # lease probe: uid -> (leaked-slot count, monotonic ts it was
        # first seen stuck at that value)
        self._lease_stuck: Dict[str, Tuple[float, float]] = {}
        # memory-plane leak probes: (kind, node, oid) -> monotonic ts
        # first seen suspect (a suspect must survive a full harvest
        # interval before alerting — absence races are one-round long)
        self._mem_suspect: Dict[Tuple[str, str, str], float] = {}
        self._prev_series: Dict[str, float] = {}
        self.alerts_total = 0

    # -- helpers ------------------------------------------------------

    @staticmethod
    def _gauge(snap: Dict[str, Any], name: str) -> Optional[float]:
        for m in snap.get("metrics", ()):
            if m["name"] == name and m["series"]:
                return sum(s["value"] for s in m["series"])
        return None

    def _alert(self, probe: str, key: str, message: str,
               severity: str = "WARNING", **fields: Any) -> None:
        now = time.monotonic()
        last = self._last_alert.get((probe, key))
        if last is not None and now - last < self.cooldown_s:
            return
        self._last_alert[(probe, key)] = now
        # expired records no longer dedupe anything — drop them, or the
        # always-on GCS accrues one per (probe, proc-uid) ever alerted
        if len(self._last_alert) > 256:
            self._last_alert = {
                k: t for k, t in self._last_alert.items()
                if now - t < self.cooldown_s}
        self.alerts_total += 1
        logger.warning("watchdog %s: %s", probe, message)
        self._emit("HEALTH_ALERT", message, severity=severity,
                   probe=probe, series=key, **fields)

    # -- probes -------------------------------------------------------

    def _probe_lease_slots(self, snaps: List[Dict[str, Any]],
                           interval_s: float) -> None:
        """A proc holding lease request slots that are not parked at an
        NM awaiting a grant has leaked them — after
        MAX_PENDING_LEASE_REQUESTS leaks that key never schedules again
        (core_worker ~1203). Alerted in two variants: with an EMPTY
        queue after two harvest intervals (unambiguous — nothing is
        driving the slots), and with QUEUED work after a longer floor
        (the stalled-with-backlog case, worse for the user but
        transiently indistinguishable from an actively-placing
        request). A slot PARKED at a saturated NM with a drained queue
        is a legitimate steady state (the granted lease absorbed every
        queued task) and never alarms, however long the NM stays full.
        Stuck windows are wall-time, not round counts, so back-to-back
        forced harvests can't fake persistence."""
        window = 2.0 * max(interval_s, 0.05)
        # With queued work, in_flight > parked is ALSO the normal shape
        # of an actively-placing request (slot claimed, "queued" reply
        # pending) and of the NM connection-retry loop, which holds a
        # slot un-parked for up to ~10s (core_worker conn_failures x
        # 0.2s backoff) — so the backlog variant needs a floor long
        # enough to outlive both. It matters MORE than the empty-queue
        # one: leaked slots with tasks queued is a key starving user
        # work (once MAX_PENDING_LEASE_REQUESTS slots leak it never
        # requests again), and any churn — a grant, a park, a new
        # request — changes `leaked` and restarts the clock, so only a
        # genuinely frozen key rides out the floor.
        backlog_window = max(window, self.LEASE_BACKLOG_FLOOR_S)
        now = time.monotonic()
        seen = set()
        for snap in snaps:
            uid = snap["proc_uid"]
            in_flight = self._gauge(snap,
                                    "ray_tpu_lease_requests_in_flight")
            queued = self._gauge(snap, "ray_tpu_lease_queued_tasks")
            if in_flight is None or queued is None:
                continue
            parked = self._gauge(
                snap, "ray_tpu_lease_requests_parked") or 0.0
            seen.add(uid)
            leaked = in_flight - parked
            if leaked <= 0:
                self._lease_stuck.pop(uid, None)
                continue
            prev, since = self._lease_stuck.get(uid, (None, now))
            if prev != leaked:
                since = now
            self._lease_stuck[uid] = (leaked, since)
            if now - since < (window if queued == 0 else backlog_window):
                continue
            if queued == 0:
                msg = (f"{snap['proc']}: {leaked:g} lease request "
                       f"slot(s) held {now - since:.1f}s with no "
                       f"queued tasks and no request parked at a "
                       f"node manager — leaked requests_in_flight "
                       f"stalls that scheduling key permanently")
            else:
                msg = (f"{snap['proc']}: {leaked:g} lease request "
                       f"slot(s) held {now - since:.1f}s not parked "
                       f"at any node manager while {queued:g} task(s) "
                       f"sit queued — leaked slots are starving "
                       f"queued work of lease requests")
            self._alert("lease_slot_balance", uid, msg,
                        severity="ERROR", proc=snap["proc"],
                        node_id=snap.get("node_id"), value=leaked)
        for uid in list(self._lease_stuck):
            if uid not in seen:
                del self._lease_stuck[uid]

    def _probe_store_occupancy(self, snaps: List[Dict[str, Any]]) -> None:
        for snap in snaps:
            used = self._gauge(snap, "ray_tpu_object_store_used_bytes")
            cap = self._gauge(snap,
                              "ray_tpu_object_store_capacity_bytes")
            pinned = self._gauge(snap,
                                 "ray_tpu_object_store_pinned_bytes")
            if used is None or not cap:
                continue
            node = snap.get("node_id")
            if pinned is not None and pinned > used:
                self._alert(
                    "store_pin_accounting", snap["proc_uid"],
                    f"node {str(node)[:12]}: pinned bytes "
                    f"({pinned:g}) exceed used bytes ({used:g}) — "
                    f"pin/lease accounting leak", severity="ERROR",
                    node_id=node, value=pinned)
            elif used / cap > self.store_occupancy_frac:
                self._alert(
                    "store_occupancy", snap["proc_uid"],
                    f"node {str(node)[:12]}: object store "
                    f"{100.0 * used / cap:.0f}% full "
                    f"({used:g}/{cap:g} bytes; pinned {pinned or 0:g})",
                    node_id=node, value=used)

    def _probe_wait_edge_age(self, snaps: List[Dict[str, Any]]) -> None:
        for snap in snaps:
            age = self._gauge(snap,
                              "ray_tpu_wait_graph_max_edge_age_seconds")
            if age is not None and age > self.wait_edge_age_s:
                self._alert(
                    "wait_edge_age", "gcs",
                    f"oldest actor wait edge is {age:.0f}s old "
                    f"(> {self.wait_edge_age_s:g}s) — a blocking get "
                    f"may be stuck short of a detectable cycle",
                    value=age)

    # Task-event drops only: losing task events loses real cluster
    # state. ray_tpu_spans_dropped_total is deliberately NOT here —
    # the span ring is drop-oldest BY DESIGN (always-on recording
    # wraps in steady state), so its growth is normal operation and
    # alerting on it would train operators to ignore HEALTH_ALERTs.
    _DROP_COUNTERS = ("ray_tpu_task_events_dropped_total",)

    def _probe_drop_growth(self, series: Dict[str, float]) -> None:
        for name in self._DROP_COUNTERS:
            cur = series.get(name)
            prev = self._prev_series.get(name)
            if cur is not None and prev is not None and cur > prev:
                self._alert(
                    "drop_growth", name,
                    f"{name} grew by {cur - prev:g} since the last "
                    f"harvest (total {cur:g}) — telemetry is being "
                    f"shed under load", value=cur)

    def _probe_queue_depth(self, snaps: List[Dict[str, Any]]) -> None:
        for snap in snaps:
            depth = self._gauge(snap, "ray_tpu_executor_queue_depth")
            if depth is not None and depth > self.queue_depth:
                self._alert(
                    "executor_queue_depth", snap["proc_uid"],
                    f"{snap['proc']}: executor queue depth {depth:g} "
                    f"exceeds {self.queue_depth} — replica/actor is "
                    f"saturated and calls are piling up",
                    proc=snap["proc"], node_id=snap.get("node_id"),
                    value=depth)

    def _probe_memory(self, snaps: List[Dict[str, Any]],
                      interval_s: float,
                      unreachable: List[str]) -> None:
        """Memory-plane leak probes over the harvest's digests
        (memory_plane.py: each core worker ships what it claims holds
        objects alive; each node manager ships its store's held-alive
        entries). Three invariants:

          - every PINNED store object is claimed by a live owner
            (violation: the owner died without releasing — the classic
            leak `ray_tpu memory` exists for);
          - every store reader LEASE is accounted by a live process's
            replica-lease table (violation: a leased view leaked, the
            block can never be evicted);
          - an object the owner already FREED is not still store-
            resident (violation: refcount vs residency mismatch).

        A suspect must persist a full harvest interval before alerting
        (creation/free races are one-round long), so a real leak alerts
        within two harvest intervals. Absence of a claim is only
        evidence when coverage was complete, so skipped rounds —
        unreachable nodes, truncated/capped digests, or a node whose
        harvest carried fewer worker digests than its node manager has
        registered workers (one stalled worker must not read as a dead
        owner) — also RESET the suspect clocks rather than letting
        them age through unverified rounds."""
        from ray_tpu._private import memory_plane as memory_plane_lib
        if unreachable:
            self._mem_suspect.clear()
            return
        claimed: set = set()
        freed: set = set()
        # reader-lease claims are per NODE: a proc's replica leases are
        # held on its OWN node's store, and a cluster-wide sum would
        # let a legitimate lease on node B mask a leaked one on node A
        leases_claimed: Dict[Tuple[str, str], int] = {}
        workers_digested: Dict[str, int] = {}
        digests = 0
        for snap in snaps:
            mem = snap.get(memory_plane_lib.PROC_DIGEST_KEY)
            if not mem:
                continue
            digests += 1
            if mem.get("dropped"):
                # capped digest: absence proves nothing this round, and
                # suspect clocks must not age through it
                self._mem_suspect.clear()
                return
            node = str(snap.get("node_id") or "?")
            if mem.get("kind") == "worker":
                workers_digested[node] = workers_digested.get(node, 0) + 1
            claimed.update(mem.get("owned_store") or ())
            freed.update(mem.get("freed") or ())
            for oid, n in (mem.get("leases") or {}).items():
                leases_claimed[(node, oid)] = \
                    leases_claimed.get((node, oid), 0) + n
        if not digests:
            return
        window = max(interval_s, 0.05)
        now = time.monotonic()
        seen: set = set()

        def suspect(kind: str, node: str, oid: str) -> bool:
            """True once the suspect has persisted a full interval."""
            key = (kind, node, oid)
            seen.add(key)
            first = self._mem_suspect.setdefault(key, now)
            return now - first >= window

        for snap in snaps:
            store = snap.get(memory_plane_lib.STORE_DIGEST_KEY)
            if not store or store.get("truncated"):
                continue
            node = str(snap.get("node_id") or "?")
            expected = store.get("registered_workers")
            if expected is not None and \
                    workers_digested.get(node, 0) < expected:
                # a registered worker on this node missed the harvest
                # (slow GIL-bound pull, spawn race): its claims are
                # unknown, so absence-based checks would false-alarm —
                # skip the node and restart its suspect clocks
                for key in [k for k in self._mem_suspect
                            if k[1] == node]:
                    del self._mem_suspect[key]
                continue
            for oid, size, pinned, leases, _spilled, age_s in \
                    store.get("entries") or ():
                young = age_s is not None and age_s < window
                if oid in freed and not young:
                    if suspect("freed_resident", node, oid):
                        self._alert(
                            "store_residency_mismatch", f"{node}:{oid}",
                            f"node {node[:12]}: object {oid[:16]} "
                            f"({size or 0} bytes) is still store-"
                            f"resident after its owner freed it — "
                            f"refcount vs residency mismatch",
                            severity="ERROR", node_id=node,
                            object_id=oid, value=float(size or 0))
                    continue
                if (pinned or 0) > 0 and oid not in claimed \
                        and not young:
                    if suspect("dead_owner", node, oid):
                        self._alert(
                            "store_leak_dead_owner", f"{node}:{oid}",
                            f"node {node[:12]}: object {oid[:16]} "
                            f"({size or 0} bytes) is pinned in the "
                            f"store but no live owner claims it — "
                            f"likely leaked by a dead owner; it will "
                            f"never be freed",
                            severity="ERROR", node_id=node,
                            object_id=oid, value=float(size or 0))
                node_claims = leases_claimed.get((node, oid), 0)
                if (leases or 0) > node_claims and not young:
                    if suspect("orphan_lease", node, oid):
                        self._alert(
                            "store_orphaned_lease", f"{node}:{oid}",
                            f"node {node[:12]}: object {oid[:16]} "
                            f"holds {leases} reader lease(s) but live "
                            f"processes on that node account for "
                            f"{node_claims} — leaked leases make the "
                            f"block unevictable",
                            node_id=node, object_id=oid,
                            value=float(leases or 0))
        # forget suspects that resolved (freed, claimed, or released)
        for key in list(self._mem_suspect):
            if key not in seen:
                del self._mem_suspect[key]

    def _probe_locks(self, snaps: List[Dict[str, Any]]) -> None:
        """Lockdep probes over the traced-lock digests riding the
        harvest (util/locks.py digest()): per-process, (1) a cycle in
        the observed acquisition-order graph — two code paths took the
        same locks in opposite orders, a deadlock that merely hasn't
        fired yet (the order is the bug, lockdep semantics); (2) a
        lock held past the configured threshold while threads queue
        behind it — a stalled critical section starving the process.
        Edges accumulate for the process's lifetime, so an inversion
        alerts within the next harvest interval and the cooldown
        dedupes the repeats."""
        from ray_tpu.util import locks as locks_lib
        for snap in snaps:
            d = snap.get(locks_lib.DIGEST_KEY)
            if not d:
                continue
            # the digest pre-computes the cycle over its process's FULL
            # edge graph (the shipped edge list is capped); fall back
            # to detecting over the shipped edges for older digests
            cycle = d.get("cycle") or locks_lib.find_cycle(
                (a, b) for a, b in d.get("edges", ()))
            if cycle:
                path = " -> ".join(cycle)
                self._alert(
                    "lock_order_inversion",
                    f"{snap['proc_uid']}:{path}",
                    f"{snap['proc']}: observed lock acquisition orders "
                    f"form a cycle {path} — threads interleaving these "
                    f"paths deadlock; pick one global order (static "
                    f"twin: graftlint RT016)", severity="ERROR",
                    proc=snap["proc"], node_id=snap.get("node_id"))
            for lh in d.get("long_holds", ()):
                if lh.get("held_s", 0.0) >= self.lock_hold_s and \
                        lh.get("waiters", 0) >= self.lock_waiters:
                    self._alert(
                        "lock_long_hold",
                        f"{snap['proc_uid']}:{lh['name']}",
                        f"{snap['proc']}: lock {lh['name']!r} held "
                        f"{lh['held_s']:.1f}s (> {self.lock_hold_s:g}s) "
                        f"with {lh['waiters']} thread(s) queued — a "
                        f"stalled critical section is starving this "
                        f"process", proc=snap["proc"],
                        node_id=snap.get("node_id"),
                        value=lh["held_s"])

    def _probe_serve_slo(self, snaps: List[Dict[str, Any]]) -> None:
        """Serve SLO probes over the harvested RED metrics (serve/
        _telemetry.py): per deployment and per harvest window,

          - ``serve_latency_slo``: the p99 upper bound from this
            round's request-histogram DELTA (cumulative buckets diffed
            against the previous round, merged across processes) over
            `serve_p99_s`;
          - ``serve_error_burn``: the 5xx fraction of this round's
            request-count delta over `serve_error_rate` (4xx are the
            client's errors and don't burn the budget).

        Windows with fewer than SERVE_MIN_REQUESTS requests are
        skipped, as are rounds whose deltas go negative (proxy/handle
        churn reset a counter — judging them would fabricate traffic).
        A sustained breach alerts within two harvest intervals (one
        round to baseline, one to judge) and the cooldown dedupes the
        repeats."""
        hist_parts: Dict[str, List[Dict[str, Any]]] = {}
        req: Dict[Tuple[str, str], float] = {}
        for snap in snaps:
            for m in snap.get("metrics", ()):
                if m["name"] == "ray_tpu_serve_request_seconds" \
                        and m["kind"] == "histogram":
                    for s in m["series"]:
                        dep = s["tags"].get("deployment", "?")
                        hist_parts.setdefault(dep, []).append(
                            {"boundaries": m["boundaries"],
                             "buckets": s["buckets"],
                             "sum": s["sum"], "count": s["count"]})
                elif m["name"] == "ray_tpu_serve_requests_total":
                    for s in m["series"]:
                        key = (s["tags"].get("deployment", "?"),
                               s["tags"].get("code", "?"))
                        req[key] = req.get(key, 0.0) + s["value"]
        # prune deployments gone from the harvest — the always-on GCS
        # must stay bounded under deployment churn (a returning
        # deployment just pays one fresh baseline round)
        for dep in [d for d in self._prev_serve_hist
                    if d not in hist_parts]:
            del self._prev_serve_hist[dep]
        # latency SLO from histogram deltas
        for dep, parts in hist_parts.items():
            cur = merge_histograms(parts)
            prev = self._prev_serve_hist.get(dep)
            self._prev_serve_hist[dep] = cur
            if prev is None or prev["boundaries"] != cur["boundaries"]:
                continue
            delta = [c - p for c, p in zip(cur["buckets"],
                                           prev["buckets"])]
            total = cur["count"] - prev["count"]
            if total < self.SERVE_MIN_REQUESTS or \
                    any(d < 0 for d in delta):
                continue
            target = 0.99 * total
            cum = 0
            p99_edge: Optional[float] = None  # None = overflow bucket
            for bound, d in zip(cur["boundaries"], delta):
                cum += d
                if cum >= target:
                    p99_edge = bound
                    break
            top = cur["boundaries"][-1]
            if p99_edge is not None and p99_edge <= self.serve_p99_s:
                continue
            shown = p99_edge if p99_edge is not None else top
            self._alert(
                "serve_latency_slo", dep,
                f"deployment {dep!r}: p99 request latency "
                f"{'>' if p99_edge is None else '<='} {shown:g}s over "
                f"the last harvest window ({total:g} requests) exceeds "
                f"the {self.serve_p99_s:g}s SLO",
                deployment=dep, value=float(shown))
        # error burn from request-count deltas. Deltas are judged
        # per KEY against the previous round; a key absent from prev —
        # first appearance, or a vanish/reappear across an unreachable
        # round — is BASELINED, not judged, exactly like the histogram
        # probe (else a reappearing counter's full cumulative history
        # reads as one window and fires a false ERROR from old traffic)
        deltas: Dict[str, Dict[str, float]] = {}
        ok = True
        for key, v in req.items():
            prev_v = self._prev_serve_req.get(key)
            if prev_v is None:
                continue  # baseline round for this key
            d = v - prev_v
            if d < 0:
                ok = False  # counter churn: skip the whole round
                break
            dep, code = key
            # 503 = admission shed (Retry-After contract): an overload
            # signal with its own probe (serve_shed_burn), not an error
            # burning the availability budget — excluded from BOTH
            # numerator and denominator (errors judged against
            # ADMITTED traffic; a brownout must not dilute a real 5xx
            # burn happening underneath it). The only 503 source in
            # this stack is the ingress admission plane.
            if code == "503":
                continue
            rec = deltas.setdefault(dep, {"total": 0.0, "errors": 0.0})
            rec["total"] += d
            if code.startswith("5"):
                rec["errors"] += d
        self._prev_serve_req = req
        if not ok:
            return
        for dep, rec in deltas.items():
            if rec["total"] < self.SERVE_MIN_REQUESTS:
                continue
            rate = rec["errors"] / rec["total"]
            if rate > self.serve_error_rate:
                self._alert(
                    "serve_error_burn", dep,
                    f"deployment {dep!r}: {rec['errors']:g} of "
                    f"{rec['total']:g} requests ({100 * rate:.0f}%) "
                    f"failed with 5xx over the last harvest window "
                    f"(error-rate SLO {100 * self.serve_error_rate:.0f}"
                    f"%)", severity="ERROR", deployment=dep,
                    value=rate)

    def _probe_serve_shed(self, snaps: List[Dict[str, Any]]) -> None:
        """``serve_shed_burn``: sustained load shedding at the ingress
        fleet. Judges per-harvest DELTAS of
        ``ray_tpu_serve_shed_total`` against the same window's total
        offered load (admitted ``requests_total`` + shed): a shed
        fraction above `serve_shed_rate` means clients are being
        browned out faster than the Retry-After contract can absorb —
        scale the deployment (or raise its admission limits) before
        goodput collapses. First-appearance keys baseline like the
        other serve probes; windows under SERVE_MIN_REQUESTS offered
        requests are noise and skipped."""
        shed: Dict[str, float] = {}
        admitted: Dict[str, float] = {}
        for snap in snaps:
            for m in snap.get("metrics", ()):
                if m["name"] == "ray_tpu_serve_shed_total":
                    for s in m["series"]:
                        dep = s["tags"].get("deployment", "?")
                        shed[dep] = shed.get(dep, 0.0) + s["value"]
                elif m["name"] == "ray_tpu_serve_requests_total":
                    for s in m["series"]:
                        dep = s["tags"].get("deployment", "?")
                        admitted[dep] = admitted.get(dep, 0.0) \
                            + s["value"]
        prev_shed, self._prev_serve_shed = self._prev_serve_shed, shed
        prev_req = self._prev_serve_admitted
        self._prev_serve_admitted = dict(admitted)
        for dep, shed_now in shed.items():
            shed_before = prev_shed.get(dep)
            if shed_before is None:
                continue  # baseline round for this deployment
            d_shed = shed_now - shed_before
            d_req = admitted.get(dep, 0.0) - prev_req.get(dep, 0.0)
            if d_shed < 0 or d_req < 0:
                continue  # proxy churn reset a counter: re-baseline
            # requests_total ALREADY includes sheds (they respond 503
            # at the proxy, where the counter lives) — offered load is
            # d_req itself; the max() only guards a legacy proxy that
            # sheds without counting
            offered = max(d_req, d_shed)
            if offered < self.SERVE_MIN_REQUESTS or d_shed <= 0:
                continue
            rate = d_shed / offered
            if rate > self.serve_shed_rate:
                self._alert(
                    "serve_shed_burn", dep,
                    f"deployment {dep!r}: ingress shed {d_shed:g} of "
                    f"{offered:g} offered requests ({100 * rate:.0f}%) "
                    f"over the last harvest window (shed-rate SLO "
                    f"{100 * self.serve_shed_rate:.0f}%) — sustained "
                    f"overload; scale the deployment or raise its "
                    f"admission limits", severity="ERROR",
                    deployment=dep, value=rate)

    def _probe_elastic(self, snaps: List[Dict[str, Any]]) -> None:
        """elastic_stuck_reconfig: a gang reconfiguration
        (train/elastic.py ReconfigTracker, riding the harvest as
        `elastic:*` snapshot extras) has been in flight longer than
        elastic_reconfig_s. The age is computed in the snapshot from
        the owner's monotonic clock, so a single observation above the
        threshold is already a sustained stall — no cross-interval
        state needed; the cooldown dedupes repeats."""
        for snap in snaps:
            for key, extra in snap.items():
                if not key.startswith("elastic:") or \
                        not isinstance(extra, dict):
                    continue
                if not extra.get("in_progress"):
                    continue
                age = float(extra.get("age_s", 0.0))
                if age <= self.elastic_reconfig_s:
                    continue
                gang = extra.get("gang", key)
                # dedup on the per-INSTANCE extra key, not the gang
                # name: two same-named gangs in one driver must not
                # share a cooldown (one stuck gang would mute the
                # other's alert)
                self._alert(
                    "elastic_stuck_reconfig",
                    f"{snap.get('proc_uid', '')}:{key}",
                    f"elastic gang {gang!r} on {snap.get('proc', '?')} "
                    f"(pid {snap.get('pid', '?')}) stuck in "
                    f"reconfiguration phase "
                    f"{extra.get('phase', '?')!r} for {age:.0f}s "
                    f"(> {self.elastic_reconfig_s:.0f}s; reason="
                    f"{extra.get('reason', '?')})",
                    severity="ERROR", gang=gang,
                    phase=extra.get("phase"), age_s=age)

    @staticmethod
    def _series_tags(key: str) -> Dict[str, str]:
        """Tags of a flat series key (`name{k=v,...}`). Sentinel labels
        are span/region names (no commas or braces), so plain splitting
        is exact for the series this parser is used on."""
        i = key.find("{")
        if i < 0 or not key.endswith("}"):
            return {}
        out: Dict[str, str] = {}
        for part in key[i + 1:-1].split(","):
            if "=" in part:
                k, v = part.split("=", 1)
                out[k] = v
        return out

    def _probe_gang_wedge(self, series: Dict[str, float]) -> None:
        """`gang_rank_wedged`: a rank's heartbeat age
        (`ray_tpu_gang_heartbeat_age_seconds{gang,rank}`, exported by
        the GCS from its gang heartbeat table each harvest) exceeds
        gang_heartbeat_stale_s. The sidecar beats every ~0.5s even
        while the rank's main thread sits inside a collective, so an
        age this large means the PROCESS is stopped — SIGSTOP'd, hard
        GIL stall, frozen host — not a slow step. The age is an
        absolute value from the GCS monotonic clock (no cross-interval
        delta needed), so the alert lands within the harvest interval
        that first observes the breach; the cooldown dedupes repeats
        while the gang supervisor's step-deadline trip tears the rank
        down. Fed from TWO cadences with identical series keys: the
        harvested gauge here in evaluate(), and the plane's liveness
        tick reading the GCS table directly — the latter because a
        wedged worker stalls the harvest fan-out for the full worker
        snapshot timeout, exactly the window this probe must fire in."""
        for key, v in series.items():
            if not key.startswith(
                    "ray_tpu_gang_heartbeat_age_seconds{"):
                continue
            if v <= self.gang_heartbeat_stale_s:
                continue
            tags = self._series_tags(key)
            gang = tags.get("gang", "?")
            rank = tags.get("rank", "?")
            self._alert(
                "gang_rank_wedged", key,
                f"gang {gang!r} rank {rank}: no heartbeat for "
                f"{v:.1f}s (> {self.gang_heartbeat_stale_s:.0f}s) — "
                f"the rank process is wedged (SIGSTOP, hard stall, or "
                f"frozen host), not merely slow; the gang supervisor's "
                f"step deadline will hard-kill it and re-form the gang "
                f"(reason=wedge)", severity="ERROR",
                gang=gang, rank=rank, value=v)

    def _probe_replay_stall(self, series: Dict[str, float]) -> None:
        """`replay_shard_stall`: a replay shard with un-acked pushes
        outstanding (`ray_tpu_replay_push_inflight{shard}` > 0) whose
        add counter (`ray_tpu_replay_added_total{shard}`) did not move
        since the previous harvest is absorbing pushes without applying
        them — a wedged or overloaded shard actor. Writers keep shedding
        against its full inflight window, so the symptom the trainer
        sees is silent sample loss, not an error. First-appearance
        series baseline (prev None), so a stalled shard alerts within
        two harvest intervals."""
        for key, inflight in series.items():
            if not key.startswith("ray_tpu_replay_push_inflight{"):
                continue
            if inflight <= 0:
                continue
            shard = self._series_tags(key).get("shard", "?")
            added_key = f"ray_tpu_replay_added_total{{shard={shard}}}"
            cur = series.get(added_key)
            prev = self._prev_series.get(added_key)
            if cur is None or prev is None:
                continue  # baseline round for this shard
            if cur <= prev:
                self._alert(
                    "replay_shard_stall", key,
                    f"replay shard {shard}: {inflight:g} pushes in "
                    f"flight but added_total did not move this harvest "
                    f"(stuck at {cur:g}) — the shard actor is wedged "
                    f"or overloaded and writers are shedding against "
                    f"its full window", shard=shard, value=inflight)

    def _probe_jax_sentinel(self, series: Dict[str, float]) -> None:
        """`jit_recompile_storm` / `unexpected_host_transfer`: per-
        harvest deltas of the jax sentinel's counters
        (util/jax_sentinel.py; static twins graftlint RT020/RT021).

          - a step-region label whose kind=recompile compile count
            grows by >= jit_recompiles within one window is recompiling
            in steady state — a shape/static-arg/donation hazard is
            making XLA rebuild the step it should be replaying. Labels
            get a warmup grace (jit_recompile_warmup_s from their first
            compile): a cold start legitimately compiles several
            modules under one label across a couple of windows.
          - host-transfer bytes accounted INSIDE a step region growing
            by >= host_transfer_bytes per window mean the hot step is
            forcing device→host syncs it shouldn't (the sanctioned
            forcing points live outside the regions).

        region="untracked"/fn="untracked" series are never judged —
        outside a step region a transfer or compile is by definition
        not on a hot path. First-appearance series BASELINE (prev round
        None), so a real storm alerts within two harvest intervals."""
        now = time.monotonic()
        fns_seen = set()
        for key in series:
            if key.startswith("ray_tpu_jit_compiles_total{"):
                fn = self._series_tags(key).get("fn")
                if fn:
                    fns_seen.add(fn)
                    self._jit_first_seen.setdefault(fn, now)
        # labels gone from the harvest drop their warmup clocks — the
        # always-on GCS stays bounded under driver churn (a returning
        # label just re-enters warmup grace)
        for fn in [f for f in self._jit_first_seen
                   if f not in fns_seen]:
            del self._jit_first_seen[fn]
        for key, v in series.items():
            prev = self._prev_series.get(key)
            if prev is None:
                continue  # baseline round for this series
            delta = v - prev
            if delta <= 0:
                continue
            if key.startswith("ray_tpu_jit_compiles_total{"):
                tags = self._series_tags(key)
                fn = tags.get("fn", "?")
                if tags.get("kind") != "recompile" \
                        or fn == "untracked":
                    continue
                first = self._jit_first_seen.get(fn, now)
                if now - first < self.jit_recompile_warmup_s \
                        or delta < self.jit_recompiles:
                    continue
                self._alert(
                    "jit_recompile_storm", key,
                    f"step region {fn!r}: {delta:g} XLA recompile(s) "
                    f"within one harvest window (total {v:g}) — the "
                    f"step is recompiling in steady state instead of "
                    f"replaying its cache; look for shape-varying "
                    f"args, python scalars traced as constants, or "
                    f"donation retriggers (static twin: graftlint "
                    f"RT020)", severity="ERROR", fn=fn, value=delta)
            elif key.startswith("ray_tpu_host_transfer_bytes_total{"):
                region = self._series_tags(key).get("region", "?")
                if region == "untracked":
                    continue
                if delta < self.host_transfer_bytes:
                    continue
                self._alert(
                    "unexpected_host_transfer", key,
                    f"step region {region!r}: {delta:g} bytes forced "
                    f"device→host within one harvest window "
                    f"(> {self.host_transfer_bytes:g}) — a hidden "
                    f".item()/np coercion/device_get is syncing the "
                    f"hot step (static twin: graftlint RT021; spans: "
                    f"host_sync.* in `ray_tpu timeline --spans`)",
                    severity="ERROR", region=region, value=delta)

    def _probe_goodput(self, series: Dict[str, float],
                       interval_s: float) -> None:
        """`goodput_regression`: a job's productive_step fraction of
        its accounted wall time over the sliding window
        (goodput_window_s) dropped below goodput_floor — the gang is
        alive but its time is going somewhere other than training.
        Judged from per-window DELTAS of the harvested
        `ray_tpu_goodput_seconds_total{job,bucket}` counters (so an old
        bad patch can't alert forever), and the alert names the
        DOMINANT badput bucket — the triage pointer: feed_stall means
        starve the sampler less, elastic_reconfig/wedge_recovery means
        churn, compile means a recompile hazard (see the
        jit_recompile_storm probe), idle means unattributed driver
        time (graftlint RT024's territory). Windows where the job was
        live for under half the wall time are skipped — a ledger that
        just appeared (or a paused harvest) must not read as badput."""
        now = time.monotonic()
        prefix = "ray_tpu_goodput_seconds_total{"
        totals: Dict[str, Dict[str, float]] = {}
        for key, v in series.items():
            if not key.startswith(prefix):
                continue
            tags = self._series_tags(key)
            job = tags.get("job")
            bucket = tags.get("bucket")
            if job and bucket:
                totals.setdefault(job, {})[bucket] = v
        # evict jobs gone from the harvest (ledger's proc died); a
        # returning job pays one fresh baseline window
        for job in [j for j in self._goodput_window if j not in totals]:
            del self._goodput_window[job]
        window = max(self.goodput_window_s, 0.0)
        for job, cur in totals.items():
            dq = self._goodput_window.setdefault(job, deque())
            dq.append((now, cur))
            # keep one entry at-or-past the window edge as the baseline
            while len(dq) >= 3 and now - dq[1][0] >= window:
                dq.popleft()
            if len(dq) < 2:
                continue  # baseline round for this job
            t0, base = dq[0]
            wall = now - t0
            if wall <= 0:
                continue
            deltas = {b: max(0.0, cur.get(b, 0.0) - base.get(b, 0.0))
                      for b in set(cur) | set(base)}
            accounted = sum(deltas.values())
            if accounted < 0.5 * wall:
                continue  # job not live for most of the window
            productive = deltas.get("productive_step", 0.0)
            frac = productive / accounted
            if frac >= self.goodput_floor:
                continue
            badput = {b: d for b, d in deltas.items()
                      if b != "productive_step" and d > 0}
            dominant, dom_s = max(
                badput.items(), key=lambda kv: kv[1],
                default=("idle", 0.0))
            self._alert(
                "goodput_regression", job,
                f"job {job!r}: productive fraction "
                f"{100.0 * frac:.0f}% over the last {wall:.0f}s is "
                f"below the {100.0 * self.goodput_floor:.0f}% floor — "
                f"dominant badput bucket is {dominant!r} "
                f"({dom_s:.1f}s of {accounted:.1f}s accounted); see "
                f"`ray_tpu goodput --job {job}`", severity="ERROR",
                job=job, value=frac, dominant=dominant)

    def _probe_harvest_coverage(self, unreachable: List[str]) -> None:
        for node in unreachable:
            self._alert(
                "harvest_unreachable", node,
                f"metrics harvest could not reach node "
                f"{node[:12]} — its series are stale this round",
                node_id=node)

    def evaluate(self, snaps: List[Dict[str, Any]],
                 series: Dict[str, float],
                 unreachable_nodes: List[str],
                 interval_s: float = 2.0) -> None:
        for probe in (lambda: self._probe_lease_slots(snaps, interval_s),
                      lambda: self._probe_store_occupancy(snaps),
                      lambda: self._probe_wait_edge_age(snaps),
                      lambda: self._probe_drop_growth(series),
                      lambda: self._probe_queue_depth(snaps),
                      lambda: self._probe_memory(snaps, interval_s,
                                                 unreachable_nodes),
                      lambda: self._probe_locks(snaps),
                      lambda: self._probe_serve_slo(snaps),
                      lambda: self._probe_serve_shed(snaps),
                      lambda: self._probe_elastic(snaps),
                      lambda: self._probe_gang_wedge(series),
                      lambda: self._probe_jax_sentinel(series),
                      lambda: self._probe_replay_stall(series),
                      lambda: self._probe_goodput(series, interval_s),
                      lambda: self._probe_harvest_coverage(
                          unreachable_nodes)):
            try:
                probe()
            except Exception:  # noqa: BLE001 - one broken probe must
                logger.exception("watchdog probe failed")  # not kill the rest
        self._prev_series = series


# ---------------------------------------------------------------------
# GCS-hosted plane
# ---------------------------------------------------------------------


class MetricsPlane:
    """Owns the sampler thread, harvest fan-out, aggregator, history
    ring, and watchdog. Hosted by the GcsServer; its RPC surface is
    registered there (metrics_collect / metrics_prometheus /
    metrics_history / metrics_merged / metrics_configure)."""

    COLLECT_TIMEOUT_S = 5.0

    def __init__(self, gcs: Any,
                 history_dir: Optional[str] = None) -> None:
        from ray_tpu._private.config import Config
        from ray_tpu._private.metrics_history import TieredHistory
        from ray_tpu.util.metrics import (Gauge, Histogram,
                                          get_or_create)
        self._gcs = gcs
        self.interval_s = Config.metrics_sample_interval_s
        self.history = TieredHistory(
            Config.metrics_history_samples,
            dir=Config.metrics_history_dir or history_dir or None,
            retention_bytes=Config.metrics_history_retention_bytes,
            segment_samples=Config.metrics_history_segment_samples)
        self.aggregator = ClusterAggregator()
        self.watchdog = Watchdog(
            emit=gcs._emit,
            cooldown_s=Config.watchdog_cooldown_s,
            wait_edge_age_s=Config.watchdog_wait_edge_age_s,
            store_occupancy_frac=Config.watchdog_store_occupancy_frac,
            queue_depth=Config.watchdog_queue_depth,
            lock_hold_s=Config.watchdog_lock_hold_s,
            lock_waiters=Config.watchdog_lock_waiters,
            serve_p99_s=Config.watchdog_serve_p99_s,
            serve_error_rate=Config.watchdog_serve_error_rate,
            serve_shed_rate=Config.watchdog_serve_shed_rate,
            elastic_reconfig_s=Config.watchdog_elastic_reconfig_s,
            gang_heartbeat_stale_s=Config.watchdog_gang_heartbeat_s,
            jit_recompiles=Config.watchdog_jit_recompiles,
            jit_recompile_warmup_s=(
                Config.watchdog_jit_recompile_warmup_s),
            host_transfer_bytes=Config.watchdog_host_transfer_bytes,
            goodput_floor=Config.watchdog_goodput_floor,
            goodput_window_s=Config.watchdog_goodput_window_s)
        self._harvest_hist = get_or_create(
            Histogram, "ray_tpu_metrics_harvest_seconds",
            description="wall time of one cluster metrics harvest "
                        "(fan-out + merge + watchdog)",
            boundaries=[0.001, 0.005, 0.02, 0.1, 0.5, 2.0])
        self._procs_gauge = get_or_create(
            Gauge, "ray_tpu_metrics_harvest_procs",
            description="processes covered by the last metrics harvest")
        # Runtime step-deadline override for gang supervisors
        # (metrics_configure(step_deadline_s=...)): the GCS hands it
        # back on every gang_heartbeats query, so the wedge deadline is
        # tunable live without touching the trainer. None = defer to
        # ScalingConfig.step_deadline_s / auto-calibration.
        self.step_deadline_override_s: Optional[float] = None
        self._lock = TracedLock("metrics_plane")
        # serializes full rounds: the sampler loop and on-demand callers
        # (scrapes, dumps) never harvest concurrently
        self._round_lock = TracedLock("metrics_round")
        self._last_snaps: List[Dict[str, Any]] = []
        self._last_series: Dict[str, float] = {}
        self._last_harvest_mono = 0.0
        self._last_history_mono = 0.0
        self._wake = threading.Event()
        self._stopped = False
        self._thread = threading.Thread(target=self._sample_loop,
                                        daemon=True, name="gcs-metrics")
        self._thread.start()
        # Liveness tick: the gang-wedge probe on its own short cadence,
        # fed straight from the GCS heartbeat table. It must not ride
        # the harvest — a wedged (SIGSTOP'd) worker stalls the fan-out
        # for the full worker-pull timeout, which is exactly when the
        # probe needs to fire (and the gang supervisor's trip clears
        # the table moments later).
        self._liveness_wake = threading.Event()
        self._liveness_thread = threading.Thread(
            target=self._liveness_loop, daemon=True,
            name="gcs-metrics-liveness")
        self._liveness_thread.start()

    # -- liveness tick ------------------------------------------------

    def _liveness_loop(self) -> None:
        """Evaluate the gang-wedge probe against LIVE heartbeat ages on
        a cadence independent of harvest latency. The harvested-gauge
        path in Watchdog.evaluate still runs (the alert cooldown keys
        are identical, so the two cadences dedupe); this loop exists so
        the alert SLO (<= 2 harvest intervals after staleness) holds
        even while the harvest itself is stalled behind the wedged
        rank's snapshot pull."""
        while not self._stopped:
            period = self.interval_s if self.interval_s > 0 else 1.0
            self._liveness_wake.wait(
                timeout=min(1.0, max(0.25, period)))
            self._liveness_wake.clear()
            if self._stopped:
                return
            try:
                ages = self._gcs.gang_heartbeat_age_series()
                if ages:
                    self.watchdog._probe_gang_wedge(ages)
            except Exception:  # noqa: BLE001 - probe tick must not die
                logger.exception("gang liveness probe tick failed")

    # -- harvest fan-out ----------------------------------------------

    def collect(self) -> List[Dict[str, Any]]:
        """The `metrics_collect` RPC: an explicit harvest-NOW — callers
        asking for this want a guaranteed-fresh gather (tests inducing a
        state then asserting on the snapshot; operators debugging)."""
        return self._run_round(force=True)

    def _harvest(self) -> Tuple[List[Dict[str, Any]], List[str]]:
        """Two-phase gather mirroring gcs.spans_collect: node managers
        first (each ships its own + its workers' snapshots and names the
        worker addresses it covered), then the remaining pubsub
        subscribers — drivers, and workers whose NM dropped out."""
        from ray_tpu._private import spans as spans_lib
        own = snapshot_process()
        nm_replies, cw_replies, unreachable = \
            spans_lib.gather_cluster_snapshots(
                self._gcs, "nm_metrics_snapshot", "cw_metrics_snapshot",
                timeout=self.COLLECT_TIMEOUT_S)
        gathered: List[Dict[str, Any]] = []
        for _addr, reply, _t0, _t1 in nm_replies:
            gathered.extend(reply["snapshots"])
        gathered.extend(snap for _a, snap, _t0, _t1 in cw_replies)
        return spans_lib.dedupe_by_uid([own] + gathered), unreachable

    def _run_round(self, force: bool = False) -> List[Dict[str, Any]]:
        """One full round — fan-out, aggregate, history sample, watchdog
        — shared by the sampler loop and on-demand callers (/metrics
        scrapes, dumps; with interval 0 the plane runs PURELY on demand,
        and every scrape still advances the aggregator/history/watchdog
        state). A non-forced caller arriving while the last round is
        fresh gets its cached snapshots instead of re-fanning out —
        and never stalls behind an in-progress harvest (which can hold
        _round_lock for the full collect timeout when a node is
        unreachable): if the cache is stale because a slow round is
        mid-flight, the scrape gets the last COMPLETED round rather
        than blocking, so /metrics stays responsive exactly when a
        node outage makes rounds slow."""
        freshness = max(self.interval_s, 1.0)

        def _cached():
            with self._lock:
                age = time.monotonic() - self._last_harvest_mono
                snaps = self._last_snaps
            return snaps if snaps and age < freshness else None

        if not force:
            snaps = _cached()
            if snaps is not None:
                return snaps
            # cache stale AND a round in progress (it holds _round_lock
            # for up to two collect timeouts when a node is down): a
            # scrape must not stall behind the fan-out — serve the last
            # COMPLETED round, however stale, and let the in-progress
            # one refresh the cache for the next caller. Only when no
            # round ever completed (cold start) is waiting the better
            # trade.
            if not self._round_lock.acquire(blocking=False):
                with self._lock:
                    stale = self._last_snaps
                if stale:
                    return stale
                self._round_lock.acquire()
        else:
            self._round_lock.acquire()
        try:
            if not force:
                # a round finished while we waited for the lock
                snaps = _cached()
                if snaps is not None:
                    return snaps
            t0 = time.monotonic()
            snaps, unreachable = self._harvest()
            series = self.aggregator.update(snaps)
            # the ring's retention contract is samples x interval_s of
            # NON-forced samples: rounds forced between sampler ticks
            # (collects, dumps) land in the raw tier tagged forced=True
            # — visible to sparklines (no gaps), excluded from rate
            # computation and from the retention count — instead of
            # being dropped outright as they were pre-PR-20
            due = (self.interval_s <= 0
                   or t0 - self._last_history_mono
                   >= 0.9 * self.interval_s)
            kinds = {m["name"]: m["kind"]
                     for snap in snaps
                     for m in snap.get("metrics", ())}
            self.history.append(time.time(), series, kinds=kinds,
                                forced=not due)
            if due:
                self._last_history_mono = t0
            self.watchdog.evaluate(snaps, series, unreachable,
                                   interval_s=self.interval_s)
            self._procs_gauge.set(float(len(snaps)))
            self._harvest_hist.observe(time.monotonic() - t0)
            with self._lock:
                self._last_snaps = snaps
                self._last_series = series
                self._last_harvest_mono = time.monotonic()
            return snaps
        finally:
            self._round_lock.release()

    # -- sampler loop -------------------------------------------------

    def _sample_loop(self) -> None:
        while not self._stopped:
            interval = self.interval_s
            if interval <= 0:
                self._wake.wait(timeout=0.5)
                self._wake.clear()
                continue
            self._wake.wait(timeout=interval)
            self._wake.clear()
            if self._stopped:
                return
            try:
                self._run_round(force=True)
            except Exception:  # noqa: BLE001
                logger.exception("metrics harvest round failed")

    # -- RPC surface (registered by GcsServer) ------------------------

    def prometheus(self, force: bool = False) -> str:
        """Cluster-merged Prometheus exposition: every harvested series
        labeled by proc + node (histogram buckets cumulative per
        series; HELP/TYPE once per metric name). A scrape serves the
        sampler's last round while it is fresh (< one interval old),
        so an external scraper — however fast — adds no fan-out load
        on top of the sampler cadence; `force=True` (CLI dumps, tests
        inducing a state then asserting on it) harvests NOW."""
        from ray_tpu.util.metrics import render_prometheus
        flat: List[Dict[str, Any]] = []
        for snap in self._run_round(force=force):
            extra = {"proc": snap["proc"]}
            if snap.get("node_id"):
                extra["node"] = str(snap["node_id"])[:12]
            for m in snap["metrics"]:
                flat.append({**m, "extra_tags": extra})
        return render_prometheus(flat)

    def merged(self, fresh: bool = False) -> Dict[str, Any]:
        """One consistent view of the last round: the per-proc
        snapshots, the flat merged series, and the tag-preserving
        merged wire metrics all come from the SAME harvest (served from
        cache while fresh — the dashboard's JSON poll loop does not
        re-fan-out the cluster per request). `fresh=True` harvests NOW,
        matching the text dump's force= semantics."""
        self._run_round(force=fresh)
        # snaps and series are stored together under _lock at the end
        # of every round — reading both under one acquisition keeps the
        # payload's views from straddling two rounds
        with self._lock:
            snaps = self._last_snaps
            series = dict(self._last_series)
        return {"ts": time.time(),
                "interval_s": self.interval_s,
                "procs": snaps,
                "series": series,
                "merged": self.aggregator.merged_wire(snaps)}

    def query_history(self, names: Optional[List[str]] = None,
                      limit: Optional[int] = None) -> Dict[str, Any]:
        rows = self.history.query_ex(names=names, limit=limit)
        return {"interval_s": self.interval_s,
                "samples": [(ts, series) for ts, series, _f in rows],
                "forced": [f for _ts, _s, f in rows]}

    def query_history_range(self, names: Optional[List[str]] = None,
                            since_s: float = 600.0,
                            tier: str = "raw") -> Dict[str, Any]:
        """The `metrics_history_range` RPC: lookback-window read across
        the durable tiers (raw samples, or downsampled windows with
        counters as per-window deltas and gauges as [min, mean, max]),
        reaching through on-disk segments — including pre-restart ones
        replayed at GCS startup."""
        return {"interval_s": self.interval_s,
                "tier": tier,
                "samples": self.history.range_query(
                    names=names, since_s=since_s, tier=tier)}

    def configure(self, interval_s: Optional[float] = None,
                  cooldown_s: Optional[float] = None,
                  wait_edge_age_s: Optional[float] = None,
                  store_occupancy_frac: Optional[float] = None,
                  queue_depth: Optional[int] = None,
                  lock_hold_s: Optional[float] = None,
                  lock_waiters: Optional[int] = None,
                  serve_p99_s: Optional[float] = None,
                  serve_error_rate: Optional[float] = None,
                  serve_shed_rate: Optional[float] = None,
                  elastic_reconfig_s: Optional[float] = None,
                  gang_heartbeat_stale_s: Optional[float] = None,
                  step_deadline_s: Optional[float] = None,
                  jit_recompiles: Optional[int] = None,
                  jit_recompile_warmup_s: Optional[float] = None,
                  host_transfer_bytes: Optional[float] = None,
                  goodput_floor: Optional[float] = None,
                  goodput_window_s: Optional[float] = None
                  ) -> Dict[str, Any]:
        """Runtime tuning (ops + tests): adjust the sample interval and
        watchdog thresholds without restarting the GCS.
        `step_deadline_s` plants the gang supervisors' runtime per-step
        deadline override (<= 0 clears it back to config/auto)."""
        if interval_s is not None:
            self.interval_s = float(interval_s)
            self._wake.set()
        if cooldown_s is not None:
            self.watchdog.cooldown_s = float(cooldown_s)
        if wait_edge_age_s is not None:
            self.watchdog.wait_edge_age_s = float(wait_edge_age_s)
        if store_occupancy_frac is not None:
            self.watchdog.store_occupancy_frac = \
                float(store_occupancy_frac)
        if queue_depth is not None:
            self.watchdog.queue_depth = int(queue_depth)
        if lock_hold_s is not None:
            self.watchdog.lock_hold_s = float(lock_hold_s)
        if lock_waiters is not None:
            self.watchdog.lock_waiters = int(lock_waiters)
        if serve_p99_s is not None:
            self.watchdog.serve_p99_s = float(serve_p99_s)
        if serve_error_rate is not None:
            self.watchdog.serve_error_rate = float(serve_error_rate)
        if serve_shed_rate is not None:
            self.watchdog.serve_shed_rate = float(serve_shed_rate)
        if elastic_reconfig_s is not None:
            self.watchdog.elastic_reconfig_s = float(elastic_reconfig_s)
        if gang_heartbeat_stale_s is not None:
            self.watchdog.gang_heartbeat_stale_s = \
                float(gang_heartbeat_stale_s)
        if step_deadline_s is not None:
            self.step_deadline_override_s = \
                float(step_deadline_s) if step_deadline_s > 0 else None
        if jit_recompiles is not None:
            self.watchdog.jit_recompiles = int(jit_recompiles)
        if jit_recompile_warmup_s is not None:
            self.watchdog.jit_recompile_warmup_s = \
                float(jit_recompile_warmup_s)
        if host_transfer_bytes is not None:
            self.watchdog.host_transfer_bytes = \
                float(host_transfer_bytes)
        if goodput_floor is not None:
            self.watchdog.goodput_floor = float(goodput_floor)
        if goodput_window_s is not None:
            self.watchdog.goodput_window_s = float(goodput_window_s)
        return {"interval_s": self.interval_s,
                "cooldown_s": self.watchdog.cooldown_s,
                "wait_edge_age_s": self.watchdog.wait_edge_age_s,
                "store_occupancy_frac":
                    self.watchdog.store_occupancy_frac,
                "queue_depth": self.watchdog.queue_depth,
                "lock_hold_s": self.watchdog.lock_hold_s,
                "lock_waiters": self.watchdog.lock_waiters,
                "serve_p99_s": self.watchdog.serve_p99_s,
                "serve_error_rate": self.watchdog.serve_error_rate,
                "serve_shed_rate": self.watchdog.serve_shed_rate,
                "elastic_reconfig_s":
                    self.watchdog.elastic_reconfig_s,
                "gang_heartbeat_stale_s":
                    self.watchdog.gang_heartbeat_stale_s,
                "step_deadline_s": self.step_deadline_override_s,
                "jit_recompiles": self.watchdog.jit_recompiles,
                "jit_recompile_warmup_s":
                    self.watchdog.jit_recompile_warmup_s,
                "host_transfer_bytes":
                    self.watchdog.host_transfer_bytes,
                "goodput_floor": self.watchdog.goodput_floor,
                "goodput_window_s": self.watchdog.goodput_window_s}

    def stop(self) -> None:
        self._stopped = True
        self._wake.set()
        self._liveness_wake.set()
        try:
            # flush buffered history segments so a restart replays
            # right up to the last harvest
            self.history.stop()
        except Exception:  # noqa: BLE001 - shutdown is best-effort
            logger.exception("metrics history flush failed on stop")
