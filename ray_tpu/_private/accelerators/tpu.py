"""TPU accelerator manager: chip discovery, visibility, pod-slice resources.

reference parity: python/ray/_private/accelerators/tpu.py:75-398
(TPUAcceleratorManager) — chip detection via /dev/accel* or /dev/vfio
(tpu.py:110-117), TPU_VISIBLE_CHIPS + TPU_CHIPS_PER_HOST_BOUNDS /
TPU_HOST_BOUNDS env plumbing for 1/2/4-chip slicing (tpu.py:157-196),
pod type from GCE metadata / GKE env (tpu.py:199-229), and the
`{tpu_name: 1, "TPU-<type>-head": 1}` pod-slice custom resources on worker 0
used for multi-host SPMD gang targeting (tpu.py:335-398).
"""

from __future__ import annotations

import glob
import logging
import os
from typing import Dict, List, Optional, Tuple

from ray_tpu._private.accelerators.accelerator import AcceleratorManager

logger = logging.getLogger(__name__)

TPU_VISIBLE_CHIPS_ENV = "TPU_VISIBLE_CHIPS"
TPU_CHIPS_PER_HOST_BOUNDS_ENV = "TPU_CHIPS_PER_HOST_BOUNDS"
TPU_HOST_BOUNDS_ENV = "TPU_HOST_BOUNDS"
TPU_SINGLE_HOST_BOUNDS = "1,1,1"
# Valid per-task chip slices on one host (reference tpu.py:13,143-155).
VALID_TPU_CHIP_COUNTS = (1, 2, 4)
# Test hook: pretend this many chips exist (the chip-free fake ladder).
TPU_FAKE_CHIPS_ENV = "RAY_TPU_FAKE_NUM_CHIPS"
TPU_FAKE_POD_TYPE_ENV = "RAY_TPU_FAKE_POD_TYPE"
TPU_FAKE_WORKER_ID_ENV = "RAY_TPU_FAKE_WORKER_ID"

_CHIPS_PER_HOST_BOUNDS = {1: "1,1,1", 2: "1,2,1", 4: "2,2,1"}


class TPUAcceleratorManager(AcceleratorManager):
    @staticmethod
    def get_resource_name() -> str:
        return "TPU"

    @staticmethod
    def get_visible_accelerator_ids_env_var() -> str:
        return TPU_VISIBLE_CHIPS_ENV

    @staticmethod
    def get_current_node_num_accelerators() -> int:
        fake = os.environ.get(TPU_FAKE_CHIPS_ENV)
        if fake is not None:
            return int(fake)
        # reference tpu.py:110-117: count /dev/accel* (PCIe) or vfio devices.
        accel = glob.glob("/dev/accel*")
        if accel:
            return len(accel)
        try:
            vfio = [e for e in os.listdir("/dev/vfio") if e != "vfio"]
            return len(vfio)
        except FileNotFoundError:
            return 0

    @staticmethod
    def get_current_node_accelerator_type() -> Optional[str]:
        pod_type = TPUAcceleratorManager._get_tpu_pod_type()
        if pod_type is None:
            return None
        # 'v5p-16' -> 'TPU-V5P'
        return "TPU-" + pod_type.split("-")[0].upper()

    @staticmethod
    def _get_tpu_pod_type() -> Optional[str]:
        # GKE env, fake env, or GCE metadata (reference tpu.py:199-229; the
        # metadata server is unreachable in tests so env wins).
        for var in (TPU_FAKE_POD_TYPE_ENV, "TPU_ACCELERATOR_TYPE"):
            v = os.environ.get(var)
            if v:
                return v
        return None

    @staticmethod
    def _get_tpu_worker_id() -> Optional[int]:
        for var in (TPU_FAKE_WORKER_ID_ENV, "TPU_WORKER_ID"):
            v = os.environ.get(var)
            if v is not None:
                try:
                    return int(v)
                except ValueError:
                    return None
        return None

    @staticmethod
    def _get_tpu_name() -> Optional[str]:
        return os.environ.get("TPU_NAME")

    @staticmethod
    def get_current_node_additional_resources() -> Dict[str, float]:
        """Pod-slice resources for multi-host gangs: every host of slice
        `name` gets {name: 1}; worker 0 additionally gets
        {"TPU-<pod_type>-head": 1} so a trainer can target one actor per
        slice head (reference tpu.py:335-398)."""
        resources: Dict[str, float] = {}
        name = TPUAcceleratorManager._get_tpu_name()
        pod_type = TPUAcceleratorManager._get_tpu_pod_type()
        worker_id = TPUAcceleratorManager._get_tpu_worker_id()
        if name:
            resources[name] = 1.0
        if pod_type is not None and worker_id == 0:
            resources[f"TPU-{pod_type}-head"] = 1.0
        return resources

    @staticmethod
    def validate_resource_request_quantity(quantity: float
                                           ) -> Tuple[bool, Optional[str]]:
        if quantity != int(quantity) or int(quantity) not in \
                VALID_TPU_CHIP_COUNTS:
            # >4 means multi-host: must use whole hosts (reference
            # tpu.py:143-155 allows only 1, 2 or 4 chips per request).
            if quantity == int(quantity) and int(quantity) % 4 == 0:
                return (True, None)
            return (False,
                    f"TPU request must be 1, 2, 4 or a multiple of 4 chips, "
                    f"got {quantity}")
        return (True, None)

    @staticmethod
    def get_current_process_visible_accelerator_ids() -> Optional[List[str]]:
        v = os.environ.get(TPU_VISIBLE_CHIPS_ENV)
        if v is None:
            return None
        return [s for s in v.split(",") if s]

    @staticmethod
    def set_current_process_visible_accelerator_ids(ids: List[str]) -> None:
        """Set chip visibility + topology bounds env for subprocesses
        (reference tpu.py:157-196: libtpu needs the host/chip bounds to
        carve a sub-host topology)."""
        os.environ[TPU_VISIBLE_CHIPS_ENV] = ",".join(str(i) for i in ids)
        n = len(ids)
        if n in _CHIPS_PER_HOST_BOUNDS and n != 4:
            os.environ[TPU_CHIPS_PER_HOST_BOUNDS_ENV] = _CHIPS_PER_HOST_BOUNDS[n]
            os.environ[TPU_HOST_BOUNDS_ENV] = TPU_SINGLE_HOST_BOUNDS
