"""Accelerator plugin registry.

reference parity: python/ray/_private/accelerators/__init__.py — pluggable
per-family AcceleratorManager classes; here TPU is first-class and NVIDIA is
a stub kept only for API-shape parity (this framework is CUDA-free).
"""

from __future__ import annotations

from typing import Dict, List, Type

from ray_tpu._private.accelerators.accelerator import AcceleratorManager
from ray_tpu._private.accelerators.tpu import TPUAcceleratorManager

_MANAGERS: List[Type[AcceleratorManager]] = [TPUAcceleratorManager]


def get_all_accelerator_managers() -> List[Type[AcceleratorManager]]:
    return list(_MANAGERS)


def get_accelerator_manager(resource_name: str) -> Type[AcceleratorManager]:
    for mgr in _MANAGERS:
        if mgr.get_resource_name() == resource_name:
            return mgr
    raise KeyError(f"no accelerator manager for resource '{resource_name}'")


def detect_node_accelerators() -> Dict[str, float]:
    """Autodetect accelerator resources on this node, including pod-slice
    custom resources (reference tpu.py:335-398)."""
    resources: Dict[str, float] = {}
    for mgr in _MANAGERS:
        n = mgr.get_current_node_num_accelerators()
        if n > 0:
            resources[mgr.get_resource_name()] = float(n)
            resources.update(mgr.get_current_node_additional_resources())
    return resources
