"""Accelerator manager ABC.

reference parity: python/ray/_private/accelerators/accelerator.py:5 — the
8-method contract every accelerator family implements.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Dict, List, Optional


class AcceleratorManager(ABC):
    """Per-family detection + visibility plumbing."""

    @staticmethod
    @abstractmethod
    def get_resource_name() -> str:
        """e.g. 'TPU'."""

    @staticmethod
    @abstractmethod
    def get_visible_accelerator_ids_env_var() -> str:
        """env var controlling which accelerators a worker sees."""

    @staticmethod
    @abstractmethod
    def get_current_node_num_accelerators() -> int:
        """How many accelerator chips this node has."""

    @staticmethod
    @abstractmethod
    def get_current_node_accelerator_type() -> Optional[str]:
        """e.g. 'TPU-V5P'."""

    @staticmethod
    def get_current_node_additional_resources() -> Dict[str, float]:
        """Extra custom resources (e.g. TPU pod-slice head markers)."""
        return {}

    @staticmethod
    def validate_resource_request_quantity(quantity: float
                                           ) -> "tuple[bool, Optional[str]]":
        return (True, None)

    @staticmethod
    @abstractmethod
    def get_current_process_visible_accelerator_ids() -> Optional[List[str]]:
        ...

    @staticmethod
    @abstractmethod
    def set_current_process_visible_accelerator_ids(ids: List[str]) -> None:
        ...
