"""Debug plane: attributed structured logs + black-box flight dumps.

reference parity: python/ray/_private/ray_logging (worker stdout/stderr
redirection with task/actor attribution) + log_monitor.py line parsing.
Every line a worker emits — print(), logging, native chatter — is
stamped at WRITE time with the process identity (proc kind/pid), the
currently-executing task id, the hosting actor id, and the active
`util.tracing` trace id, so the log monitor can index it and the
cluster query plane (`ray_tpu logs`, GCS `logs_query`) can filter
server-side without ever re-joining logs to traces by timestamp
(Dapper-style correlation: the trace id IS on the line).

The stamp is a line-oriented prefix, one record per line:

    @rt1 <unix_ts> <kind>/<pid> <task|-> <actor|-> <trace|-> <LEVEL> <msg>

Unstamped lines (native libraries, faulthandler dumps) parse as level
"RAW" records carrying only the message — they still land in the tail
index and the query plane, just without attribution.

Black-box flight dumps: a worker that knows it is about to die hard
(chaos self-kill) writes its span-ring tail + recent log records to a
sidecar file next to its log; the node manager folds it into the crash
postmortem bundle it reports to the GCS (see node_manager.py
`_capture_postmortem`).
"""

from __future__ import annotations

import collections
import io
import json
import logging
import os
import re
import threading
import time
from typing import Any, Callable, Dict, List, Optional

STAMP = "@rt1"
_STAMP_PREFIX = STAMP + " "

# process identity for stamps; set by install()/init_worker_io()
_kind = "proc"
_tail_ring: "collections.deque" = collections.deque(maxlen=2048)
_context_provider: Optional[Callable[[], tuple]] = None
_worker_io_installed = False
_capture_installed = False
_raw_stderr = None
_lock = threading.Lock()


def set_context_provider(fn: Callable[[], tuple]) -> None:
    """fn() -> (task_id_hex | None, actor_id_hex | None, trace_id | None);
    read at stamp time (must be cheap + never raise)."""
    global _context_provider
    _context_provider = fn


def _context() -> tuple:
    fn = _context_provider
    if fn is None:
        return (None, None, None)
    try:
        return fn()
    except Exception:  # noqa: BLE001 - stamping must never break a write
        return (None, None, None)


def _short(id_hex: Optional[str], n: int = 12) -> str:
    return id_hex[:n] if id_hex else "-"


def format_line(msg: str, level: str,
                ts: Optional[float] = None) -> tuple:
    """(stamped line, parsed record) for one message line."""
    task, actor, trace = _context()
    ts = time.time() if ts is None else ts
    line = (f"{STAMP} {ts:.6f} {_kind}/{os.getpid()} {_short(task)} "
            f"{_short(actor)} {trace or '-'} {level} {msg}")
    rec = {"ts": ts, "kind": _kind, "pid": os.getpid(),
           "task_id": task[:12] if task else None,
           "actor_id": actor[:12] if actor else None,
           "trace_id": trace, "level": level, "msg": msg}
    return line, rec


def parse_line(raw: str) -> Dict[str, Any]:
    """Parse one log-file line back into a record; unstamped lines
    become level-RAW records (native output, faulthandler dumps)."""
    if raw.startswith(_STAMP_PREFIX):
        parts = raw.split(" ", 7)
        if len(parts) >= 7:
            kind, _, pid = parts[2].partition("/")
            try:
                ts: Optional[float] = float(parts[1])
            except ValueError:
                ts = None
            try:
                pid_i: Optional[int] = int(pid)
            except ValueError:
                pid_i = None
            return {"ts": ts, "kind": kind, "pid": pid_i,
                    "task_id": None if parts[3] == "-" else parts[3],
                    "actor_id": None if parts[4] == "-" else parts[4],
                    "trace_id": None if parts[5] == "-" else parts[5],
                    "level": parts[6],
                    "msg": parts[7] if len(parts) > 7 else ""}
    return {"ts": None, "kind": None, "pid": None, "task_id": None,
            "actor_id": None, "trace_id": None, "level": "RAW",
            "msg": raw}


def _ids_match(rec_val: Optional[str], query: str) -> bool:
    """Prefix-tolerant id compare: stamps carry 12-char prefixes while
    callers may pass full hex (or an even shorter prefix)."""
    if not rec_val:
        return False
    n = min(len(rec_val), len(query))
    return n > 0 and rec_val[:n] == query[:n]


def filter_records(records, filters: Optional[Dict[str, Any]]
                   ) -> List[Dict[str, Any]]:
    """Server-side record filtering shared by the log monitor tail
    index, the NM snapshot handler, driver snapshots, and follow mode.
    Supported keys: node_id / worker_id / actor_id / task_id (prefix),
    trace_id (exact or prefix), level (exact), match (regex over msg),
    since_ts (float)."""
    if not filters:
        return list(records)
    rx = None
    if filters.get("match"):
        rx = re.compile(filters["match"])
    since = filters.get("since_ts")
    out = []
    for rec in records:
        if filters.get("node_id") and not _ids_match(
                rec.get("node_id"), filters["node_id"]):
            continue
        if filters.get("worker_id") and not _ids_match(
                rec.get("worker_id"), filters["worker_id"]):
            continue
        if filters.get("actor_id") and not _ids_match(
                rec.get("actor_id"), filters["actor_id"]):
            continue
        if filters.get("task_id") and not _ids_match(
                rec.get("task_id"), filters["task_id"]):
            continue
        if filters.get("trace_id") and not _ids_match(
                rec.get("trace_id"), filters["trace_id"]):
            continue
        if filters.get("level") and rec.get("level") != filters["level"]:
            continue
        if since is not None and (rec.get("ts") or 0.0) < since:
            continue
        if rx is not None and not rx.search(rec.get("msg") or ""):
            continue
        out.append(rec)
    return out


# ---------------------------------------------------------------------
# Worker-side stream redirection + logging integration
# ---------------------------------------------------------------------


def _emit(msg: str, level: str, raw) -> None:
    try:
        line, rec = format_line(msg, level)
        _tail_ring.append(rec)
        raw.write(line + "\n")
        raw.flush()
    except Exception:  # noqa: BLE001 - a broken pipe must not kill the
        pass           # writer (the NM reads the file, not the pipe)


class AttributedStream(io.TextIOBase):
    """Line-buffering stdout/stderr wrapper that stamps each COMPLETE
    line with the current task/actor/trace context. Partial lines stay
    buffered until their newline arrives (a stamp mid-line would split
    one print() into two records)."""

    def __init__(self, raw, level: str):
        self._raw = raw
        self._level = level
        self._buf = ""
        # concurrent writers (task thread + RPC handler threads share
        # sys.stdout): an unlocked read-modify-write of the buffer
        # garbles, duplicates, or drops interleaved lines
        self._wlock = threading.Lock()

    def write(self, s: str) -> int:
        if not isinstance(s, str):
            s = str(s)
        with self._wlock:
            self._buf += s
            while "\n" in self._buf:
                line, self._buf = self._buf.split("\n", 1)
                _emit(line, self._level, self._raw)
        return len(s)

    def flush(self) -> None:
        try:
            self._raw.flush()
        except Exception:  # noqa: BLE001 - sink closed mid-flush
            pass

    def fileno(self) -> int:
        return self._raw.fileno()

    def isatty(self) -> bool:
        return False

    @property
    def encoding(self):
        return getattr(self._raw, "encoding", "utf-8")

    @property
    def buffer(self):
        # native writers (np.savetxt, json.dump(fp.buffer)) bypass the
        # stamper; their bytes land unstamped and index as RAW lines
        return self._raw.buffer

    @property
    def name(self):
        return getattr(self._raw, "name", "<attributed>")


class StampedHandler(logging.Handler):
    """Root-logger handler writing stamped lines straight to the RAW
    stream (bypassing the AttributedStream wrapper, so log records carry
    their real level instead of ERR)."""

    def __init__(self, raw):
        super().__init__()
        self._raw = raw

    def emit(self, record: logging.LogRecord) -> None:
        try:
            # keep the logger name (the old worker format carried it)
            text = f"{record.name}: {record.getMessage()}"
            if record.exc_info:
                import traceback as _tb
                text += "\n" + "".join(
                    _tb.format_exception(*record.exc_info)).rstrip()
            for ln in text.splitlines() or [""]:
                _emit(ln, record.levelname, self._raw)
        except Exception:  # noqa: BLE001 - logging must never raise
            pass


class _RingCaptureHandler(logging.Handler):
    """Driver-side capture: record into the in-process tail ring only
    (the driver's console output is untouched)."""

    def emit(self, record: logging.LogRecord) -> None:
        try:
            _, rec = format_line(record.getMessage(), record.levelname)
            _tail_ring.append(rec)
        except Exception:  # noqa: BLE001 - must not throw into logging
            pass


def init_worker_io(kind: str = "worker") -> None:
    """Worker-process bootstrap: redirect stdout/stderr through the
    line stamper and route `logging` through a stamped root handler.
    Called once from worker_main before any task runs."""
    global _kind, _worker_io_installed, _raw_stderr
    import sys
    _kind = kind
    _resize_ring()
    raw_out, raw_err = sys.stdout, sys.stderr
    for s in (raw_out, raw_err):
        try:
            s.reconfigure(line_buffering=True)
        except Exception:  # noqa: BLE001 - no reconfigure; default buffering
            pass
    _raw_stderr = raw_err
    sys.stdout = AttributedStream(raw_out, "OUT")
    sys.stderr = AttributedStream(raw_err, "ERR")
    root = logging.getLogger()
    root.handlers[:] = [StampedHandler(raw_err)]
    root.setLevel(logging.INFO)
    _worker_io_installed = True


def install_capture(kind: str = "driver") -> None:
    """Driver-side (or any non-redirected process) logging capture into
    the in-process tail ring, so `ray_tpu logs` also answers for
    drivers. Idempotent; a no-op where init_worker_io already ran."""
    global _kind, _capture_installed
    with _lock:
        if _worker_io_installed or _capture_installed:
            return
        _kind = kind
        _resize_ring()
        logging.getLogger().addHandler(_RingCaptureHandler())
        _capture_installed = True


def _resize_ring() -> None:
    global _tail_ring
    try:
        from ray_tpu._private.config import Config
        n = int(Config.log_tail_lines)
    except Exception:  # noqa: BLE001
        n = 2048
    if _tail_ring.maxlen != n:
        _tail_ring = collections.deque(_tail_ring, maxlen=n)


def raw_stderr():
    """The unwrapped stderr (for faulthandler, which needs a real fd
    and must not deadlock against the stamping wrapper in a signal
    handler)."""
    import sys
    return _raw_stderr or sys.stderr


def tail(n: Optional[int] = None) -> List[Dict[str, Any]]:
    recs = list(_tail_ring)
    return recs[-n:] if n else recs


def snapshot(filters: Optional[Dict[str, Any]] = None,
             tail: Optional[int] = None) -> Dict[str, Any]:
    """This process's in-memory log tail, filtered server-side — the
    `cw_logs_snapshot` gather point of the GCS `logs_query` fan-out
    (drivers live outside any node manager's log dir)."""
    from ray_tpu._private import spans as _spans
    label = _spans.process_label()
    node_id = _spans.process_node_id()
    # attach process identity BEFORE filtering: ring records carry no
    # node/worker ids of their own, so a node- or worker-filtered query
    # would otherwise silently drop every driver record
    recs = []
    for rec in list(_tail_ring):
        rec = dict(rec)
        rec.setdefault("node_id", node_id[:12] if node_id else None)
        rec.setdefault("worker_id", label)
        recs.append(rec)
    recs = filter_records(recs, filters)
    if tail:
        recs = recs[-int(tail):]
    return {"proc_uid": _spans.PROC_UID, "pid": os.getpid(),
            "label": label, "node_id": node_id, "records": recs}


# ---------------------------------------------------------------------
# Black-box flight dumps
# ---------------------------------------------------------------------


def flight_dump_path() -> Optional[str]:
    d = os.environ.get("RAY_TPU_SESSION_DIR")
    wid = os.environ.get("RAY_TPU_WORKER_ID")
    if not d or not wid:
        return None
    return os.path.join(d, "logs", f"worker-{wid[:12]}.flight.json")


def read_rss_bytes(pid: Optional[int] = None) -> Optional[int]:
    try:
        with open(f"/proc/{pid or os.getpid()}/statm") as f:
            return int(f.read().split()[1]) * (os.sysconf("SC_PAGE_SIZE")
                                               if hasattr(os, "sysconf")
                                               else 4096)
    except Exception:  # noqa: BLE001 - non-linux / proc gone
        return None


def write_flight_dump(reason: str = "") -> Optional[str]:
    """Persist this process's span-ring tail + recent log records to the
    sidecar file the node manager folds into the crash postmortem. Runs
    on the about-to-die path (chaos self-kill), so it must be quick and
    must never raise."""
    path = flight_dump_path()
    if path is None:
        return None
    try:
        from ray_tpu._private import spans as _spans
        from ray_tpu._private.config import Config
        k = int(Config.postmortem_span_tail)
        dump = {
            "ts": time.time(),
            "reason": reason,
            "pid": os.getpid(),
            "rss_bytes": read_rss_bytes(),
            "span_tail": [list(r) for r in
                          _spans.ring().snapshot_records()[-k:]],
            "log_tail": tail(int(Config.postmortem_log_lines)),
        }
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(dump, f, default=str)
        os.replace(tmp, path)
        return path
    except Exception:  # noqa: BLE001 - dying anyway; best effort
        return None


def consume_flight_dump(log_dir: str,
                        worker_id_hex: str) -> Optional[Dict[str, Any]]:
    """Read-and-delete a dead worker's flight dump (node-manager side)."""
    path = os.path.join(log_dir, f"worker-{worker_id_hex[:12]}.flight.json")
    try:
        with open(path) as f:
            dump = json.load(f)
    except Exception:  # noqa: BLE001 - no dump (SIGKILL'd from outside)
        return None
    try:
        os.unlink(path)
    except OSError:
        pass
    return dump
