"""Serialization: cloudpickle for code, pickle5 + out-of-band buffers for data.

reference parity: python/ray/_private/serialization.py (SerializationContext).
Values are serialized to a (meta, buffers) envelope so large numpy/jax arrays
travel as raw buffers that can land in (and be read zero-copy out of) the
shared-memory object store.

Envelope layout (the on-shm format of a stored object):

    u32 meta_len | u32 nbuf                      -- 8-byte fixed header
    (u64 buf_offset | u64 buf_len) * nbuf        -- buffer table
    meta bytes                                   -- pickle stream (in-band)
    ...padding...                                -- to 64-byte alignment
    buffer payloads at their table offsets       -- each 64-byte aligned

Offsets are absolute from the envelope start. Because the arena allocator
hands out 64-byte-aligned blocks and maps the arena at a page boundary,
aligned-relative means aligned-absolute: zero-copy numpy views over the
buffers are SIMD/cacheline aligned. Writers size the envelope with
plan_envelope() and scatter-write it straight into the destination
(`store.create` view) with write_envelope() — one copy from the source
arrays into shm, no intermediate joined blob. Readers (`unpack`) slice
buffer views out of the envelope without copying.
"""

from __future__ import annotations

import io
import itertools
import pickle
import struct
from typing import Any, List, Sequence, Tuple

import cloudpickle

from ray_tpu._private import spans as _spans

try:
    import numpy as _np
except Exception:  # noqa: BLE001 - numpy-less env: slower copies only
    _np = None

_HDR = struct.Struct(">II")      # meta_len, nbuf
_BUF = struct.Struct(">QQ")      # offset, length (per buffer)
BUFFER_ALIGN = 64
# numpy's copy loop moves large buffers into the shm mapping ~3x faster
# than memoryview slice assignment on this class of box; below this size
# the frombuffer setup costs more than it saves
_NP_COPY_MIN = 1 << 14
# Envelope spans only for payloads big enough to be worth measuring —
# tiny inline envelopes (task args) would pay more to be measured than
# to be processed.
_SPAN_MIN_BYTES = 1 << 16
# Both envelope spans are edge-sampled (Dapper): they sit INSIDE the
# always-on cw.store_value / cw.get umbrella spans, and a recorder call
# next to a MB-scale copy runs with a cold cache (~10µs, not the ~2µs
# tight-loop cost), which would alone break the <1% put-path budget.
# One in K still shows the serialize-vs-copy split, scaled by the rate.
_WRITE_SAMPLE_K = 16
_READ_SAMPLE_K = 32
_write_tick = itertools.count()
_read_tick = itertools.count()


_mp_main_registered: set = set()


def _ensure_mp_main_by_value() -> None:
    """multiprocessing-spawn drivers load the user script as
    `__mp_main__` (aliased to `__main__` only inside spawn CHILDREN):
    cloudpickle special-cases just `__main__` as unimportable, so
    without this registration it pickles `__mp_main__` functions BY
    REFERENCE — and workers, whose `__main__` is worker_main and which
    have no `__mp_main__` at all, cannot resolve the reference."""
    import sys
    mod = sys.modules.get("__mp_main__")
    if mod is None or id(mod) in _mp_main_registered:
        return
    try:
        cloudpickle.register_pickle_by_value(mod)
    except Exception:  # noqa: BLE001 - odd module shape; fall through
        return
    _mp_main_registered.add(id(mod))


def dumps_function(fn: Any) -> bytes:
    """Serialize a function/class by value (for export to the GCS fn table)."""
    _ensure_mp_main_by_value()
    return cloudpickle.dumps(fn)


def loads_function(blob: bytes) -> Any:
    return cloudpickle.loads(blob)


def serialize(value: Any) -> Tuple[bytes, List[pickle.PickleBuffer]]:
    """pickle5 with out-of-band buffers; cloudpickle where it matters.

    Plain pickle serializes driver-script (__main__) functions *by
    reference* without error, and the reference breaks only at
    deserialization time inside a worker whose __main__ is worker_main;
    CloudPickler pickles unimportable objects (closures, __main__
    functions, lambdas) by value. But CloudPickler construction costs
    ~25µs per call — real money on the task-submission hot path where
    args are almost always plain data. So: plain C pickler first, and
    fall back to cloudpickle when it fails OR when the blob contains a
    by-reference __main__ marker (a string arg merely containing
    "__main__" just pays the cloudpickle price — safe, not wrong).
    """
    buffers: List[pickle.PickleBuffer] = []
    try:
        blob = pickle.dumps(value, protocol=5,
                            buffer_callback=buffers.append)
        # __mp_main__ is __main__'s alias under multiprocessing-spawn
        # drivers (and NOT a substring of "__main__", so it needs its
        # own check): a by-reference __mp_main__ function deserializes
        # only in processes spawned from the same parent — workers
        # aren't, so such blobs must route through cloudpickle too.
        if b"__main__" not in blob and b"__mp_main__" not in blob:
            return b"P" + blob, buffers
    except Exception:  # noqa: BLE001 — unpicklable by plain pickle
        pass
    buffers = []
    _ensure_mp_main_by_value()
    f = io.BytesIO()
    cloudpickle.CloudPickler(
        f, protocol=5, buffer_callback=buffers.append).dump(value)
    return b"C" + f.getvalue(), buffers


def deserialize(meta: Any, buffers: List[Any]) -> Any:
    tag = bytes(meta[:1])
    if tag in (b"P", b"C"):
        return pickle.loads(meta[1:], buffers=buffers)
    raise ValueError(f"bad serialization tag {tag!r}")


def raw_buffers(buffers: Sequence[pickle.PickleBuffer]) -> List[memoryview]:
    """Flat C-contiguous views of the out-of-band buffers (raw() raises
    on non-contiguous data, but pickle5 only emits contiguous ones)."""
    return [b.raw() for b in buffers]


def plan_envelope(meta: bytes, raws: Sequence[memoryview]
                  ) -> Tuple[int, List[int]]:
    """(total envelope size, per-buffer offsets) for write_envelope.

    Computing the size up front lets the writer allocate the destination
    (shm block or bytearray) exactly once and scatter the parts in.
    """
    off = _HDR.size + _BUF.size * len(raws) + len(meta)
    offsets: List[int] = []
    for r in raws:
        off = (off + BUFFER_ALIGN - 1) & ~(BUFFER_ALIGN - 1)
        offsets.append(off)
        off += r.nbytes
    return off, offsets


def write_envelope(dest: Any, meta: bytes, raws: Sequence[memoryview],
                   offsets: Sequence[int]) -> None:
    """Scatter-write header + meta + buffers into `dest` (a writable
    bytes-like of plan_envelope() size): each source buffer is copied
    exactly once, directly to its final (aligned) location."""
    sampled = (len(dest) >= _SPAN_MIN_BYTES
               and next(_write_tick) % _WRITE_SAMPLE_K == 0)
    with _spans.span("envelope.write", bytes=len(dest),
                     sampled=_WRITE_SAMPLE_K) if sampled else _spans.NOOP:
        _HDR.pack_into(dest, 0, len(meta), len(raws))
        pos = _HDR.size
        for off, r in zip(offsets, raws):
            _BUF.pack_into(dest, pos, off, r.nbytes)
            pos += _BUF.size
        dest[pos:pos + len(meta)] = meta
        np_dest = None
        for off, r in zip(offsets, raws):
            n = r.nbytes
            if _np is not None and n >= _NP_COPY_MIN:
                if np_dest is None:
                    np_dest = _np.frombuffer(dest, dtype=_np.uint8)
                _np.copyto(np_dest[off:off + n],
                           _np.frombuffer(r, dtype=_np.uint8))
            else:
                dest[off:off + n] = r


def pack(value: Any) -> bytes:
    """Serialize into one contiguous envelope blob (inline objects, task
    args — payloads that travel in-band over RPC rather than through
    the shm store)."""
    meta, buffers = serialize(value)
    raws = raw_buffers(buffers)
    total, offsets = plan_envelope(meta, raws)
    out = bytearray(total)
    write_envelope(out, meta, raws, offsets)
    return bytes(out)


def unpack(buf: memoryview) -> Any:
    """Zero-copy deserialize from an envelope (buffers view into `buf`)."""
    sampled = (len(buf) >= _SPAN_MIN_BYTES
               and next(_read_tick) % _READ_SAMPLE_K == 0)
    with _spans.span("envelope.read", bytes=len(buf),
                     sampled=_READ_SAMPLE_K) if sampled else _spans.NOOP:
        meta_len, nbuf = _HDR.unpack_from(buf, 0)
        pos = _HDR.size
        buffers = []
        for _ in range(nbuf):
            off, blen = _BUF.unpack_from(buf, pos)
            pos += _BUF.size
            buffers.append(buf[off:off + blen])
        meta = buf[pos:pos + meta_len]
        return deserialize(meta, buffers)
