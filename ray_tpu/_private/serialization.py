"""Serialization: cloudpickle for code, pickle5 + out-of-band buffers for data.

reference parity: python/ray/_private/serialization.py (SerializationContext).
Values are serialized to a (meta, buffers) envelope so large numpy/jax arrays
travel as raw buffers that can land in (and be read zero-copy out of) the
shared-memory object store.
"""

from __future__ import annotations

import io
import pickle
from typing import Any, List, Tuple

import cloudpickle


def dumps_function(fn: Any) -> bytes:
    """Serialize a function/class by value (for export to the GCS fn table)."""
    return cloudpickle.dumps(fn)


def loads_function(blob: bytes) -> Any:
    return cloudpickle.loads(blob)


def serialize(value: Any) -> Tuple[bytes, List[pickle.PickleBuffer]]:
    """pickle5 with out-of-band buffers; cloudpickle where it matters.

    Plain pickle serializes driver-script (__main__) functions *by
    reference* without error, and the reference breaks only at
    deserialization time inside a worker whose __main__ is worker_main;
    CloudPickler pickles unimportable objects (closures, __main__
    functions, lambdas) by value. But CloudPickler construction costs
    ~25µs per call — real money on the task-submission hot path where
    args are almost always plain data. So: plain C pickler first, and
    fall back to cloudpickle when it fails OR when the blob contains a
    by-reference __main__ marker (a string arg merely containing
    "__main__" just pays the cloudpickle price — safe, not wrong).
    """
    buffers: List[pickle.PickleBuffer] = []
    try:
        blob = pickle.dumps(value, protocol=5,
                            buffer_callback=buffers.append)
        if b"__main__" not in blob:
            return b"P" + blob, buffers
    except Exception:  # noqa: BLE001 — unpicklable by plain pickle
        pass
    buffers = []
    f = io.BytesIO()
    cloudpickle.CloudPickler(
        f, protocol=5, buffer_callback=buffers.append).dump(value)
    return b"C" + f.getvalue(), buffers


def deserialize(meta: bytes, buffers: List[Any]) -> Any:
    tag, body = meta[:1], meta[1:]
    if tag in (b"P", b"C"):
        return pickle.loads(body, buffers=buffers)
    raise ValueError(f"bad serialization tag {tag!r}")


def pack(value: Any) -> bytes:
    """Serialize into one contiguous blob: u32 meta_len | meta | u32 nbuf |
    (u64 len | bytes)*  — the on-disk/shm layout of a stored object."""
    import struct
    meta, buffers = serialize(value)
    parts = [struct.pack(">I", len(meta)), meta, struct.pack(">I", len(buffers))]
    for b in buffers:
        raw = b.raw()
        parts.append(struct.pack(">Q", raw.nbytes))
        parts.append(raw)
    return b"".join(parts)


def unpack(buf: memoryview) -> Any:
    """Zero-copy deserialize from a packed blob (buffers view into `buf`)."""
    import struct
    (meta_len,) = struct.unpack_from(">I", buf, 0)
    off = 4
    meta = bytes(buf[off:off + meta_len])
    off += meta_len
    (nbuf,) = struct.unpack_from(">I", buf, off)
    off += 4
    buffers = []
    for _ in range(nbuf):
        (blen,) = struct.unpack_from(">Q", buf, off)
        off += 8
        buffers.append(buf[off:off + blen])
        off += blen
    return deserialize(meta, buffers)
