"""Shared runtime data structures: task specs, resources, node info.

TaskSpecification equivalent of reference src/ray/common/task/task_spec.h —
but as plain dataclasses shipped over the framed-pickle RPC instead of
protobuf. Resource accounting mirrors reference
src/ray/common/scheduling/resource_set.h (fixed-point there; floats with an
epsilon here, quantized to 1e-4 like the reference's FixedPoint).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field, fields
from enum import Enum
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private.ids import (ActorID, JobID, NodeID, ObjectID,
                                  PlacementGroupID, TaskID, WorkerID)

RESOURCE_EPS = 1e-4


def quantize(v: float) -> float:
    """Quantize to 1e-4 granularity (reference FixedPoint precision)."""
    return round(v / RESOURCE_EPS) * RESOURCE_EPS


class ResourceSet:
    """A bag of named resource quantities with fixed-point-ish arithmetic."""

    __slots__ = ("_r",)

    def __init__(self, resources: Optional[Dict[str, float]] = None):
        self._r = {k: quantize(float(v)) for k, v in (resources or {}).items()
                   if v and float(v) > 0}

    def get(self, name: str) -> float:
        return self._r.get(name, 0.0)

    def to_dict(self) -> Dict[str, float]:
        return dict(self._r)

    def is_subset_of(self, other: "ResourceSet") -> bool:
        return all(other.get(k) + RESOURCE_EPS / 2 >= v for k, v in self._r.items())

    def subtract(self, other: "ResourceSet") -> None:
        for k, v in other._r.items():
            self._r[k] = quantize(self._r.get(k, 0.0) - v)

    def add(self, other: "ResourceSet") -> None:
        for k, v in other._r.items():
            self._r[k] = quantize(self._r.get(k, 0.0) + v)

    def is_empty(self) -> bool:
        return not any(v > RESOURCE_EPS / 2 for v in self._r.values())

    def __repr__(self) -> str:
        return f"ResourceSet({self._r})"


class SchedulingStrategy:
    """Base for scheduling strategies (reference util/scheduling_strategies.py)."""


@dataclass
class DefaultSchedulingStrategy(SchedulingStrategy):
    pass


@dataclass
class SpreadSchedulingStrategy(SchedulingStrategy):
    pass


@dataclass
class NodeAffinitySchedulingStrategy(SchedulingStrategy):
    node_id: str = ""
    soft: bool = False


@dataclass
class PlacementGroupSchedulingStrategy(SchedulingStrategy):
    placement_group: Any = None  # PlacementGroup handle
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: bool = False


@dataclass
class NodeLabelSchedulingStrategy(SchedulingStrategy):
    """Label-constrained placement (reference
    util/scheduling_strategies.py:135 + node_label_scheduling_policy.h).
    hard: {label_key: [allowed values]} — every key must match ("" in the
    list means 'key exists'); soft: preferred but not required."""

    hard: Dict[str, List[str]] = field(default_factory=dict)
    soft: Dict[str, List[str]] = field(default_factory=dict)


class TaskType(Enum):
    NORMAL_TASK = 0
    ACTOR_CREATION_TASK = 1
    ACTOR_TASK = 2


@dataclass
class TaskSpec:
    """Everything an executor needs to run a task.

    reference parity: src/ray/common/task/task_spec.h TaskSpecification.
    `function_key` points at the exported function/class blob in the GCS
    function table (reference: _private/function_manager.py export keys).
    """

    task_id: TaskID
    job_id: JobID
    task_type: TaskType
    function_key: str                  # GCS KV key of the pickled function/class
    function_name: str                 # human-readable, for errors/state API
    args: bytes                        # serialized (args, kwargs) envelope
    arg_object_refs: List[ObjectID]    # top-level ObjectRef deps to resolve
    num_returns: int
    resources: Dict[str, float]
    owner_address: Tuple[str, int]     # core-worker RPC addr of the submitter
    owner_worker_id: WorkerID
    # Actor fields
    actor_id: Optional[ActorID] = None
    actor_method_name: str = ""
    sequence_number: int = -1          # ordering for actor tasks
    max_restarts: int = 0
    max_task_retries: int = 0
    max_concurrency: int = 1
    # named concurrency groups (reference concurrency_group_manager.h):
    # creation carries {group: max_concurrency}; each actor call carries
    # the group it executes in ("" = default pool)
    concurrency_groups: Optional[Dict[str, int]] = None
    concurrency_group: str = ""
    # Normal-task fields
    max_retries: int = 0
    retry_exceptions: bool = False
    # worker recycling (reference max_calls option): the worker process
    # exits after executing this function max_calls times — the escape
    # hatch for native libraries that leak
    max_calls: int = 0
    # num_returns="dynamic" (reference _raylet.pyx:269
    # StreamingObjectRefGenerator): the task yields a variable number of
    # values; each becomes its own object, and the single declared
    # return resolves to the list of their refs.
    dynamic_returns: bool = False
    # Scheduling
    scheduling_strategy: SchedulingStrategy = field(
        default_factory=DefaultSchedulingStrategy)
    placement_group_id: Optional[PlacementGroupID] = None
    placement_group_bundle_index: int = -1
    # Runtime env (dict: env_vars, working_dir, ...)
    runtime_env: Optional[Dict[str, Any]] = None
    # Data-locality hints: node id hex -> bytes of this task's args
    # already resident there (reference lease_policy.h:56 locality-aware
    # lease policy / scorer.h:25)
    locality_hints: Dict[str, float] = field(default_factory=dict)
    # arg oid hex -> (store address, size): lets the dispatching node
    # manager PREFETCH remote args into its local store while the lease
    # is granted (reference raylet DependencyManager + PullManager pull
    # task args to the node before dispatch)
    arg_locations: Dict[str, Any] = field(default_factory=dict)
    # Tracing (reference util/tracing/tracing_helper.py: context rides
    # inside the task spec): all tasks of one logical request share a
    # trace id; parent_task_id links the causal chain.
    trace_id: Optional[str] = None
    parent_task_id: Optional[str] = None
    # Owner's node id hex: lets an executor on the same node pick the
    # shm ring for its cw_task_done report instead of the loopback
    # socket (_private/shm_channel.py). A real field, not an ad-hoc
    # attribute, so the compact positional pickle fast path holds.
    owner_node_id: str = ""
    # Misc
    name: str = ""
    namespace: str = ""

    detached: bool = False
    submitted_at: float = field(default_factory=time.time)

    def required_resources(self) -> ResourceSet:
        return ResourceSet(self.resources)

    def scheduling_key(self) -> Tuple:
        """Tasks with the same key can reuse a leased worker (reference:
        direct_task_transport lease reuse, SchedulingKey). repr() of the
        strategy (not just its type): NodeAffinity(node A) must not
        reuse a lease held for NodeAffinity(node B)."""
        return (self.function_key, tuple(sorted(self.resources.items())),
                repr(self.scheduling_strategy),
                self.placement_group_id.hex() if self.placement_group_id else "",
                self.placement_group_bundle_index,
                # FULL runtime env, canonicalized (dict insertion order
                # must not split keys): working_dir / py_modules / pip
                # change what a worker has materialized, and a reused
                # lease pins the worker
                _canonical(self.runtime_env) if self.runtime_env else "")

    # Compact pickling: specs cross a process boundary on every task
    # push; the default dataclass reduce ships all 30 field-name strings
    # per spec. A positional tuple roughly halves encode+decode cost on
    # the control-plane hot path (reference keeps specs in protobuf for
    # the same reason). Ad-hoc attributes (e.g. the worker-side
    # _lease_id) ride in the extras dict.
    def __getstate__(self):
        d = self.__dict__
        if len(d) == len(_SPEC_FIELDS):  # common case: no ad-hoc attrs
            extras = None
        else:
            extras = {k: v for k, v in d.items()
                      if k not in _SPEC_FIELD_SET} or None
        return ([d[f] for f in _SPEC_FIELDS], extras)

    def __setstate__(self, state):
        vals, extras = state
        self.__dict__.update(zip(_SPEC_FIELDS, vals))
        if extras:
            self.__dict__.update(extras)


_SPEC_FIELDS = tuple(f.name for f in fields(TaskSpec))
_SPEC_FIELD_SET = frozenset(_SPEC_FIELDS)


def _canonical(v: Any):
    """Order-insensitive hashable form of nested dict/list config."""
    if isinstance(v, dict):
        return tuple(sorted((k, _canonical(x)) for k, x in v.items()))
    if isinstance(v, (list, tuple)):
        return tuple(_canonical(x) for x in v)
    return v


class WorkerExitType(Enum):
    IDLE = 0
    INTENDED = 1
    CRASH = 2
    NODE_DEATH = 3


@dataclass
class NodeInfo:
    node_id: NodeID
    address: Tuple[str, int]            # node manager RPC address
    store_address: Tuple[str, int]      # object store server RPC address
    resources_total: Dict[str, float]
    labels: Dict[str, str] = field(default_factory=dict)
    alive: bool = True
    is_head: bool = False
    start_time: float = field(default_factory=time.time)


@dataclass
class PlacementGroupInfo:
    """GCS placement-group table entry (reference:
    gcs_placement_group_manager.h GcsPlacementGroup; states per
    gcs.proto PlacementGroupTableData)."""
    pg_id: PlacementGroupID
    name: str
    bundles: List[Dict[str, float]]
    strategy: str                        # PACK/SPREAD/STRICT_PACK/STRICT_SPREAD
    state: str = "PENDING"               # PENDING/CREATED/REMOVED/RESCHEDULING
    # node id hex per bundle once committed
    bundle_nodes: List[str] = field(default_factory=list)
    creator_job_id: str = ""
    detached: bool = False


@dataclass
class ActorInfo:
    actor_id: ActorID
    name: str
    namespace: str
    class_name: str
    state: str                           # PENDING/ALIVE/RESTARTING/DEAD
    address: Optional[Tuple[str, int]]   # worker core RPC address when ALIVE
    node_id: Optional[NodeID]
    num_restarts: int = 0
    max_restarts: int = 0
    death_cause: str = ""
