"""Profiling plane: task-attributed CPU flamegraphs for every process.

reference parity: dashboard/modules/reporter/profile_manager.py (the
reference shells out to py-spy/memray per process) + `ray stack`
(scripts.py:1810). Here the sampler is IN-process — a daemon thread over
`sys._current_frames()` — so profiles work with zero external binaries
and carry runtime context no external sampler can see: the task id /
actor id / trace id executing on each sampled thread, read from the
same per-thread context the debug plane's log stamper uses.

The plane has three layers:

  - **Sampler** (this module, per process): start/stop/snapshot around a
    fixed-rate sampling loop; samples aggregate immediately into a
    BOUNDED folded-stack table (function-granularity frames, root
    first), so memory is O(distinct stacks) with an explicit drop
    counter once `Config.profile_max_stacks` distinct stacks exist —
    never O(duration). Each entry is keyed by (thread name, task id,
    actor id, trace id, frames): flamegraphs group by attribution.
  - **Cluster collect** (gcs.profile_collect): one fan-out —
    start→sleep→snapshot on every node manager (which covers its
    workers one hop below) and every pubsub-subscribed driver,
    CONCURRENTLY, under one overall deadline. Merging is clock-free:
    folded stacks carry counts, not timestamps, so skewed clocks
    cannot misalign anything.
  - **Renders**: speedscope JSON (`to_speedscope`) and collapsed
    flamegraph text (`to_folded`, flamegraph.pl format), surfaced as
    `ray_tpu profile`, dashboard /api/profile, util.state.profile().

Overhead contract (asserted in tests/test_profiler.py, same in-situ
methodology as the PR 5 spans bound): while sampling at `hz`, cost is
hz x measured per-sample walk time (< 2% of wall at 100 hz); while
stopped there is NO sampler thread and the only standing cost is the
executor's per-task context-dict write.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple
from ray_tpu.util.locks import TracedLock

# ---------------------------------------------------------------------
# Per-thread execution context (the attribution the sampler stamps)
# ---------------------------------------------------------------------
# threading.local is invisible across threads, so the core worker
# mirrors its TLS here: plain dicts keyed by thread ident. CPython dict
# item assignment is atomic — the executor's set/clear never contends
# with the sampler's reads.
_THREAD_TASK: Dict[int, str] = {}
_THREAD_TRACE: Dict[int, str] = {}
# actor identity is per-process (one actor instance per worker)
_process_actor_id: Optional[str] = None
_process_worker_id: Optional[str] = None


def set_thread_task(task_id_hex: Optional[str]) -> None:
    ident = threading.get_ident()
    if task_id_hex is None:
        _THREAD_TASK.pop(ident, None)
    else:
        _THREAD_TASK[ident] = task_id_hex


def set_thread_trace(trace_id: Optional[str]) -> None:
    ident = threading.get_ident()
    if trace_id is None:
        _THREAD_TRACE.pop(ident, None)
    else:
        _THREAD_TRACE[ident] = trace_id


def set_process_actor(actor_id_hex: Optional[str]) -> None:
    global _process_actor_id
    _process_actor_id = actor_id_hex


def set_process_worker(worker_id_hex: Optional[str]) -> None:
    """Worker identity for `ray_tpu profile --worker` filtering (the
    span-plane label only carries an 8-char prefix)."""
    global _process_worker_id
    _process_worker_id = worker_id_hex


# ---------------------------------------------------------------------
# Sampler
# ---------------------------------------------------------------------


class Sampler:
    """Fixed-rate stack sampler with bounded folded aggregation.

    One instance per process (module-level `sampler()`); start/stop are
    idempotent-friendly under the collect singleflight. Aggregation
    happens inside the sampling loop — a snapshot is a cheap dict copy,
    not a replay of raw samples.

    Idle threads (top frame parked in a stdlib wait or the RPC layer's
    socket read) are edge-sampled 1-in-IDLE_SAMPLE_K with their counts
    scaled back up: a daemon process is mostly parked threads, and
    walking every one of them every sample is what blows the overhead
    budget (~5µs/thread on this class of box — the same reasoning as
    the span plane's 1-in-16 server-dispatch sampling). Busy threads —
    the ones a profile exists for — are walked every sample.
    """

    MAX_DEPTH = 96
    IDLE_SAMPLE_K = 16
    # a thread whose TOP python frame lives here is parked in a wait
    # primitive (C-level sleeps/recvs don't push a frame, so the
    # caller's stdlib wrapper is what shows)
    _IDLE_FILES = ("threading.py", "queue.py", "selectors.py",
                   "socketserver.py", "ssl.py", "socket.py")
    _IDLE_NAMES = ("_recv_exact",)  # rpc.py socket reads

    def __init__(self, max_stacks: int = 2000):
        self.max_stacks = max(16, int(max_stacks))
        self._lock = TracedLock("profiler")  # start/stop/snapshot control
        self._thread: Optional[threading.Thread] = None
        self._stop_ev = threading.Event()
        self.hz = 0.0
        # (thread_name, task, actor, trace, frames) -> count
        self._stacks: Dict[Tuple, int] = {}
        self.samples_total = 0
        self.dropped = 0          # samples lost to the stack-table cap
        self.sample_cost_s = 0.0  # cumulative in-situ walk time
        # last-256 per-sample walk costs: the overhead bound uses the
        # MEDIAN — a walk preempted mid-flight measures GIL wait (time
        # the workload was actually running), and that preemption tail
        # would otherwise dominate the mean under load
        from collections import deque
        self._cost_ring: "deque" = deque(maxlen=256)
        self._started_mono = 0.0
        self._sampled_wall_s = 0.0
        self._thread_names: Dict[int, str] = {}

    # -- control ------------------------------------------------------

    def start(self, hz: float = 100.0) -> bool:
        """Begin sampling at `hz`; returns False if already running
        (the running session keeps its own rate)."""
        hz = min(1000.0, max(1.0, float(hz)))
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return False
            self.hz = hz
            self._stacks = {}
            self.samples_total = 0
            self.dropped = 0
            self.sample_cost_s = 0.0
            self._started_mono = time.monotonic()
            self._sampled_wall_s = 0.0
            self._stop_ev = threading.Event()
            self._thread = threading.Thread(
                target=self._loop, args=(self._stop_ev, hz),
                daemon=True, name="ray-tpu-profiler")
            self._thread.start()
            return True

    def stop(self) -> None:
        with self._lock:
            t = self._thread
            self._stop_ev.set()
            self._thread = None
        if t is not None:
            t.join(timeout=2.0)

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    # -- sampling loop ------------------------------------------------

    def _loop(self, stop_ev: threading.Event, hz: float) -> None:
        period = 1.0 / hz
        next_t = time.monotonic()
        while not stop_ev.is_set():
            t0 = time.perf_counter()
            # under the control lock: snapshot() iterates the stacks
            # table and the cost ring, and an unlocked insert mid-copy
            # raises "changed size during iteration", losing the whole
            # profile. Contention is one rare snapshot per collect, so
            # the lock costs an uncontended acquire per sample.
            with self._lock:
                try:
                    self._sample_once()
                except Exception:  # noqa: BLE001 - a torn frame walk
                    pass           # loses one sample, never the sampler
                cost = time.perf_counter() - t0
                self.sample_cost_s += cost
                self._cost_ring.append(cost)
                self.samples_total += 1
            next_t += period
            delay = next_t - time.monotonic()
            if delay > 0:
                stop_ev.wait(delay)
            else:
                # behind schedule (GIL-starved): resynchronize instead
                # of bursting to catch up — the rate is a ceiling
                next_t = time.monotonic()
        with self._lock:
            self._sampled_wall_s += time.monotonic() - self._started_mono

    def _thread_name(self, ident: int) -> str:
        name = self._thread_names.get(ident)
        if name is None:
            self._thread_names = {
                t.ident: t.name for t in threading.enumerate()
                if t.ident is not None}
            name = self._thread_names.get(ident)
            if name is None:
                # foreign/C-created thread: CACHE the fallback, or this
                # rebuild would repeat every sample for the whole
                # session (exactly the walk cost the overhead budgets)
                name = f"thread-{ident}"
                self._thread_names[ident] = name
        return name

    def _sample_once(self) -> None:
        own = threading.get_ident()
        actor = _process_actor_id
        tick = self.samples_total
        idle_round = tick % self.IDLE_SAMPLE_K == 0
        for ident, top in sys._current_frames().items():
            if ident == own:
                continue
            code = top.f_code
            idle = (code.co_filename.endswith(self._IDLE_FILES)
                    or code.co_name in self._IDLE_NAMES)
            if idle and not idle_round:
                continue
            weight = self.IDLE_SAMPLE_K if idle else 1
            frames: List[Tuple[str, str, int]] = []
            f = top
            depth = 0
            while f is not None and depth < self.MAX_DEPTH:
                code = f.f_code
                frames.append((code.co_name, code.co_filename,
                               code.co_firstlineno))
                f = f.f_back
                depth += 1
            frames.reverse()  # root first (folded/speedscope order)
            key = (self._thread_name(ident), _THREAD_TASK.get(ident),
                   actor, _THREAD_TRACE.get(ident), tuple(frames))
            n = self._stacks.get(key)
            if n is not None:
                self._stacks[key] = n + weight
            elif len(self._stacks) < self.max_stacks:
                self._stacks[key] = weight
            else:
                self.dropped += 1

    # -- snapshot -----------------------------------------------------

    def snapshot(self, reset: bool = False) -> Dict[str, Any]:
        """This process's aggregated profile (wire form). `reset=True`
        atomically hands the aggregation table over, so back-to-back
        collects don't double-count."""
        from ray_tpu._private import spans as spans_lib
        with self._lock:
            running = self.running
            stacks = self._stacks
            sampled_s = self._sampled_wall_s
            if running:
                sampled_s += time.monotonic() - self._started_mono
            out = {
                "proc_uid": spans_lib.PROC_UID,
                "pid": os.getpid(),
                "label": spans_lib.process_label(),
                "node_id": spans_lib.process_node_id(),
                "worker_id": _process_worker_id,
                "actor_id": _process_actor_id,
                "hz": self.hz,
                "running": running,
                "duration_s": sampled_s,
                "samples": self.samples_total,
                "dropped": self.dropped,
                "sample_cost_s": self.sample_cost_s,
                "sample_cost_p50_s": (
                    sorted(self._cost_ring)[len(self._cost_ring) // 2]
                    if self._cost_ring else 0.0),
                "stacks": [
                    {"thread": thr, "task_id": task, "actor_id": act,
                     "trace_id": trace,
                     "frames": [list(fr) for fr in frames],
                     "count": count}
                    for (thr, task, act, trace, frames), count
                    in stacks.items()],
            }
            if reset:
                self._stacks = {}
        return out


_SAMPLER: Optional[Sampler] = None
_SAMPLER_LOCK = threading.Lock()


def sampler() -> Sampler:
    global _SAMPLER
    with _SAMPLER_LOCK:
        if _SAMPLER is None:
            from ray_tpu._private.config import Config
            _SAMPLER = Sampler(max_stacks=Config.profile_max_stacks)
        return _SAMPLER


# ---------------------------------------------------------------------
# Local collect (start → sleep → snapshot), singleflight
# ---------------------------------------------------------------------

# The cluster fan-out can reach one process twice (its node manager's
# worker gather AND the GCS's direct subscriber pull run concurrently):
# the first arrival runs the session, later arrivals wait for it and
# share its result, so a process is never double-sampled.
_collect_cv = threading.Condition()
_collect_running = False
_collect_gen = 0
_collect_result: Optional[Dict[str, Any]] = None


def collect_local(duration_s: float = 5.0,
                  hz: float = 100.0) -> Dict[str, Any]:
    global _collect_running, _collect_gen, _collect_result
    duration_s = min(120.0, max(0.05, float(duration_s)))
    with _collect_cv:
        if _collect_running:
            gen = _collect_gen
            _collect_cv.wait_for(lambda: _collect_gen != gen,
                                 timeout=duration_s + 10.0)
            if _collect_result is not None:
                return _collect_result
            # the in-flight session wedged; fall through and sample
        _collect_running = True
    s = sampler()
    started_here = s.start(hz)
    prof: Optional[Dict[str, Any]] = None
    try:
        time.sleep(duration_s)
        prof = s.snapshot(reset=True)
    finally:
        if started_here:
            s.stop()
        with _collect_cv:
            _collect_running = False
            _collect_gen += 1
            _collect_result = prof
            _collect_cv.notify_all()
    if prof is None:  # unreachable unless sleep/snapshot raised
        raise RuntimeError("profile collect failed")
    return prof


# ---------------------------------------------------------------------
# Device mode (xplane traces via util.tpu_profiler)
# ---------------------------------------------------------------------


def device_profile(duration_s: float = 5.0,
                   log_dir: Optional[str] = None) -> Dict[str, Any]:
    """`ray_tpu profile --device`: run a jax profiler trace on this
    process for `duration_s` and report the xplane dir. Only processes
    that already initialized jax participate — importing jax here would
    claim the device tunnel out from under the workload."""
    from ray_tpu._private import spans as spans_lib
    base = {"proc_uid": spans_lib.PROC_UID, "pid": os.getpid(),
            "label": spans_lib.process_label(),
            "node_id": spans_lib.process_node_id(),
            "worker_id": _process_worker_id,
            "actor_id": _process_actor_id}
    if "jax" not in sys.modules:
        return {**base, "skipped": "jax not initialized in this process"}
    try:
        import tempfile

        import jax

        from ray_tpu.util import tpu_profiler
        log_dir = log_dir or os.path.join(
            tempfile.gettempdir(),
            f"ray_tpu_xplane_{os.getpid()}_{int(time.time())}")
        with tpu_profiler.trace(log_dir):
            time.sleep(min(120.0, max(0.05, float(duration_s))))
        return {**base, "xplane_dir": log_dir,
                "devices": [str(d) for d in jax.devices()]}
    except Exception as e:  # noqa: BLE001 - report, don't kill the fan-out
        return {**base, "error": f"{type(e).__name__}: {e}"}


# ---------------------------------------------------------------------
# Merge + renders (clock-free: counts, not timestamps)
# ---------------------------------------------------------------------


def _attr_frames(stack: Dict[str, Any]) -> List[Tuple[str, str, int]]:
    """Synthetic root frames carrying the attribution, so flamegraphs
    group by thread → actor → task → trace before any code frame."""
    out: List[Tuple[str, str, int]] = [
        (f"thread:{stack.get('thread') or '?'}", "", 0)]
    if stack.get("actor_id"):
        out.append((f"actor:{stack['actor_id'][:12]}", "", 0))
    if stack.get("task_id"):
        out.append((f"task:{stack['task_id'][:12]}", "", 0))
    if stack.get("trace_id"):
        out.append((f"trace:{stack['trace_id']}", "", 0))
    return out


def filter_profiles(profiles: List[Dict[str, Any]],
                    node_id: Optional[str] = None,
                    worker_id: Optional[str] = None,
                    actor_id: Optional[str] = None,
                    trace_id: Optional[str] = None
                    ) -> List[Dict[str, Any]]:
    """Client-side selection for the CLI's --node/--worker/--actor/
    --trace-id modes; node/worker/actor ids match by prefix."""
    out: List[Dict[str, Any]] = []
    for p in profiles:
        if node_id and not str(p.get("node_id") or "").startswith(node_id):
            continue
        if worker_id and not str(p.get("worker_id") or "").startswith(
                worker_id):
            continue
        if actor_id and not (
                str(p.get("actor_id") or "").startswith(actor_id)
                or any(str(s.get("actor_id") or "").startswith(actor_id)
                       for s in p.get("stacks", ()))):
            continue
        if trace_id:
            stacks = [s for s in p.get("stacks", ())
                      if s.get("trace_id") == trace_id]
            if not stacks:
                continue
            p = {**p, "stacks": stacks}
        out.append(p)
    return out


def _frame_label(name: str, path: str, line: int) -> str:
    if not path:
        return name
    short = "/".join(path.split("/")[-2:])
    return f"{name} ({short}:{line})"


def to_folded(profiles: List[Dict[str, Any]]) -> str:
    """Collapsed flamegraph.pl format: one `a;b;c count` line per
    distinct stack, cluster-merged (identical lines from different
    sampling windows sum)."""
    agg: Dict[str, int] = {}
    for p in profiles:
        label = p.get("label") or f"proc-{p.get('pid')}"
        for s in p.get("stacks", ()):
            parts = [label]
            parts.extend(n for n, _f, _l in _attr_frames(s))
            parts.extend(_frame_label(*fr) for fr in s["frames"])
            line = ";".join(x.replace(";", ",") for x in parts)
            agg[line] = agg.get(line, 0) + int(s["count"])
    return "\n".join(f"{line} {count}"
                     for line, count in sorted(agg.items())) + "\n"


def to_speedscope(profiles: List[Dict[str, Any]],
                  name: str = "ray_tpu profile") -> Dict[str, Any]:
    """One speedscope file for the whole cluster: a shared frame table
    and one "sampled" profile per process (pick processes in the
    speedscope UI's profile selector). Weights are sample counts
    (unit "none") — the merge is clock-free by construction."""
    frames: List[Dict[str, Any]] = []
    frame_index: Dict[Tuple[str, str, int], int] = {}

    def fidx(fr: Tuple[str, str, int]) -> int:
        i = frame_index.get(fr)
        if i is None:
            i = len(frames)
            frame_index[fr] = i
            rec: Dict[str, Any] = {"name": _frame_label(*fr)}
            if fr[1]:
                rec["file"] = fr[1]
                rec["line"] = fr[2]
            frames.append(rec)
        return i

    out_profiles: List[Dict[str, Any]] = []
    for p in profiles:
        samples: List[List[int]] = []
        weights: List[int] = []
        for s in p.get("stacks", ()):
            stack = [fidx(fr) for fr in _attr_frames(s)]
            stack.extend(fidx((n, f, int(l))) for n, f, l in s["frames"])
            samples.append(stack)
            weights.append(int(s["count"]))
        total = sum(weights)
        label = p.get("label") or f"proc-{p.get('pid')}"
        if p.get("node_id"):
            label = f"{label}@{str(p['node_id'])[:8]}"
        out_profiles.append({
            "type": "sampled",
            "name": f"{label} ({p.get('samples', total)} samples @ "
                    f"{p.get('hz', 0):g}hz)",
            "unit": "none",
            "startValue": 0,
            "endValue": total,
            "samples": samples,
            "weights": weights,
        })
    return {
        "$schema": "https://www.speedscope.app/file-format-schema.json",
        "name": name,
        "activeProfileIndex": 0,
        "exporter": "ray_tpu",
        "shared": {"frames": frames},
        "profiles": out_profiles,
    }
