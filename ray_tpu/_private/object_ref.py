"""ObjectRef: a distributed future with ownership metadata.

reference parity: ObjectRef in python/ray/includes/object_ref.pxi — carries
the object id plus the owner's address so any holder can resolve the value,
and participates in reference counting via __del__.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Optional, Tuple

from ray_tpu._private.ids import ObjectID

# Active nested-ref collector for the current thread's serialization
# (reference: the SerializationContext tracks "contained object refs" so
# the submitter pins refs nested anywhere inside task args, not just
# top-level ones).
_collect_ctx = threading.local()


@contextlib.contextmanager
def collect_serialized_refs(out: list):
    prev = getattr(_collect_ctx, "refs", None)
    _collect_ctx.refs = out
    try:
        yield out
    finally:
        _collect_ctx.refs = prev


class ObjectRef:
    __slots__ = ("_id", "_owner_address", "_registered", "_cw_epoch",
                 "__weakref__")

    def __init__(self, object_id: ObjectID,
                 owner_address: Optional[Tuple[str, int]] = None,
                 _register: bool = True):
        self._id = object_id
        self._owner_address = tuple(owner_address) if owner_address else None
        self._registered = False
        self._cw_epoch = None
        if _register:
            from ray_tpu._private import worker as worker_mod
            w = worker_mod.global_worker_or_none()
            if w is not None:
                w.core_worker.add_local_ref(self)
                self._registered = True
                # the release must reach the CoreWorker INSTANCE that
                # counted the add: after a shutdown+reinit, a stale ref
                # GC'd late would otherwise double-release against the
                # NEW worker's reference table (the ownership state
                # machine rejects that as an illegal transition).
                # Compared by EPOCH, not a weakref: a ref dying inside
                # a garbage cycle has its weakrefs cleared before
                # __del__ runs, which silently skipped the release.
                self._cw_epoch = w.core_worker.epoch

    @property
    def id(self) -> ObjectID:
        return self._id

    def hex(self) -> str:
        return self._id.hex()

    def binary(self) -> bytes:
        return self._id.binary()

    def task_id(self):
        return self._id.task_id()

    @property
    def owner_address(self) -> Optional[Tuple[str, int]]:
        return self._owner_address

    def __hash__(self) -> int:
        return hash(self._id)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self) -> str:
        return f"ObjectRef({self._id.hex()})"

    def __del__(self) -> None:
        if self._registered:
            try:
                from ray_tpu._private import worker as worker_mod
                w = worker_mod.global_worker_or_none()
                if w is not None and \
                        w.core_worker.epoch == self._cw_epoch:
                    w.core_worker.remove_local_ref(self)
            except Exception:  # noqa: BLE001 - interpreter shutdown
                pass

    def __reduce__(self):
        # Serialized refs re-register on the receiving process; the sender's
        # core worker pins the object for in-flight arg refs separately.
        collector = getattr(_collect_ctx, "refs", None)
        if collector is not None:
            collector.append(self)
        return (_deserialize_ref, (self._id, self._owner_address))

    def future(self):
        """Return a concurrent.futures.Future resolving to the value."""
        from ray_tpu._private import worker as worker_mod
        import concurrent.futures
        fut: concurrent.futures.Future = concurrent.futures.Future()
        w = worker_mod.global_worker()

        def _wait() -> None:
            try:
                fut.set_result(w.core_worker.get([self], timeout=None)[0])
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

        import threading
        threading.Thread(target=_wait, daemon=True).start()
        return fut


def _deserialize_ref(object_id: ObjectID,
                     owner_address: Optional[Tuple[str, int]]) -> ObjectRef:
    return ObjectRef(object_id, owner_address)


class ObjectRefGenerator:
    """Iterator over a streaming generator task's child refs, yielding
    each as it is produced (reference StreamingObjectRefGenerator,
    _raylet.pyx:269). Iterable only in the owner process (the one that
    submitted the task); the handle ref still resolves to the full list
    for batch consumers."""

    def __init__(self, handle_ref: ObjectRef):
        self._handle = handle_ref
        self._task_hex = handle_ref.task_id().hex()
        self._i = 0

    @property
    def handle(self) -> ObjectRef:
        return self._handle

    def __iter__(self) -> "ObjectRefGenerator":
        return self

    def __next__(self) -> ObjectRef:
        from ray_tpu._private import worker as worker_mod
        cw = worker_mod.global_worker().core_worker
        entry = cw.tasks.get(self._task_hex)
        if entry is None:
            raise RuntimeError(
                "ObjectRefGenerator can only iterate in the process that "
                "submitted the task")
        # children are keyed by return index (2-based: index 1 is the
        # handle); iterate strictly in index order so a dropped or
        # re-ordered incremental report can't skip/duplicate a child
        want = self._i + 2
        while True:
            with cw._lock:
                child = entry.dynamic_arrived.get(want)
                if child is not None:
                    self._i += 1
                    return ObjectRef(child, cw.address)
                if entry.done:
                    break
                # events are lazy (the owner holds an entry per queued
                # task; most tasks never have a streaming iterator) —
                # the first waiter creates one under the owner's lock.
                # Completion paths set it only when present, so the 1s
                # wait timeout below bounds the missed-wakeup window of
                # a setter that read None just before this create.
                if entry.dynamic_event is None:
                    entry.dynamic_event = threading.Event()
                entry.dynamic_event.clear()
            entry.dynamic_event.wait(timeout=1.0)
        # task over: surface any error via the handle, else serve any
        # child whose incremental report was lost from the final batch
        # (position i in the list IS index i+2 by construction)
        remaining = cw.get([self._handle], timeout=60)[0]
        if self._i < len(remaining):
            ref = remaining[self._i]
            self._i += 1
            return ref
        raise StopIteration
