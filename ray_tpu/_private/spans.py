"""Flight recorder: always-on, per-process span ring buffer.

Dapper-style sampled-at-the-edge tracing for the intra-process layer the
task-event records (task_events.py, task granularity) can't see: every
hot path records microsecond spans into a fixed-size ring, steady-state
overhead is bounded by the ring (drop-oldest, never blocks), and the GCS
gathers all rings on demand into one cluster-merged Chrome trace
(`ray_tpu timeline --spans`, see gcs.spans_collect + api.timeline).

Design constraints:
  - lock-light: recording is an index bump + slot write (a lost
    increment under a rare write race overwrites one slot; the recorder
    must never contend on the paths it measures)
  - monotonic timestamps (`perf_counter`) — wall clock only appears in
    snapshot metadata, where the merger uses it (plus an RPC-midpoint
    offset estimate) to align processes onto one timebase
  - compile-to-no-op: with RAY_TPU_SPANS=0, span() returns a shared
    no-op context manager and instant() returns immediately — call
    sites pay one flag check
  - drop-oldest with an exported `ray_tpu_spans_dropped_total` counter

Span records are tuples (ph, name, t_mono, dur_s, tid, trace_id, attrs):
ph "X" = complete span, "i" = instant event (Chrome trace phases).
"""

from __future__ import annotations

import os
import threading
import uuid
from _thread import get_ident as _get_ident
from time import perf_counter
from time import time as _wall_time
from typing import Any, Dict, Iterable, List, Optional

# One id per interpreter: snapshots are deduped on it when a process is
# reachable through two fan-out paths (e.g. the head process hosts the
# GCS, a node manager, AND the driver core worker).
PROC_UID = uuid.uuid4().hex

DEFAULT_CAPACITY = 16384

_tls = threading.local()


def _env_enabled() -> bool:
    return os.environ.get("RAY_TPU_SPANS", "1").lower() not in (
        "0", "false", "no", "off")


_enabled = _env_enabled()
_process_label: Optional[str] = None
_node_id: Optional[str] = None


class SpanRing:
    """Fixed-size drop-oldest ring of span records.

    record() is deliberately unlocked: a data race costs one overwritten
    slot, never a corrupt structure (list item assignment is atomic in
    CPython), and the recorder sits on paths whose latency it measures.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = max(16, int(capacity))
        self._buf: List[Optional[tuple]] = [None] * self.capacity
        self._i = 0
        self._dropped_synced = 0  # already added to the metric

    def record(self, rec: tuple) -> None:
        i = self._i
        self._i = i + 1
        self._buf[i % self.capacity] = rec

    @property
    def dropped_total(self) -> int:
        return max(0, self._i - self.capacity)

    def snapshot_records(self) -> List[tuple]:
        """Current contents, oldest first (best-effort under concurrent
        writers)."""
        i = self._i
        n = self.capacity
        if i <= n:
            out = self._buf[:i]
        else:
            head = i % n
            out = self._buf[head:] + self._buf[:head]
        return [r for r in out if r is not None]

    def clear(self) -> None:
        self._buf = [None] * self.capacity
        self._i = 0
        self._dropped_synced = 0

    def sync_dropped_metric(self) -> int:
        """Push the drop count delta into the process metrics registry;
        returns the lifetime total. Called from snapshot(), off the
        recording hot path."""
        total = self.dropped_total
        delta = total - self._dropped_synced
        if delta > 0:
            self._dropped_synced = total
            try:
                from ray_tpu.util.metrics import Counter, get_or_create
                get_or_create(
                    Counter, "ray_tpu_spans_dropped_total",
                    description="flight-recorder spans overwritten by "
                                "ring-buffer drop-oldest").inc(delta)
            except Exception:  # noqa: BLE001 - metrics are best-effort
                pass
        return total


def _ring_capacity() -> int:
    try:
        return int(os.environ.get("RAY_TPU_SPANS_CAPACITY",
                                  DEFAULT_CAPACITY))
    except ValueError:
        return DEFAULT_CAPACITY


_RING = SpanRing(_ring_capacity())


def configure(enabled: Optional[bool] = None,
              capacity: Optional[int] = None) -> None:
    """Runtime switch (tests, the spans-overhead bench). Processes read
    RAY_TPU_SPANS at import, so workers inherit the env var instead."""
    global _enabled, _RING
    if enabled is not None:
        _enabled = bool(enabled)
    if capacity is not None:
        _RING = SpanRing(capacity)


def enabled() -> bool:
    return _enabled


def ring() -> SpanRing:
    return _RING


def set_process_label(label: str, node_id: Optional[str] = None) -> None:
    """Name this process's row in the merged trace (driver-1a2b, a
    worker id, raylet, gcs). Last caller wins — one process, one row."""
    global _process_label, _node_id
    _process_label = label
    if node_id is not None:
        _node_id = node_id


def process_label() -> str:
    """This process's trace-row name (also the metrics plane's `proc`
    label — one identity per process across both planes)."""
    return _process_label or f"proc-{os.getpid()}"


def process_node_id() -> Optional[str]:
    return _node_id


def set_current_trace(trace_id: Optional[str]) -> None:
    """Mirror of the core worker's trace TLS (kept here so recording
    never imports the worker stack)."""
    _tls.trace_id = trace_id


def get_current_trace() -> Optional[str]:
    return getattr(_tls, "trace_id", None)


class _Span:
    __slots__ = ("name", "attrs", "t0", "trace_id")

    def __init__(self, name: str, attrs: Dict[str, Any]):
        self.name = name
        self.attrs = attrs
        self.trace_id = getattr(_tls, "trace_id", None)
        self.t0 = 0.0

    def __enter__(self) -> Dict[str, Any]:
        self.t0 = perf_counter()
        return self.attrs

    def __exit__(self, exc_type, exc, tb) -> None:
        # lean on purpose: this records on the paths whose latency it
        # measures (ring.record is an index bump + slot write)
        t1 = perf_counter()
        if exc_type is not None:
            self.attrs["error"] = exc_type.__name__
        ring = _RING
        i = ring._i
        ring._i = i + 1
        ring._buf[i % ring.capacity] = (
            "X", self.name, self.t0, t1 - self.t0, _get_ident(),
            self.trace_id, self.attrs or None)


class _NoopSpan:
    """Shared no-op: call sites may still write attrs into the dict it
    yields (bounded: keys only, values overwritten)."""

    __slots__ = ("attrs",)

    def __init__(self):
        self.attrs: Dict[str, Any] = {}

    def __enter__(self) -> Dict[str, Any]:
        return self.attrs

    def __exit__(self, exc_type, exc, tb) -> None:
        return None


_NOOP = _NoopSpan()
# public no-op for call sites that gate a span on their own condition:
#   with (span("x") if big else spans.NOOP): ...
NOOP = _NOOP


def span(name: str, /, **attrs: Any):
    """Context manager recording one complete span; yields its attrs
    dict so values computed mid-span can ride along:

        with span("cw.store_value") as sp:
            ...
            sp["bytes"] = total

    `name` is positional-only so an attr may also be called "name"
    (e.g. task.run spans carry the task's function name).
    """
    if not _enabled:
        return _NOOP
    return _Span(name, attrs)


def start_span(name: str, /, **attrs: Any):
    """Manual begin/end variant for code whose span must bracket a
    region a `with` block can't (e.g. a finally-heavy executor body).
    Returns the span; call `finish_span(sp)` to record it, or None when
    disabled."""
    if not _enabled:
        return None
    sp = _Span(name, attrs)
    sp.__enter__()
    return sp


def finish_span(sp) -> None:
    if sp is not None:
        sp.__exit__(None, None, None)


def begin() -> float:
    """Cheapest span start: just the clock (pair with end()). The
    context-manager protocol costs ~1µs of interpreter overhead per
    span; the always-on spans on the put/get critical path use this
    pair instead so the recorder stays under 1% there."""
    return perf_counter()


def end(name: str, t0: float, /, **attrs: Any) -> None:
    """Record a span begun with begin(); no-op when disabled."""
    if not _enabled:
        return
    t1 = perf_counter()
    ring = _RING
    i = ring._i
    ring._i = i + 1
    ring._buf[i % ring.capacity] = (
        "X", name, t0, t1 - t0, _get_ident(),
        getattr(_tls, "trace_id", None), attrs or None)


def complete(name: str, dur_s: float, /, **attrs: Any) -> None:
    """Record a span that ends NOW with an externally-measured duration
    — for stages whose start lived in another process (serve's replica
    time-in-queue: the handle's submit wall stamp → execution start).
    The record lands on this process's timeline ending at the current
    instant, stretching `dur_s` back — exactly end() with a
    back-computed t0."""
    if not _enabled:
        return
    end(name, perf_counter() - max(0.0, dur_s), **attrs)


def instant(name: str, /, **attrs: Any) -> None:
    """Point-in-time event (Chrome trace ph 'i')."""
    if not _enabled:
        return
    _RING.record(("i", name, perf_counter(), 0.0,
                  _get_ident(), getattr(_tls, "trace_id", None),
                  attrs or None))


# ---------------------------------------------------------------------
# Snapshot + cluster merge
# ---------------------------------------------------------------------


def pull_snapshot(addr, method: str, timeout: float,
                  call_kwargs: Optional[Dict[str, Any]] = None):
    """One snapshot RPC with the wall-clock stamps every collector's
    offset estimate needs (peer_wall - our_wall, from the RPC midpoint
    or entry point — the caller picks the reference). Returns
    (reply, t0_wall, t1_wall) or None when the peer is unreachable —
    dead processes just drop out of the trace. `call_kwargs` rides the
    RPC verbatim (the log plane pushes its filters server-side)."""
    from ray_tpu._private import rpc as rpc_lib
    try:
        client = rpc_lib.RpcClient(tuple(addr), timeout=timeout)
        t0 = _wall_time()
        reply = client.call(method, **(call_kwargs or {}))
        t1 = _wall_time()
        client.close()
    except Exception:  # noqa: BLE001 - peer gone mid-collect
        return None
    return reply, t0, t1


def pull_snapshots(addrs, method: str, timeout: float,
                   grace_s: float = 1.0,
                   call_kwargs: Optional[Dict[str, Any]] = None
                   ) -> List[tuple]:
    """pull_snapshot fanned out to many peers on daemon threads under
    one shared deadline (per-RPC timeout + grace for the joins).
    Returns [(addr, reply, t0_wall, t1_wall)] for the peers that
    answered; unreachable peers just drop out. Every gather point (NM
    worker gathers, GCS span and metrics collects) goes through here so
    the deadline/join semantics can't silently diverge between planes."""
    from time import monotonic
    lock = threading.Lock()
    out: List[tuple] = []

    def _pull(addr) -> None:
        got = pull_snapshot(addr, method, timeout=timeout,
                            call_kwargs=call_kwargs)
        if got is None:
            return
        reply, t0, t1 = got
        with lock:
            out.append((tuple(addr), reply, t0, t1))

    threads = [threading.Thread(target=_pull, args=(a,), daemon=True)
               for a in addrs]
    for t in threads:
        t.start()
    deadline = monotonic() + timeout + grace_s
    for t in threads:
        t.join(timeout=max(0.1, deadline - monotonic()))
    return out


def gather_cluster_snapshots(gcs, nm_method: str, cw_method: str,
                             timeout: float, grace_s: float = 1.0,
                             call_kwargs: Optional[Dict[str, Any]] = None,
                             concurrent: bool = False):
    """The two-phase cluster gather both telemetry planes share:
    enumerate alive node managers + pubsub subscribers under the GCS
    lock, pull `nm_method` from every NM (each ships its own snapshot
    plus its workers' and names the worker addresses it covered), then
    pull `cw_method` from the remaining subscribers — drivers, and
    workers whose NM dropped out mid-collect. Returns
    (nm_replies, cw_replies, unreachable_node_ids) with replies in
    pull_snapshots' (addr, reply, t0, t1) form; per-snapshot
    annotation (clock offsets, tags) stays with the caller. One
    topology for spans_collect and metrics_collect, so a scheduling
    change (e.g. excluding draining nodes) can't silently diverge the
    planes. BOTH phases run under one overall deadline of
    timeout + grace_s: when unreachable NMs burn phase 1's budget, the
    subscriber phase gets only the remainder — an outage must not
    double the collect's worst case (the metrics sampler holds its
    round lock for this long against a 2s interval).

    `concurrent=True` runs both phases SIMULTANEOUSLY under the same
    deadline, skipping the covered-worker subtraction (callers dedupe
    by proc uid; peers reached twice must make the double call cheap —
    the profile plane's collect singleflight). This exists for gathers
    whose handlers BLOCK for a sampling window: serial phases would
    give drivers a different window than workers and double the
    wall-clock."""
    from time import monotonic
    deadline = monotonic() + timeout + grace_s
    with gcs._lock:
        nm_targets = [(nid, tuple(n.address))
                      for nid, n in gcs.nodes.items() if n.alive]
        sub_addrs = {tuple(addr)
                     for subs in gcs.subscribers.values()
                     for addr, _tok in subs}
    sub_addrs -= {a for _nid, a in nm_targets}  # NMs answer nm_*, not cw_*

    if concurrent:
        nm_box: List[List[tuple]] = [[]]

        def _pull_nms() -> None:
            nm_box[0] = pull_snapshots(
                [a for _nid, a in nm_targets], nm_method,
                timeout=timeout, grace_s=grace_s,
                call_kwargs=call_kwargs)

        t = threading.Thread(target=_pull_nms, daemon=True)
        t.start()
        cw_replies = pull_snapshots(sorted(sub_addrs), cw_method,
                                    timeout=timeout, grace_s=grace_s,
                                    call_kwargs=call_kwargs)
        t.join(timeout=max(0.1, deadline - monotonic()))
        nm_replies = nm_box[0]
        answered = {addr for addr, _r, _t0, _t1 in nm_replies}
        unreachable = [nid for nid, a in nm_targets if a not in answered]
        return nm_replies, cw_replies, unreachable

    nm_replies = pull_snapshots([a for _nid, a in nm_targets], nm_method,
                                timeout=timeout, grace_s=grace_s,
                                call_kwargs=call_kwargs)
    answered = {addr for addr, _r, _t0, _t1 in nm_replies}
    unreachable = [nid for nid, a in nm_targets if a not in answered]
    covered: set = set()
    for _addr, reply, _t0, _t1 in nm_replies:
        covered.update(tuple(a) for a in reply.get("worker_addrs", ()))
    # healthy phase 1 leaves the full timeout + grace; a slow one
    # shrinks phase 2 down to a 0.5s floor
    remaining = max(0.5, deadline - monotonic())
    t2 = min(timeout, remaining)
    cw_replies = pull_snapshots(sorted(sub_addrs - covered), cw_method,
                                timeout=t2,
                                grace_s=min(grace_s, remaining - t2),
                                call_kwargs=call_kwargs)
    return nm_replies, cw_replies, unreachable


def dedupe_by_uid(snaps) -> List[Dict[str, Any]]:
    """First occurrence wins — callers order the concatenation by
    preference (own snapshot first, then the estimation-quality order
    that matters to them)."""
    seen: set = set()
    unique: List[Dict[str, Any]] = []
    for snap in snaps:
        uid = snap.get("proc_uid")
        if uid in seen:
            continue
        seen.add(uid)
        unique.append(snap)
    return unique


def snapshot() -> Dict[str, Any]:
    """This process's ring, with the clock pair the merger needs to map
    monotonic span times onto this process's wall clock (and from there,
    via the collector's RPC-midpoint offset estimate, onto one cluster
    timebase)."""
    dropped = _RING.sync_dropped_metric()
    return {
        "proc_uid": PROC_UID,
        "pid": os.getpid(),
        "label": _process_label or f"proc-{os.getpid()}",
        "node_id": _node_id,
        # sampled back-to-back: wall = mono + (wall_time - mono_time)
        "mono_time": perf_counter(),
        "wall_time": _wall_time(),
        "dropped": dropped,
        "spans": _RING.snapshot_records(),
    }


def snapshot_events(snap: Dict[str, Any],
                    trace_id: Optional[str] = None) -> List[Dict[str, Any]]:
    """Convert one snapshot to Chrome-trace events on the collector's
    timebase. `clock_offset_s` (set by the collector: estimated
    peer_wall - collector_wall) is subtracted so all processes share one
    clock; within a process, span ordering is exactly the monotonic
    clock's."""
    base = (snap["wall_time"] - snap["mono_time"]
            - snap.get("clock_offset_s", 0.0))
    pid = snap.get("label") or f"proc-{snap.get('pid')}"
    out: List[Dict[str, Any]] = []
    for rec in snap.get("spans", ()):
        ph, name, t0, dur, tid, tr, attrs = rec
        if trace_id is not None and tr != trace_id:
            continue
        args: Dict[str, Any] = dict(attrs) if attrs else {}
        if tr is not None:
            args["trace_id"] = tr
        ev: Dict[str, Any] = {
            "ph": ph, "cat": "span", "name": name,
            "pid": pid, "tid": tid,
            "ts": (base + t0) * 1e6,
            "args": args,
        }
        if ph == "X":
            ev["dur"] = max(dur, 0.0) * 1e6
        else:
            ev["s"] = "t"  # instant scope: thread
        out.append(ev)
    return out


def merge_snapshots(snaps: Iterable[Dict[str, Any]],
                    trace_id: Optional[str] = None) -> List[Dict[str, Any]]:
    """Merge per-process snapshots into one event list: dedupe processes
    reached via two fan-out paths, emit process_name metadata rows, and
    sort by aligned timestamp (Chrome/Perfetto want ts-ordered JSON)."""
    events: List[Dict[str, Any]] = []
    seen: set = set()
    for snap in snaps:
        if not snap or snap.get("proc_uid") in seen:
            continue
        seen.add(snap.get("proc_uid"))
        pid = snap.get("label") or f"proc-{snap.get('pid')}"
        events.append({
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": pid,
                     **({"node_id": snap["node_id"][:12]}
                        if snap.get("node_id") else {})},
        })
        events.extend(snapshot_events(snap, trace_id=trace_id))
    events.sort(key=lambda e: e.get("ts", 0.0))
    return events
