"""Runtime-env plugins with URI caching.

reference parity: python/ray/_private/runtime_env/pip.py (pip plugin:
per-env package installs), plugin.py (plugin protocol), and the URI
cache (uri_cache.py / working_dir URI reuse): each distinct pip spec
hashes to a content URI; the install happens ONCE per node into a
cache directory keyed by that URI, and every worker whose env carries
the same spec just gets the cached site prepended to PYTHONPATH. The
reference runs this in a per-node runtime-env agent; here the node
manager calls it in-process before spawning the worker (same
serialization point — worker spawn already happens on the node
manager's spawn path).

Installs run `pip install --target <cache>/<uri>` with
`--no-build-isolation` so local source trees install without network
(this environment has no egress; callers ship wheels/source dirs and
pass `--no-index --find-links ...` via pip_args).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

_DEFAULT_CACHE = os.path.expanduser("~/.cache/ray_tpu/runtime_env")


def pip_spec(renv: Optional[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Normalize the pip field: list of requirements or
    {"packages": [...], "pip_args": [...]} -> canonical dict."""
    pip = (renv or {}).get("pip")
    if pip is None:
        return None
    if isinstance(pip, (list, tuple)):
        return {"packages": [str(p) for p in pip], "pip_args": []}
    if isinstance(pip, dict):
        return {"packages": [str(p) for p in pip.get("packages") or ()],
                "pip_args": [str(a) for a in pip.get("pip_args") or ()]}
    raise ValueError(f"runtime_env pip must be a list or dict, got {pip!r}")


def pip_uri(spec: Dict[str, Any]) -> str:
    """Content-hash URI for a pip spec (reference: pip.py get_uri)."""
    blob = json.dumps(spec, sort_keys=True).encode()
    py = f"py{sys.version_info.major}.{sys.version_info.minor}"
    return f"pip-{py}-{hashlib.sha1(blob).hexdigest()[:20]}"


# ---------------------------------------------------------------------------
# conda plugin (reference _private/runtime_env/conda.py): the env
# materializes ONCE per node into the URI cache; workers of that env run
# with <prefix>/bin/python and CONDA_PREFIX set. The create command is a
# module-level hook so chip-/binary-free CI can fake materialization
# (this box has no conda); production uses `conda env create --prefix`.
# ---------------------------------------------------------------------------


_CONDA_KEYS = {"name", "dependencies", "channels"}


def conda_spec(renv: Optional[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Normalize the conda field: env NAME (str) or an environment.yml-
    style dict -> canonical dict. Unknown dict keys fail fast — a typo
    like {'deps': [...]} must not materialize an empty environment."""
    conda = (renv or {}).get("conda")
    if conda is None:
        return None
    if isinstance(conda, str):
        return {"name": conda, "dependencies": None, "channels": None}
    if isinstance(conda, dict):
        bad = set(conda) - _CONDA_KEYS
        if bad:
            raise ValueError(
                f"unknown runtime_env conda key(s) {sorted(bad)}; "
                f"supported: {sorted(_CONDA_KEYS)}")
        return {"name": conda.get("name"),
                "dependencies": conda.get("dependencies"),
                "channels": conda.get("channels")}
    raise ValueError(
        f"runtime_env conda must be an env name or dict, got {conda!r}")


def conda_uri(spec: Dict[str, Any]) -> str:
    blob = json.dumps(spec, sort_keys=True).encode()
    return f"conda-{hashlib.sha1(blob).hexdigest()[:20]}"


def _default_conda_create(target: str, spec: Dict[str, Any]) -> None:
    """Materialize a conda prefix at `target` (production path)."""
    if spec.get("dependencies") is None and spec.get("name"):
        cmd = ["conda", "create", "--yes", "--prefix", target,
               "--clone", spec["name"]]
    else:
        env_yaml = os.path.join(os.path.dirname(target),
                                os.path.basename(target) + ".yml")
        body = {"dependencies": spec.get("dependencies") or []}
        if spec.get("channels"):
            body["channels"] = spec["channels"]
        with open(env_yaml, "w", encoding="utf-8") as f:
            json.dump(body, f)
        cmd = ["conda", "env", "create", "--prefix", target,
               "--file", env_yaml]
    proc = subprocess.run(cmd, capture_output=True, text=True,
                          timeout=1800)
    if proc.returncode != 0:
        raise RuntimeError(
            f"conda env create failed: {proc.stderr[-2000:]}")


# test seam (reference: the runtime-env agent's conda handler is mocked
# the same way in its unit tests)
CONDA_CREATE_HOOK = _default_conda_create


def container_spec(renv: Optional[Dict[str, Any]]
                   ) -> Optional[Dict[str, Any]]:
    """Normalize the container field (reference container.py):
    {"image": ..., "run_options": [...]}; image is required."""
    container = (renv or {}).get("container")
    if container is None:
        return None
    if not isinstance(container, dict) or not container.get("image"):
        raise ValueError(
            "runtime_env container must be a dict with an 'image' key, "
            f"got {container!r}")
    return {"image": str(container["image"]),
            "run_options": [str(o) for o in
                            container.get("run_options") or ()]}


def _default_container_wrap(cmd: List[str], image: str,
                            run_options: List[str],
                            env: Optional[Dict[str, str]] = None
                            ) -> List[str]:
    """Wrap a worker command in a container runtime invocation
    (production path; host networking so the worker's RPC server is
    reachable, repo mounted for the package, the worker's RAY_TPU_* /
    PYTHONPATH / env_vars forwarded — Popen's env only reaches the
    docker CLIENT, not the container)."""
    import ray_tpu
    pkg_root = os.path.dirname(os.path.dirname(
        os.path.abspath(ray_tpu.__file__)))
    env_flags: List[str] = []
    for k, v in (env or {}).items():
        env_flags += ["--env", f"{k}={v}"]
    return (["docker", "run", "--rm", "--network=host",
             f"--volume={pkg_root}:{pkg_root}:ro", *env_flags,
             *run_options, image] + cmd)


CONTAINER_WRAP_HOOK = _default_container_wrap


class RuntimeEnvManager:
    """Per-node plugin resolver with a content-addressed install cache."""

    def __init__(self, cache_dir: str = _DEFAULT_CACHE):
        self.cache_dir = cache_dir
        self._locks: Dict[str, threading.Lock] = {}
        self._guard = threading.Lock()
        # failed URIs fail fast on retry instead of re-running a long
        # doomed install per task-retry attempt
        self._failed: Dict[str, str] = {}

    def _lock_for(self, uri: str) -> threading.Lock:
        with self._guard:
            return self._locks.setdefault(uri, threading.Lock())

    def setup_pip(self, renv: Optional[Dict[str, Any]]) -> Optional[str]:
        """Ensure the env's pip packages are installed in the cache;
        returns the site dir to prepend to PYTHONPATH (None if no pip
        field). Concurrent workers for the same URI serialize on a
        lock; a `.ready` marker makes completed installs reusable
        across node-manager restarts."""
        spec = pip_spec(renv)
        if spec is None or not spec["packages"]:
            return None
        uri = pip_uri(spec)
        target = os.path.join(self.cache_dir, uri)
        marker = os.path.join(target, ".ready")
        with self._lock_for(uri):
            prior = self._failed.get(uri)
            if prior is not None:
                raise RuntimeError(
                    f"runtime_env pip install previously failed for "
                    f"{spec['packages']}: {prior}")
            if os.path.exists(marker):
                self._touch(marker)
                return target
            os.makedirs(target, exist_ok=True)
            cmd = [sys.executable, "-m", "pip", "install",
                   "--quiet", "--no-build-isolation",
                   "--target", target, *spec["pip_args"],
                   *spec["packages"]]
            logger.info("runtime_env pip install (%s): %s", uri,
                        " ".join(spec["packages"]))
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=600)
            if proc.returncode != 0:
                self._failed[uri] = proc.stderr[-500:]
                raise RuntimeError(
                    f"runtime_env pip install failed "
                    f"({spec['packages']}): {proc.stderr[-2000:]}")
            self._touch(marker)
            return target

    def setup_conda(self, renv: Optional[Dict[str, Any]]
                    ) -> Optional[str]:
        """Ensure the env's conda prefix exists in the cache; returns
        the prefix path (None if no conda field). Same URI-cache
        contract as setup_pip: one create per spec per node, `.ready`
        marker, failure memo. The worker then runs with
        <prefix>/bin/python when present (module hook materializes —
        fake in chip-free CI, `conda env create` in production)."""
        spec = conda_spec(renv)
        if spec is None:
            return None
        uri = conda_uri(spec)
        target = os.path.join(self.cache_dir, uri)
        marker = os.path.join(target, ".ready")
        with self._lock_for(uri):
            prior = self._failed.get(uri)
            if prior is not None:
                raise RuntimeError(
                    f"runtime_env conda create previously failed for "
                    f"{spec}: {prior}")
            if os.path.exists(marker):
                self._touch(marker)
                return target
            os.makedirs(target, exist_ok=True)
            logger.info("runtime_env conda create (%s)", uri)
            try:
                CONDA_CREATE_HOOK(target, spec)
            except Exception as e:  # noqa: BLE001
                self._failed[uri] = str(e)[-500:]
                raise RuntimeError(
                    f"runtime_env conda create failed ({spec}): {e}")
            self._touch(marker)
            return target

    @staticmethod
    def wrap_container(renv: Optional[Dict[str, Any]],
                       cmd: List[str],
                       env: Optional[Dict[str, str]] = None
                       ) -> List[str]:
        """Wrap a worker command per the env's container field (no-op
        without one). `env` is the spawn environment; the wrap forwards
        the worker-contract subset (RAY_TPU_*, PYTHONPATH) plus the
        env's declared env_vars into the container."""
        spec = container_spec(renv)
        if spec is None:
            return cmd
        # forward the worker contract + the env's declared env_vars —
        # NOT the whole host environment
        src = env or {}
        fwd = {k: v for k, v in src.items()
               if k.startswith("RAY_TPU_") or k in ("PYTHONPATH",
                                                    "CONDA_PREFIX")}
        for k in (renv or {}).get("env_vars") or {}:
            if str(k) in src:
                fwd[str(k)] = src[str(k)]
        return CONTAINER_WRAP_HOOK(list(cmd), spec["image"],
                                   spec["run_options"], fwd)

    @staticmethod
    def _touch(marker: str) -> None:
        with open(marker, "w", encoding="utf-8") as f:
            f.write(str(time.time()))

    def gc(self, max_entries: int = 10) -> List[str]:
        """Drop least-recently-used cached envs beyond max_entries
        (reference: URI cache eviction). Returns removed URIs."""
        import shutil
        if not os.path.isdir(self.cache_dir):
            return []
        entries = []
        for name in os.listdir(self.cache_dir):
            marker = os.path.join(self.cache_dir, name, ".ready")
            try:
                with open(marker, encoding="utf-8") as f:
                    stamp = float(f.read().strip() or 0)
            except OSError:
                stamp = 0.0
            entries.append((stamp, name))
        entries.sort(reverse=True)
        removed = []
        for _, name in entries[max_entries:]:
            shutil.rmtree(os.path.join(self.cache_dir, name),
                          ignore_errors=True)
            removed.append(name)
        return removed
