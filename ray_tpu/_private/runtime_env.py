"""Runtime-env plugins with URI caching.

reference parity: python/ray/_private/runtime_env/pip.py (pip plugin:
per-env package installs), plugin.py (plugin protocol), and the URI
cache (uri_cache.py / working_dir URI reuse): each distinct pip spec
hashes to a content URI; the install happens ONCE per node into a
cache directory keyed by that URI, and every worker whose env carries
the same spec just gets the cached site prepended to PYTHONPATH. The
reference runs this in a per-node runtime-env agent; here the node
manager calls it in-process before spawning the worker (same
serialization point — worker spawn already happens on the node
manager's spawn path).

Installs run `pip install --target <cache>/<uri>` with
`--no-build-isolation` so local source trees install without network
(this environment has no egress; callers ship wheels/source dirs and
pass `--no-index --find-links ...` via pip_args).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

_DEFAULT_CACHE = os.path.expanduser("~/.cache/ray_tpu/runtime_env")


def pip_spec(renv: Optional[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Normalize the pip field: list of requirements or
    {"packages": [...], "pip_args": [...]} -> canonical dict."""
    pip = (renv or {}).get("pip")
    if pip is None:
        return None
    if isinstance(pip, (list, tuple)):
        return {"packages": [str(p) for p in pip], "pip_args": []}
    if isinstance(pip, dict):
        return {"packages": [str(p) for p in pip.get("packages") or ()],
                "pip_args": [str(a) for a in pip.get("pip_args") or ()]}
    raise ValueError(f"runtime_env pip must be a list or dict, got {pip!r}")


def pip_uri(spec: Dict[str, Any]) -> str:
    """Content-hash URI for a pip spec (reference: pip.py get_uri)."""
    blob = json.dumps(spec, sort_keys=True).encode()
    py = f"py{sys.version_info.major}.{sys.version_info.minor}"
    return f"pip-{py}-{hashlib.sha1(blob).hexdigest()[:20]}"


class RuntimeEnvManager:
    """Per-node plugin resolver with a content-addressed install cache."""

    def __init__(self, cache_dir: str = _DEFAULT_CACHE):
        self.cache_dir = cache_dir
        self._locks: Dict[str, threading.Lock] = {}
        self._guard = threading.Lock()
        # failed URIs fail fast on retry instead of re-running a long
        # doomed install per task-retry attempt
        self._failed: Dict[str, str] = {}

    def _lock_for(self, uri: str) -> threading.Lock:
        with self._guard:
            return self._locks.setdefault(uri, threading.Lock())

    def setup_pip(self, renv: Optional[Dict[str, Any]]) -> Optional[str]:
        """Ensure the env's pip packages are installed in the cache;
        returns the site dir to prepend to PYTHONPATH (None if no pip
        field). Concurrent workers for the same URI serialize on a
        lock; a `.ready` marker makes completed installs reusable
        across node-manager restarts."""
        spec = pip_spec(renv)
        if spec is None or not spec["packages"]:
            return None
        uri = pip_uri(spec)
        target = os.path.join(self.cache_dir, uri)
        marker = os.path.join(target, ".ready")
        with self._lock_for(uri):
            prior = self._failed.get(uri)
            if prior is not None:
                raise RuntimeError(
                    f"runtime_env pip install previously failed for "
                    f"{spec['packages']}: {prior}")
            if os.path.exists(marker):
                self._touch(marker)
                return target
            os.makedirs(target, exist_ok=True)
            cmd = [sys.executable, "-m", "pip", "install",
                   "--quiet", "--no-build-isolation",
                   "--target", target, *spec["pip_args"],
                   *spec["packages"]]
            logger.info("runtime_env pip install (%s): %s", uri,
                        " ".join(spec["packages"]))
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=600)
            if proc.returncode != 0:
                self._failed[uri] = proc.stderr[-500:]
                raise RuntimeError(
                    f"runtime_env pip install failed "
                    f"({spec['packages']}): {proc.stderr[-2000:]}")
            self._touch(marker)
            return target

    @staticmethod
    def _touch(marker: str) -> None:
        with open(marker, "w", encoding="utf-8") as f:
            f.write(str(time.time()))

    def gc(self, max_entries: int = 10) -> List[str]:
        """Drop least-recently-used cached envs beyond max_entries
        (reference: URI cache eviction). Returns removed URIs."""
        import shutil
        if not os.path.isdir(self.cache_dir):
            return []
        entries = []
        for name in os.listdir(self.cache_dir):
            marker = os.path.join(self.cache_dir, name, ".ready")
            try:
                with open(marker, encoding="utf-8") as f:
                    stamp = float(f.read().strip() or 0)
            except OSError:
                stamp = 0.0
            entries.append((stamp, name))
        entries.sort(reverse=True)
        removed = []
        for _, name in entries[max_entries:]:
            shutil.rmtree(os.path.join(self.cache_dir, name),
                          ignore_errors=True)
            removed.append(name)
        return removed
