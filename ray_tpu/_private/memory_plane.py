"""Memory attribution plane: who owns every byte of object store.

reference parity: `ray memory` (scripts.py:1921) backed by each core
worker's reference table (reference_count.h) joined with plasma
residency. Here the join happens at the GCS: `memory_collect` gathers

  - every core worker's reference-table snapshot (`cw_memory_snapshot`:
    owned objects + their location, local ref counts, submitted-arg
    pins, borrows held from remote owners, borrower pins granted,
    reader leases on pulled replicas, and — behind
    `Config.memory_callsite_capture` — the put()/.remote() callsite
    that created each owned object), and
  - every node's store residency (`nm_memory_snapshot` wraps
    `store_list`: size, pinned, leases, spilled, age),

into one cluster object table (`build_object_table`): per object, who
owns it, what holds it alive (pins / borrows / leases), and where bytes
are resident (primary = the owner's recorded location; other copies are
replicas). `group_rows` aggregates by callsite / actor / node / owner
for `ray_tpu memory --group-by`.

The leak probes (metrics_plane.Watchdog._probe_memory) consume compact
digests of the same data that ride the ordinary 2s metrics harvest, so
a leaked pin alerts within two harvest intervals with no extra fan-out:
an object pinned in a store that no live owner claims (dead-owner
leak), store reader leases no live process accounts for (orphaned
lease), and store-resident objects their owner already freed
(refcount-vs-residency mismatch).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

# digest keys (attached to metrics-plane process snapshots)
PROC_DIGEST_KEY = "memory"
STORE_DIGEST_KEY = "store_objects"


# ---------------------------------------------------------------------
# Cluster object table (the join behind `ray_tpu memory`)
# ---------------------------------------------------------------------


def _addr_key(addr: Any) -> Optional[str]:
    if not addr:
        return None
    return f"{addr[0]}:{addr[1]}"


def build_object_table(proc_snaps: List[Dict[str, Any]],
                       node_snaps: List[Dict[str, Any]]
                       ) -> List[Dict[str, Any]]:
    """Join reference-table snapshots with store residency into one row
    per object id. Owner fields come from the snapshot that OWNS the
    object; borrow/lease counts sum over every live process."""
    rows: Dict[str, Dict[str, Any]] = {}

    def row(oid: str) -> Dict[str, Any]:
        r = rows.get(oid)
        if r is None:
            r = rows[oid] = {
                "object_id": oid, "size": None,
                "owner": None, "owner_worker_id": None,
                "owner_actor_id": None, "owner_node_id": None,
                "owner_pid": None, "owner_state": None,
                "primary_store": None,
                "local_refs": 0, "arg_pins": 0,
                "borrower_pins": 0, "borrowers": 0,
                "replica_leases": 0, "borrow_holders": 0,
                "callsite": None,
                "residency": [], "resident_bytes": 0,
            }
        return r

    for snap in proc_snaps:
        for oid, rec in (snap.get("objects") or {}).items():
            r = row(oid)
            if rec.get("owned"):
                r["owner"] = snap.get("label")
                r["owner_worker_id"] = snap.get("worker_id")
                r["owner_actor_id"] = snap.get("actor_id")
                r["owner_node_id"] = snap.get("node_id")
                r["owner_pid"] = snap.get("pid")
                r["owner_state"] = rec.get("loc")
                r["primary_store"] = _addr_key(rec.get("store_addr"))
                if rec.get("size") is not None:
                    r["size"] = rec["size"]
                if rec.get("callsite"):
                    r["callsite"] = rec["callsite"]
            r["local_refs"] += int(rec.get("local_refs") or 0)
            r["arg_pins"] += int(rec.get("arg_pins") or 0)
            bp = rec.get("borrower_pins") or {}
            r["borrower_pins"] += sum(bp.values())
            r["borrowers"] += len(bp)
            r["replica_leases"] += int(rec.get("replica_leases") or 0)
            if rec.get("borrowed_from"):
                r["borrow_holders"] += 1

    for nsnap in node_snaps:
        node_id = nsnap.get("node_id")
        store_addr = _addr_key(nsnap.get("store_addr"))
        for ent in nsnap.get("store") or ():
            oid = ent["object_id"]
            r = row(oid)
            primary = (r["primary_store"] is not None
                       and store_addr == r["primary_store"])
            r["residency"].append({
                "node_id": node_id,
                "size": ent.get("size"),
                "pinned": ent.get("pinned"),
                "leases": ent.get("leases"),
                "spilled": ent.get("spilled"),
                "age_s": ent.get("age_s"),
                "primary": primary,
            })
            r["resident_bytes"] += int(ent.get("size") or 0)
            if r["size"] is None:
                r["size"] = ent.get("size")
    return sorted(rows.values(),
                  key=lambda r: -(r["resident_bytes"] or r["size"] or 0))


_GROUP_KEYS = ("callsite", "actor", "node", "owner")


def group_rows(rows: List[Dict[str, Any]], by: str,
               top: Optional[int] = None) -> List[Dict[str, Any]]:
    """Aggregate the object table for `--group-by callsite|actor|node|
    owner`: object count, bytes, and alive-holder totals per group."""
    if by not in _GROUP_KEYS:
        raise ValueError(f"group_by must be one of {_GROUP_KEYS}")
    groups: Dict[str, Dict[str, Any]] = {}
    for r in rows:
        if by == "callsite":
            key = r.get("callsite") or "(callsite capture off — set " \
                "RAY_TPU_memory_callsite_capture=1)"
        elif by == "actor":
            key = r.get("owner_actor_id") or "(no actor)"
        elif by == "node":
            nodes = [res["node_id"] for res in r["residency"]
                     if res.get("node_id")] or [r.get("owner_node_id")]
            key = None  # handled below (an object can span nodes)
        else:
            key = r.get("owner") or "(owner gone)"
        keys = ([str(n)[:12] if n else "(unknown node)" for n in nodes]
                if by == "node" else [key])
        for k in keys:
            g = groups.setdefault(k, {
                by: k, "objects": 0, "bytes": 0, "pinned": 0,
                "leases": 0, "borrower_pins": 0})
            g["objects"] += 1
            g["bytes"] += int(r.get("resident_bytes")
                              or r.get("size") or 0)
            g["pinned"] += sum(int(res.get("pinned") or 0)
                               for res in r["residency"])
            g["leases"] += sum(int(res.get("leases") or 0)
                               for res in r["residency"])
            g["borrower_pins"] += int(r.get("borrower_pins") or 0)
    out = sorted(groups.values(), key=lambda g: -g["bytes"])
    return out[:top] if top else out


# ---------------------------------------------------------------------
# Harvest digests (ride the metrics plane; inputs to the leak probes)
# ---------------------------------------------------------------------


def store_digest(store_list: List[Dict[str, Any]],
                 cap: int = 512) -> Tuple[List[List[Any]], bool]:
    """Held-alive store entries (pinned or leased) as compact tuples
    for the harvest: [oid, size, pinned, leases, spilled, age_s].
    Returns (entries, truncated)."""
    held = [[e["object_id"], e.get("size"), e.get("pinned"),
             e.get("leases"), e.get("spilled"), e.get("age_s")]
            for e in store_list
            if (e.get("pinned") or 0) > 0 or (e.get("leases") or 0) > 0]
    held.sort(key=lambda t: -(t[1] or 0))
    return held[:cap], len(held) > cap
