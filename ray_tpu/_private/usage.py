"""Usage stats: local-only feature-usage reporting, opt-out.

reference parity: _private/usage/usage_lib.py — the reference pings a
telemetry endpoint unless RAY_USAGE_STATS_ENABLED=0; this build NEVER
egresses (zero-network policy): it records the same feature-usage
report as a JSON file in the session dir so operators can inspect what
their jobs exercised. Same env-var contract: RAY_TPU_USAGE_STATS_ENABLED
(default on; "0"/"false" disables).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Set

_lock = threading.Lock()
_features: Set[str] = set()
_extra: Dict[str, Any] = {}


def usage_stats_enabled() -> bool:
    return os.environ.get("RAY_TPU_USAGE_STATS_ENABLED",
                          "1").lower() not in ("0", "false", "no")


def record_library_usage(name: str) -> None:
    """Called by library entry points (train/tune/rllib/data/serve)."""
    if not usage_stats_enabled():
        return
    with _lock:
        _features.add(name)


def record_extra_usage_tag(key: str, value: Any) -> None:
    if not usage_stats_enabled():
        return
    with _lock:
        _extra[key] = value


def usage_report() -> Dict[str, Any]:
    import platform
    with _lock:
        return {
            "schema_version": "0.1",
            "collected_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "python_version": platform.python_version(),
            "os": platform.system().lower(),
            "libraries_used": sorted(_features),
            "extra_tags": dict(_extra),
        }


def write_usage_report(target_dir: str,
                       filename: str = "usage_stats.json") -> str:
    """Persist the report as a local file (no egress). No-op when the
    opt-out env var disables usage stats."""
    path = os.path.join(target_dir, filename)
    if not usage_stats_enabled():
        return path
    try:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(usage_report(), f, indent=2)
    except OSError:
        pass
    return path
