"""Per-job goodput/badput wall-time ledger.

The TPU-fleet "Goodput" discipline: every second of a training job's
gang lifetime is bucketed into exactly one of BUCKETS, always-on, so
`of the last hour of wall time, how much trained the model?` has a
first-class answer. The driver-side training loops (backend_executor
result rounds, LearnerGroup.update, the IMPALA/DQN learner threads)
bind a ledger to their thread and wrap their phases in `bucket(...)`
scopes; cross-cutting signals that already exist re-attribute time
INSIDE an open scope instead of adding new timers:

  - the jax sentinel's backend-compile duration event charges
    `compile` against the open window (util/jax_sentinel.py fires it
    synchronously on the jit-calling thread),
  - DeviceFeed.get charges its blocked wait to `feed_stall` /
    `replay_stall` (rllib/utils/device_feed.py),
  - elastic re-forms open `elastic_reconfig` / `wedge_recovery` for
    the whole drain->reform->resume window (train/elastic.py).

Accounting invariant: per job, sum(bucket seconds) == wall time since
the ledger was created (to clock precision). Unattributed time is
`idle` — which is why graftlint RT024 flags bare sleeps inside
instrumented loops: they read as phantom idle.

Export: the harvest sampler flushes per-bucket deltas into
`ray_tpu_goodput_seconds_total{job,bucket}` (rides the normal metrics
fan-out, lands in the durable history tiers), and a snapshot extra
carries the in-flight bucket + lifetime totals per job so a forced
`ray_tpu goodput` sees sub-harvest state.

Buckets nest innermost-wins (a checkpoint_save inside a productive
window attributes to checkpoint_save). `charge()` re-attribution is
borrow-based: the charged seconds are deducted from the enclosing
window when it next advances, so wall time is conserved.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, Iterator, List, Optional, Tuple

PRODUCTIVE = "productive_step"

BUCKETS = (
    PRODUCTIVE,
    "compile",
    "checkpoint_save",
    "checkpoint_restore",
    "elastic_reconfig",
    "wedge_recovery",
    "feed_stall",
    "replay_stall",
    "idle",
)

METRIC = "ray_tpu_goodput_seconds_total"
SNAPSHOT_KEY = "goodput"

_tls = threading.local()

_registry_lock = threading.Lock()
_LEDGERS: Dict[str, "GoodputLedger"] = {}
_hooks_registered = False
_counter: Any = None


class GoodputLedger:
    """Wall-time classifier for one job.

    Thread model: one *driving* thread owns the bucket stack (the loop
    that binds the ledger); `charge()` may be called from any thread
    holding the same ledger binding (sentinel compile events fire on
    the jit-calling thread, which IS the driving thread). A plain lock
    — not TracedLock — guards state: this sits inside the step hot
    path and must stay nanoseconds-cheap.
    """

    def __init__(self, job: str, time_fn=time.monotonic):
        self.job = job
        self._now = time_fn
        self._lock = threading.Lock()
        self._totals: Dict[str, float] = {b: 0.0 for b in BUCKETS}
        self._stack: List[str] = []
        self._mark = self._now()
        self._born = self._mark
        # seconds already charge()d against the open window: deducted
        # from the next advance so wall time is conserved
        self._borrowed = 0.0
        self._exported: Dict[str, float] = {b: 0.0 for b in BUCKETS}
        # when the current stack top (or idle) became the attribution
        # target — purely informational (snapshot bucket_age_s)
        self._top_since = self._mark

    # -- core accounting ----------------------------------------------

    def _advance_locked(self, now: float) -> None:
        dt = now - self._mark
        if dt > 0.0:
            borrow = min(self._borrowed, dt)
            dt -= borrow
            self._borrowed -= borrow
            if dt > 0.0:
                top = self._stack[-1] if self._stack else "idle"
                self._totals[top] = self._totals.get(top, 0.0) + dt
        self._mark = now

    def push(self, name: str) -> None:
        now = self._now()
        with self._lock:
            self._advance_locked(now)
            self._stack.append(name)
            self._top_since = now

    def pop(self, name: str) -> None:
        now = self._now()
        with self._lock:
            self._advance_locked(now)
            if self._stack and self._stack[-1] == name:
                self._stack.pop()
                self._top_since = now
            elif name in self._stack:
                # unbalanced exit (an exception skipped inner pops):
                # unwind through the matching entry
                while self._stack:
                    if self._stack.pop() == name:
                        break
                self._top_since = now

    @contextmanager
    def bucket(self, name: str) -> Iterator[None]:
        self.push(name)
        try:
            yield
        finally:
            self.pop(name)

    def charge(self, name: str, seconds: float) -> None:
        """Attribute `seconds` of already-elapsed wall time to `name`,
        borrowing them back from the enclosing window. Clamped to the
        unaccounted span so a mis-measured duration can never mint
        time that didn't pass."""
        if seconds <= 0.0:
            return
        now = self._now()
        with self._lock:
            avail = max(0.0, now - self._mark - self._borrowed)
            dt = min(float(seconds), avail)
            if dt <= 0.0:
                return
            self._totals[name] = self._totals.get(name, 0.0) + dt
            self._borrowed += dt

    # -- views ---------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        now = self._now()
        with self._lock:
            self._advance_locked(now)
            return {
                "job": self.job,
                "bucket": self._stack[-1] if self._stack else "idle",
                "bucket_age_s": max(0.0, now - self._top_since),
                "uptime_s": now - self._born,
                "totals": {b: round(v, 6)
                           for b, v in self._totals.items() if v > 0.0},
            }

    def totals(self) -> Dict[str, float]:
        now = self._now()
        with self._lock:
            self._advance_locked(now)
            return dict(self._totals)

    def flush_deltas(self) -> Dict[str, float]:
        """Per-bucket seconds accrued since the last flush (harvest
        sampler feed for the monotone counter)."""
        now = self._now()
        with self._lock:
            self._advance_locked(now)
            out = {}
            for b, v in self._totals.items():
                d = v - self._exported.get(b, 0.0)
                if d > 1e-9:
                    out[b] = d
                    self._exported[b] = v
            return out

    # -- thread binding ------------------------------------------------

    def bind(self) -> "GoodputLedger":
        """Make this ledger the current thread's ledger (the thread
        whose bucket()/charge() calls should land here)."""
        _tls.ledger = self
        return self


# ---------------------------------------------------------------------
# Module-level API: call sites never hold a ledger reference
# ---------------------------------------------------------------------


def ledger(job: str, time_fn=time.monotonic) -> GoodputLedger:
    """Get-or-create the process-wide ledger for `job` and register
    the harvest hooks on first use."""
    with _registry_lock:
        led = _LEDGERS.get(job)
        if led is None:
            led = _LEDGERS[job] = GoodputLedger(job, time_fn=time_fn)
        _register_hooks()
        return led


def current() -> Optional[GoodputLedger]:
    return getattr(_tls, "ledger", None)


def unbind() -> None:
    _tls.ledger = None


class _NoopCtx:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return None


_NOOP = _NoopCtx()


def bucket(name: str):
    """Bucket scope on the current thread's ledger; shared no-op when
    no ledger is bound (library code can instrument unconditionally)."""
    led = current()
    if led is None:
        return _NOOP
    return led.bucket(name)


def charge(name: str, seconds: float) -> None:
    """Re-attribute elapsed seconds on the current thread's ledger
    (no-op unbound)."""
    led = current()
    if led is not None:
        led.charge(name, seconds)


def enter(name: str) -> Optional[Tuple[GoodputLedger, str]]:
    """Open a bucket without a lexical scope (elastic re-forms open on
    detect, close on finish/abort). Returns an opaque token for
    exit()."""
    led = current()
    if led is None:
        return None
    led.push(name)
    return (led, name)


def exit(token: Optional[Tuple[GoodputLedger, str]]) -> None:  # noqa: A001
    if token is not None:
        token[0].pop(token[1])


def summary() -> Dict[str, Any]:
    """Per-job lifetime bucket totals + productive fraction from THIS
    process's ledgers (the bench tools embed this in their JSON so a
    run's goodput rides along with its throughput numbers; the
    cluster-wide view is util.state.goodput())."""
    with _registry_lock:
        ledgers = list(_LEDGERS.values())
    out: Dict[str, Any] = {}
    for led in ledgers:
        totals = led.totals()
        acc = sum(totals.values())
        out[led.job] = {
            "buckets": {b: round(v, 3)
                        for b, v in totals.items() if v > 1e-3},
            "accounted_s": round(acc, 3),
            "productive_frac": round(totals.get(PRODUCTIVE, 0.0) / acc,
                                     4) if acc > 0 else None,
        }
    return out


# ---------------------------------------------------------------------
# Harvest integration
# ---------------------------------------------------------------------


def _register_hooks() -> None:
    global _hooks_registered, _counter
    if _hooks_registered:
        return
    from ray_tpu._private import metrics_plane
    from ray_tpu.util.metrics import Counter, get_or_create
    _counter = get_or_create(
        Counter, METRIC,
        description="wall seconds of gang lifetime by goodput bucket "
                    "(productive_step is goodput; everything else is "
                    "badput — see README 'Goodput & metrics history')",
        tag_keys=("job", "bucket"))
    metrics_plane.register_sampler("goodput", _sample)
    metrics_plane.register_snapshot_extra(SNAPSHOT_KEY, _snapshot_extra)
    _hooks_registered = True


def _sample() -> None:
    with _registry_lock:
        ledgers = list(_LEDGERS.values())
    for led in ledgers:
        for b, d in led.flush_deltas().items():
            _counter.inc(d, tags={"job": led.job, "bucket": b})


def _snapshot_extra() -> Dict[str, Any]:
    with _registry_lock:
        ledgers = list(_LEDGERS.values())
    return {"jobs": {led.job: led.snapshot() for led in ledgers}}


def _reset_for_tests() -> None:
    global _hooks_registered, _counter
    with _registry_lock:
        _LEDGERS.clear()
        _hooks_registered = False
        _counter = None
    _tls.ledger = None
