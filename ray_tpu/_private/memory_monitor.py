"""Node memory monitor + worker-killing policy (OOM defense).

reference parity: src/ray/common/memory_monitor.h:52 (cgroup//proc usage
polling against memory_usage_threshold, ray_config_def.h:77 default
0.95) feeding the raylet's worker-killing policies
(worker_killing_policy_retriable_fifo.h: kill the newest retriable task
first — it loses the least work and its owner retries it elsewhere).
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Callable, Optional

logger = logging.getLogger(__name__)


def system_memory_usage_fraction() -> float:
    """1 - MemAvailable/MemTotal from /proc/meminfo; test override via
    RAY_TPU_testing_fake_memory_usage."""
    fake = os.environ.get("RAY_TPU_testing_fake_memory_usage")
    if fake:
        return float(fake)
    try:
        fields = {}
        with open("/proc/meminfo") as f:
            for line in f:
                name, _, rest = line.partition(":")
                fields[name] = int(rest.strip().split()[0])
        total = fields.get("MemTotal", 0)
        avail = fields.get("MemAvailable", 0)
        if total <= 0:
            return 0.0
        return 1.0 - avail / total
    except OSError:
        return 0.0


class MemoryMonitor:
    """Polls memory usage; above threshold, invokes the kill callback
    once per breach-poll until usage recovers."""

    def __init__(self, kill_callback: Callable[[], bool],
                 threshold: float, period_s: float,
                 usage_fn: Optional[Callable[[], float]] = None):
        self._kill = kill_callback
        self.threshold = threshold
        self.period_s = period_s
        self._usage = usage_fn or system_memory_usage_fraction
        self.num_kills = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="memory-monitor")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.period_s):
            try:
                usage = self._usage()
            except Exception:  # noqa: BLE001 - probe raced an exit; retry next tick
                continue
            if usage < self.threshold:
                continue
            logger.warning(
                "memory usage %.1f%% over threshold %.1f%%: engaging "
                "worker-killing policy", usage * 100,
                self.threshold * 100)
            try:
                if self._kill():
                    self.num_kills += 1
            except Exception:  # noqa: BLE001
                logger.exception("memory-pressure kill failed")

    def stop(self) -> None:
        self._stop.set()
