"""Tiny length-prefixed RPC layer over TCP sockets.

TPU-native rebuild of the reference's gRPC control plane (reference:
src/ray/rpc/grpc_server.h, grpc_client.h). The reference wraps gRPC services;
we use a minimal framed-pickle protocol: every process that serves RPCs hosts
an RpcServer with named handlers; clients hold pooled persistent connections.

Wire format: 8-byte big-endian length | pickled (method, kwargs) request,
same framing for the pickled (status, payload) reply.
"""

from __future__ import annotations

import logging
import pickle
import random
import socket
import socketserver
import struct
import threading
import time
import traceback
from typing import Any, Callable, Dict, Optional, Tuple

import itertools

from ray_tpu._private import chaos as chaos_lib
from ray_tpu._private import spans as _spans

_LEN = struct.Struct(">Q")

# Server-handle spans are edge-sampled (Dapper-style): most handlers are
# tens of µs and a per-dispatch record would tax every RPC by ~1%; one
# in K still shows where server time goes, scaled by the rate. Blocking
# ops keep their own always-on spans (store.wait / store.pull).
_SERVER_SPAN_SAMPLE_K = 16
_server_span_tick = itertools.count()


def find_free_port(host: str = "127.0.0.1") -> int:
    """Bind-and-release a port (rendezvous endpoints: jax coordinator,
    torch MASTER_PORT, learner gangs)."""
    sock = socket.socket()
    sock.bind((host, 0))
    port = sock.getsockname()[1]
    sock.close()
    return port


class RpcError(Exception):
    """Remote handler raised; carries the remote traceback string."""


class ConnectionLost(Exception):
    """Peer went away mid-call."""


def _send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(n - len(buf), 4 << 20))
        if not chunk:
            raise ConnectionLost("socket closed")
        buf += chunk
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> bytes:
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    return _recv_exact(sock, n)


def _chaos_delay() -> None:
    """Compat shim. The randomized handler delay that used to live here
    (reference asio_chaos.cc:29-40, env RAY_TPU_testing_rpc_delay_us) is
    now a startup-installed `delay` rule in the chaos plane
    (_private/chaos.py; the env vars still work but are deprecated —
    see _private/config.py). Kept for callers/tests that invoke the
    delay point directly."""
    chaos_lib.on_server_dispatch("_legacy_delay_hook")


class _Handler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        server: RpcServer = self.server.rpc_server  # type: ignore[attr-defined]
        sock = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # track live connections so stop() can close them — otherwise
        # handler threads outlive the server and keep ANSWERING against
        # the stopped instance (a restarted server on the same port then
        # never sees those clients). The stopping flag closes the race
        # where a connection accepted around stop() registers after the
        # snapshot and lingers anyway.
        with self.server.conn_lock:  # type: ignore[attr-defined]
            if self.server.stopping:  # type: ignore[attr-defined]
                try:
                    sock.close()
                except OSError:
                    pass
                return
            self.server.conns.add(sock)  # type: ignore[attr-defined]
        try:
            while True:
                req = _recv_frame(sock)
                item = pickle.loads(req)
                if len(item) == 3:
                    method, kwargs, oneway = item
                else:
                    (method, kwargs), oneway = item, False
                with _spans.span("rpc.server", method=method,
                                 bytes=len(req),
                                 sampled=_SERVER_SPAN_SAMPLE_K) \
                        if next(_server_span_tick) \
                        % _SERVER_SPAN_SAMPLE_K == 0 else _spans.NOOP:
                    # chaos plane server hook: delay / kill_worker rules
                    # (subsumes the old _chaos_delay env-var injection)
                    chaos_lib.on_server_dispatch(method)
                    try:
                        handler = server.handlers[method]
                    except KeyError:
                        reply = ("err", f"no such rpc method: {method}")
                    else:
                        try:
                            result = handler(**kwargs)
                            reply = ("ok", result)
                        except Exception as e:  # noqa: BLE001 - to caller
                            # Typed propagation: the client re-raises the
                            # real exception class (e.g.
                            # ObjectStoreFullError from a store handler) so
                            # callers can catch specifically; the traceback
                            # string rides along for diagnostics.
                            try:
                                blob = pickle.dumps(e, protocol=5)
                            except Exception:  # noqa: BLE001 - unpicklable
                                blob = None
                            reply = ("err", (blob, traceback.format_exc()))
                    if oneway:
                        # fire-and-forget frame: no reply; surface handler
                        # errors in the server log (callers detect failures
                        # out-of-band — death pubsub, connection loss)
                        if reply[0] == "err":
                            logging.getLogger(__name__).warning(
                                "oneway rpc %s failed: %s", method,
                                reply[1])
                        continue
                    _send_frame(sock, pickle.dumps(reply, protocol=5))
        except (ConnectionLost, ConnectionResetError, BrokenPipeError, OSError):
            return
        finally:
            with self.server.conn_lock:  # type: ignore[attr-defined]
                self.server.conns.discard(sock)  # type: ignore[attr-defined]


class _ThreadingTCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.conns: set = set()
        self.conn_lock = threading.Lock()
        self.stopping = False


class RpcServer:
    """Threaded RPC server; one thread per client connection."""

    def __init__(self, handlers: Dict[str, Callable], host: str = "127.0.0.1",
                 port: int = 0):
        self.handlers = dict(handlers)
        self._server = _ThreadingTCPServer((host, port), _Handler)
        self._server.rpc_server = self  # type: ignore[attr-defined]
        self.address: Tuple[str, int] = self._server.server_address[:2]
        self._thread = threading.Thread(
            target=self._server.serve_forever, kwargs={"poll_interval": 0.1},
            daemon=True, name=f"rpc-server-{self.address[1]}")
        self._thread.start()

    def register(self, method: str, fn: Callable) -> None:
        self.handlers[method] = fn

    def stop(self) -> None:
        try:
            self._server.shutdown()
            self._server.server_close()
        except Exception:  # noqa: BLE001 - server already stopped
            pass
        # sever live connections so clients fail over immediately
        # (e.g. to a restarted server on the same port) instead of
        # talking to this zombie's handler threads
        with self._server.conn_lock:
            self._server.stopping = True
            conns = list(self._server.conns)
        for sock in conns:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass


# Methods safe to RESEND even after a send apparently succeeded (the
# peer may have executed them): reads, pings, and naturally-idempotent
# writes. A send into a dead peer's kernel buffer "succeeds" locally, so
# without this the first call after a server restart always fails.
_IDEMPOTENT_PREFIXES = ("get_", "list_", "kv_get", "kv_keys", "nm_get",
                        "nm_list", "cl_get", "cl_list",
                        # token-keyed add/remove + snapshot reads
                        "wait_graph_",
                        # metrics plane: harvest/exposition/history
                        # reads and last-writer-wins tuning
                        "metrics_")
_IDEMPOTENT_METHODS = frozenset({
    "ping", "nm_ping", "report_resources", "register_node", "subscribe",
    "unsubscribe",
    "next_job_id", "cluster_resources", "available_resources",
    # object-store reads (store_wait is excluded: pin=True takes a
    # lease, and a blind resend would double-count it)
    "store_contains", "store_stats", "store_list", "store_arena_info",
    # metrics-plane snapshot reads (registry reads; samplers only
    # overwrite gauges, so a retried snapshot is harmless)
    "cw_metrics_snapshot", "nm_metrics_snapshot",
    # debug-plane reads (tail-index/postmortem-ring queries)
    "logs_query", "nm_logs_snapshot", "cw_logs_snapshot",
    "postmortem_list", "postmortem_get",
    # memory-plane reads (reference-table/residency snapshots). The
    # profile RPCs are deliberately NOT here: a blind resend of a
    # collect would run a second multi-second sampling window, and
    # cw_profile_snapshot(reset=True) is destructive — a retry after a
    # dropped reply would find the already-handed-over table and
    # silently return an empty profile.
    "memory_collect", "nm_memory_snapshot", "cw_memory_snapshot",
    "nm_profile_workers",
    # ownership-plane reads (RefState/LeaseState + transition-ring
    # snapshots)
    "ownership_collect", "nm_ownership_snapshot",
    "cw_ownership_snapshot",
    # ownership-protocol writes that are duplicate-safe BY DESIGN, so a
    # retry after a sent-but-reply-lost attempt cannot corrupt state:
    # cw_task_done/cw_task_failed dedup on the owner's entry.done (a
    # duplicate settle is a recorded no-op in the lease machine),
    # nm_return_worker releases a lease id at most once. A lost
    # completion report used to strand the task (and its arg pins)
    # forever — the ownership fuzzer's drop schedules hit exactly this.
    "cw_task_done", "cw_task_failed", "nm_return_worker",
    # batched forms of the above: each element dedups exactly like its
    # singleton twin, so replaying a whole batch is as safe as replaying
    # one report. cw_lease_granted_batch rides note_grant's dedup ring;
    # nm_lease_request_batch re-queues under the SAME lease ids only on
    # the client's resend-after-send-failure path (the NM never saw the
    # first copy), and a duplicate grant for an id is dropped by the
    # owner anyway.
    "cw_task_done_batch", "nm_lease_request_batch", "cw_lease_granted_batch",
    # pure read: the borrower's current claim set (anti-entropy sweep)
    "cw_claims",
    # actor-creation push (the NM's only call-form w_push_task): the
    # executor dedups creation specs by task_id, so a resend after a
    # lost reply queues nothing. Without the retry budget, two
    # back-to-back connect failures against a freshly-spawned worker
    # (loaded box, listener backlog) declared the actor dead before it
    # ever ran. Lease-path pushes ride send_oneway and are unaffected.
    "w_push_task",
})


def _is_idempotent(method: str) -> bool:
    return method.startswith(_IDEMPOTENT_PREFIXES) or \
        method in _IDEMPOTENT_METHODS


class RpcClient:
    """Client with one persistent connection, thread-safe via a lock.

    For concurrent calls from many threads use one client per thread or a
    ClientPool; a single in-flight call holds the lock end-to-end (the
    protocol is strictly request/reply per connection).
    """

    def __init__(self, address: Tuple[str, int], timeout: Optional[float] = None):
        self.address = tuple(address)
        self._timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()

    def _connect(self) -> socket.socket:
        # Holding _lock across the connect is this class's CONTRACT:
        # the lock serializes the one request/reply channel, and every
        # caller queued behind it needs the connection up anyway.
        # Concurrency comes from one-client-per-thread / ClientPool.
        sock = socket.create_connection(  # graftlint: disable=RT015
            self.address, timeout=self._timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    # Reconnect-retry budget for idempotent control-plane calls: a
    # transient drop (server restart, chaos drop_connection on the peer,
    # GC pause) must not cascade into OwnerDiedError/ConnectionLost at
    # the caller. Capped exponential backoff with full jitter; the first
    # retry is immediate (the common case is a stale pooled connection).
    IDEMPOTENT_RETRIES = 4
    _BACKOFF_BASE_S = 0.05
    _BACKOFF_CAP_S = 1.0

    def call(self, method: str, **kwargs: Any) -> Any:
        payload = pickle.dumps((method, kwargs), protocol=5)
        # always-on span via the cheap begin/end pair; covers lock wait
        # + send + recv — the latency the CALLER observes (lock
        # contention on a shared client is real stall)
        _t0 = _spans.begin()
        try:
            return self._call_locked(method, payload)
        finally:
            _spans.end("rpc.client", _t0, method=method,
                       bytes=len(payload))

    def _call_locked(self, method: str, payload: bytes) -> Any:
        idempotent = _is_idempotent(method)
        max_attempts = 1 + (self.IDEMPOTENT_RETRIES if idempotent else 1)
        with self._lock:
            for attempt in range(max_attempts):
                sent = False
                try:
                    # chaos plane client hook: drop_connection /
                    # partition rules raise ConnectionLost here, before
                    # anything is sent — each retry attempt re-consults
                    # the policy, so an injected drop behaves exactly
                    # like a real broken socket (retried with backoff
                    # for idempotent methods, surfaced otherwise)
                    chaos_lib.on_client_call(method, self.address)
                    if self._sock is None:
                        self._sock = self._connect()
                    _send_frame(self._sock, payload)
                    sent = True
                    reply = _recv_frame(self._sock)
                    break
                except (ConnectionLost, ConnectionResetError, BrokenPipeError,
                        OSError):
                    self.close_locked()
                    # Retry when the request never left this client
                    # (stale pooled connection / refused connect) OR the
                    # method is idempotent. After a successful send a
                    # non-idempotent handler may have executed —
                    # re-sending would duplicate it.
                    if attempt + 1 >= max_attempts or \
                            (sent and not idempotent):
                        raise ConnectionLost(
                            f"rpc to {self.address} failed: {method}")
                    if attempt >= 1:
                        backoff = min(self._BACKOFF_CAP_S,
                                      self._BACKOFF_BASE_S * (2 ** (attempt - 1)))
                        # backoff keeps the channel lock: the connection
                        # is down, so queued callers could only fail the
                        # same way — sleeping unlocked would just let
                        # them interleave doomed reconnect attempts
                        time.sleep(  # graftlint: disable=RT015
                            backoff * random.uniform(0.5, 1.0))
        status, result = pickle.loads(reply)
        if status != "ok":
            if isinstance(result, tuple) and len(result) == 2:
                blob, tb = result
                if blob is not None:
                    try:
                        remote_exc = pickle.loads(blob)
                    except Exception:  # noqa: BLE001
                        remote_exc = None
                    if remote_exc is not None:
                        raise remote_exc from RpcError(
                            f"remote error from {self.address}.{method}:\n{tb}")
                result = tb
            raise RpcError(f"remote error from {self.address}.{method}:\n{result}")
        return result

    def send_oneway(self, method: str, **kwargs: Any) -> None:
        """Fire-and-forget: the server runs the handler without replying,
        so the caller never blocks on a round trip. Send failures raise
        (full-frame resend on a fresh connection is safe — a partial
        frame on a dead socket was never dispatched); handler errors are
        logged server-side only. Use for pushes whose failure is
        detected out-of-band (actor-death pubsub, worker connection
        loss), never for requests whose reply carries state."""
        payload = pickle.dumps((method, kwargs, True), protocol=5)
        # span only for sends big enough that the kernel copy is worth
        # measuring; tiny fire-and-forget frames (store_register, ref
        # bookkeeping) are visible server-side as rpc.server records
        with _spans.span("rpc.client.oneway", method=method,
                         bytes=len(payload)) \
                if len(payload) >= (1 << 16) else _spans.NOOP, \
                self._lock:
            for attempt in (0, 1):
                try:
                    chaos_lib.on_client_call(method, self.address)
                    if self._sock is None:
                        self._sock = self._connect()
                    _send_frame(self._sock, payload)
                    return
                except (ConnectionLost, ConnectionResetError,
                        BrokenPipeError, OSError):
                    self.close_locked()
                    if attempt == 1:
                        raise ConnectionLost(
                            f"oneway rpc to {self.address} failed: "
                            f"{method}")

    def send_oneways(self, items) -> None:
        """Flush-coalesced fire-and-forget: ship N queued one-way frames
        in ONE sendall. `items` is a list of (method, kwargs) pairs; each
        becomes its own wire frame (the server's frame loop needs no
        change), but the kernel sees a single write — one syscall, one
        TCP segment train, instead of N per-message round trips through
        the socket layer.

        Failure semantics: a send error resends the WHOLE batch on a
        fresh connection, so every element must be duplicate-safe (the
        same contract as retrying an idempotent call). Callers batch
        only methods from the duplicate-safe set (cw_task_done et al) —
        and a batch that fails both attempts raises with NO element
        delivered-or-not knowledge, exactly like a lost singleton
        one-way: the out-of-band failure path (death pubsub, lease
        reclaim) owns recovery for every sibling, not just the first.
        """
        if not items:
            return
        if len(items) == 1:
            method, kwargs = items[0]
            self.send_oneway(method, **kwargs)
            return
        frames = []
        for method, kwargs in items:
            payload = pickle.dumps((method, kwargs, True), protocol=5)
            frames.append(_LEN.pack(len(payload)))
            frames.append(payload)
        blob = b"".join(frames)
        with _spans.span("rpc.client.oneway_batch", n=len(items),
                         bytes=len(blob)) \
                if len(blob) >= (1 << 16) else _spans.NOOP, \
                self._lock:
            for attempt in (0, 1):
                try:
                    chaos_lib.on_client_call(items[0][0], self.address)
                    if self._sock is None:
                        self._sock = self._connect()
                    # the lock IS the per-connection serializer (same
                    # contract as send_oneway/_send_frame): writers
                    # queued behind it would interleave frames on the
                    # shared socket if this moved outside
                    self._sock.sendall(blob)  # graftlint: disable=RT015
                    return
                except (ConnectionLost, ConnectionResetError,
                        BrokenPipeError, OSError):
                    self.close_locked()
                    if attempt == 1:
                        raise ConnectionLost(
                            f"oneway batch ({len(items)} frames) to "
                            f"{self.address} failed")

    def close_locked(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self) -> None:
        with self._lock:
            self.close_locked()


class ClientPool:
    """Cache of RpcClients keyed by address."""

    def __init__(self, timeout: Optional[float] = None):
        self._clients: Dict[Tuple[str, int], RpcClient] = {}
        self._lock = threading.Lock()
        self._timeout = timeout

    def get(self, address: Tuple[str, int]) -> RpcClient:
        address = tuple(address)
        with self._lock:
            client = self._clients.get(address)
            if client is None:
                client = RpcClient(address, timeout=self._timeout)
                self._clients[address] = client
            return client

    def invalidate(self, address: Tuple[str, int]) -> None:
        with self._lock:
            client = self._clients.pop(tuple(address), None)
        if client is not None:
            client.close()

    def close_all(self) -> None:
        with self._lock:
            clients = list(self._clients.values())
            self._clients.clear()
        for c in clients:
            c.close()
