"""Tiny length-prefixed RPC layer over TCP sockets.

TPU-native rebuild of the reference's gRPC control plane (reference:
src/ray/rpc/grpc_server.h, grpc_client.h). The reference wraps gRPC services;
we use a minimal framed-pickle protocol: every process that serves RPCs hosts
an RpcServer with named handlers; clients hold pooled persistent connections.

Wire format: 8-byte big-endian length | pickled (method, kwargs) request,
same framing for the pickled (status, payload) reply.
"""

from __future__ import annotations

import logging
import pickle
import socket
import socketserver
import struct
import threading
import traceback
from typing import Any, Callable, Dict, Optional, Tuple

_LEN = struct.Struct(">Q")


def find_free_port(host: str = "127.0.0.1") -> int:
    """Bind-and-release a port (rendezvous endpoints: jax coordinator,
    torch MASTER_PORT, learner gangs)."""
    sock = socket.socket()
    sock.bind((host, 0))
    port = sock.getsockname()[1]
    sock.close()
    return port


class RpcError(Exception):
    """Remote handler raised; carries the remote traceback string."""


class ConnectionLost(Exception):
    """Peer went away mid-call."""


def _send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(n - len(buf), 4 << 20))
        if not chunk:
            raise ConnectionLost("socket closed")
        buf += chunk
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> bytes:
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    return _recv_exact(sock, n)


_chaos_rng = None


def _chaos_delay() -> None:
    """Chaos testing: inject a random handler delay (reference
    asio_chaos.cc:29-40, env RAY_testing_asio_delay_us). Set
    RAY_TPU_testing_rpc_delay_us to randomize RPC handler latencies and
    surface race/ordering bugs in tests. With
    RAY_TPU_testing_rpc_delay_seed also set, every process draws from
    the SAME seeded stream, so sweeping seeds explores different delay
    schedules and re-running a seed replays the per-process schedules
    (best effort — OS scheduling nondeterminism still varies the
    interleaving across runs; the reference relies on TSAN + the same
    asio randomization)."""
    from ray_tpu._private.config import Config
    max_us = Config.testing_rpc_delay_us
    if max_us > 0:
        import random
        import time
        global _chaos_rng
        if _chaos_rng is None:
            import os
            seed = os.environ.get("RAY_TPU_testing_rpc_delay_seed")
            _chaos_rng = random.Random(
                None if seed is None else int(seed))
        time.sleep(_chaos_rng.uniform(0, max_us) / 1e6)


class _Handler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        server: RpcServer = self.server.rpc_server  # type: ignore[attr-defined]
        sock = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # track live connections so stop() can close them — otherwise
        # handler threads outlive the server and keep ANSWERING against
        # the stopped instance (a restarted server on the same port then
        # never sees those clients). The stopping flag closes the race
        # where a connection accepted around stop() registers after the
        # snapshot and lingers anyway.
        with self.server.conn_lock:  # type: ignore[attr-defined]
            if self.server.stopping:  # type: ignore[attr-defined]
                try:
                    sock.close()
                except OSError:
                    pass
                return
            self.server.conns.add(sock)  # type: ignore[attr-defined]
        try:
            while True:
                req = _recv_frame(sock)
                item = pickle.loads(req)
                if len(item) == 3:
                    method, kwargs, oneway = item
                else:
                    (method, kwargs), oneway = item, False
                _chaos_delay()
                try:
                    handler = server.handlers[method]
                except KeyError:
                    reply = ("err", f"no such rpc method: {method}")
                else:
                    try:
                        result = handler(**kwargs)
                        reply = ("ok", result)
                    except Exception as e:  # noqa: BLE001 - ship to caller
                        # Typed propagation: the client re-raises the real
                        # exception class (e.g. ObjectStoreFullError from a
                        # store handler) so callers can catch specifically;
                        # the traceback string rides along for diagnostics.
                        try:
                            blob = pickle.dumps(e, protocol=5)
                        except Exception:  # noqa: BLE001 - unpicklable exc
                            blob = None
                        reply = ("err", (blob, traceback.format_exc()))
                if oneway:
                    # fire-and-forget frame: no reply; surface handler
                    # errors in the server log (callers detect failures
                    # out-of-band — death pubsub, connection loss)
                    if reply[0] == "err":
                        logging.getLogger(__name__).warning(
                            "oneway rpc %s failed: %s", method, reply[1])
                    continue
                _send_frame(sock, pickle.dumps(reply, protocol=5))
        except (ConnectionLost, ConnectionResetError, BrokenPipeError, OSError):
            return
        finally:
            with self.server.conn_lock:  # type: ignore[attr-defined]
                self.server.conns.discard(sock)  # type: ignore[attr-defined]


class _ThreadingTCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.conns: set = set()
        self.conn_lock = threading.Lock()
        self.stopping = False


class RpcServer:
    """Threaded RPC server; one thread per client connection."""

    def __init__(self, handlers: Dict[str, Callable], host: str = "127.0.0.1",
                 port: int = 0):
        self.handlers = dict(handlers)
        self._server = _ThreadingTCPServer((host, port), _Handler)
        self._server.rpc_server = self  # type: ignore[attr-defined]
        self.address: Tuple[str, int] = self._server.server_address[:2]
        self._thread = threading.Thread(
            target=self._server.serve_forever, kwargs={"poll_interval": 0.1},
            daemon=True, name=f"rpc-server-{self.address[1]}")
        self._thread.start()

    def register(self, method: str, fn: Callable) -> None:
        self.handlers[method] = fn

    def stop(self) -> None:
        try:
            self._server.shutdown()
            self._server.server_close()
        except Exception:  # noqa: BLE001
            pass
        # sever live connections so clients fail over immediately
        # (e.g. to a restarted server on the same port) instead of
        # talking to this zombie's handler threads
        with self._server.conn_lock:
            self._server.stopping = True
            conns = list(self._server.conns)
        for sock in conns:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass


# Methods safe to RESEND even after a send apparently succeeded (the
# peer may have executed them): reads, pings, and naturally-idempotent
# writes. A send into a dead peer's kernel buffer "succeeds" locally, so
# without this the first call after a server restart always fails.
_IDEMPOTENT_PREFIXES = ("get_", "list_", "kv_get", "kv_keys", "nm_get",
                        "nm_list", "cl_get", "cl_list",
                        # token-keyed add/remove + snapshot reads
                        "wait_graph_")
_IDEMPOTENT_METHODS = frozenset({
    "ping", "nm_ping", "report_resources", "register_node", "subscribe",
    "next_job_id", "cluster_resources", "available_resources",
})


def _is_idempotent(method: str) -> bool:
    return method.startswith(_IDEMPOTENT_PREFIXES) or \
        method in _IDEMPOTENT_METHODS


class RpcClient:
    """Client with one persistent connection, thread-safe via a lock.

    For concurrent calls from many threads use one client per thread or a
    ClientPool; a single in-flight call holds the lock end-to-end (the
    protocol is strictly request/reply per connection).
    """

    def __init__(self, address: Tuple[str, int], timeout: Optional[float] = None):
        self.address = tuple(address)
        self._timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()

    def _connect(self) -> socket.socket:
        sock = socket.create_connection(self.address, timeout=self._timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    def call(self, method: str, **kwargs: Any) -> Any:
        payload = pickle.dumps((method, kwargs), protocol=5)
        with self._lock:
            for attempt in (0, 1):
                if self._sock is None:
                    self._sock = self._connect()
                sent = False
                try:
                    _send_frame(self._sock, payload)
                    sent = True
                    reply = _recv_frame(self._sock)
                    break
                except (ConnectionLost, ConnectionResetError, BrokenPipeError,
                        OSError):
                    self.close_locked()
                    # Retry when the request never left this client
                    # (stale pooled connection died on send) OR the
                    # method is idempotent. After a successful send a
                    # non-idempotent handler may have executed —
                    # re-sending would duplicate it.
                    if attempt == 1 or (sent and
                                        not _is_idempotent(method)):
                        raise ConnectionLost(
                            f"rpc to {self.address} failed: {method}")
        status, result = pickle.loads(reply)
        if status != "ok":
            if isinstance(result, tuple) and len(result) == 2:
                blob, tb = result
                if blob is not None:
                    try:
                        remote_exc = pickle.loads(blob)
                    except Exception:  # noqa: BLE001
                        remote_exc = None
                    if remote_exc is not None:
                        raise remote_exc from RpcError(
                            f"remote error from {self.address}.{method}:\n{tb}")
                result = tb
            raise RpcError(f"remote error from {self.address}.{method}:\n{result}")
        return result

    def send_oneway(self, method: str, **kwargs: Any) -> None:
        """Fire-and-forget: the server runs the handler without replying,
        so the caller never blocks on a round trip. Send failures raise
        (full-frame resend on a fresh connection is safe — a partial
        frame on a dead socket was never dispatched); handler errors are
        logged server-side only. Use for pushes whose failure is
        detected out-of-band (actor-death pubsub, worker connection
        loss), never for requests whose reply carries state."""
        payload = pickle.dumps((method, kwargs, True), protocol=5)
        with self._lock:
            for attempt in (0, 1):
                if self._sock is None:
                    self._sock = self._connect()
                try:
                    _send_frame(self._sock, payload)
                    return
                except (ConnectionLost, ConnectionResetError,
                        BrokenPipeError, OSError):
                    self.close_locked()
                    if attempt == 1:
                        raise ConnectionLost(
                            f"oneway rpc to {self.address} failed: "
                            f"{method}")

    def close_locked(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self) -> None:
        with self._lock:
            self.close_locked()


class ClientPool:
    """Cache of RpcClients keyed by address."""

    def __init__(self, timeout: Optional[float] = None):
        self._clients: Dict[Tuple[str, int], RpcClient] = {}
        self._lock = threading.Lock()
        self._timeout = timeout

    def get(self, address: Tuple[str, int]) -> RpcClient:
        address = tuple(address)
        with self._lock:
            client = self._clients.get(address)
            if client is None:
                client = RpcClient(address, timeout=self._timeout)
                self._clients[address] = client
            return client

    def invalidate(self, address: Tuple[str, int]) -> None:
        with self._lock:
            client = self._clients.pop(tuple(address), None)
        if client is not None:
            client.close()

    def close_all(self) -> None:
        with self._lock:
            clients = list(self._clients.values())
            self._clients.clear()
        for c in clients:
            c.close()
