"""Structured cluster-event schema + fire-and-forget emit helper.

reference parity: src/ray/util/event.h (RayEvent record shape) — ONE
place owns the record schema so every emitter (GCS, node manager,
autoscaler, applications via the state API) stays in sync.
"""

from __future__ import annotations

import time
from typing import Any, Dict

SEVERITIES = ("INFO", "WARNING", "ERROR")


def build_event(source: str, event_type: str, message: str = "",
                severity: str = "INFO", **fields: Any) -> Dict[str, Any]:
    return {
        "ts": time.time(),
        "source": source,
        "event_type": event_type,
        "severity": severity if severity in SEVERITIES else "INFO",
        "message": message,
        **fields,
    }


def emit_via(gcs_call, source: str, event_type: str, message: str = "",
             severity: str = "INFO", **fields: Any) -> None:
    """Best-effort emit through a GCS client's .call; never raises."""
    try:
        gcs_call("add_events", events=[build_event(
            source, event_type, message, severity, **fields)])
    except Exception:  # noqa: BLE001 - events must never break the caller
        pass
