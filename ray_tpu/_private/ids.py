"""Binary IDs for jobs/tasks/actors/objects/nodes/placement groups.

Mirrors the reference's ID scheme (reference: src/ray/common/id.h) in spirit:
fixed-width random binary ids with embedded structure — an ObjectID embeds the
TaskID that produced it plus a return/put index, a TaskID embeds its JobID —
so lineage can be read off an id without a directory lookup.
"""

from __future__ import annotations

import os
import random
import struct
from typing import Optional

# Process-local id entropy. os.urandom is a syscall per call — measured
# at hundreds of µs under syscall-filtered sandboxes — and id minting
# sits on the per-task hot path (TaskID + trace id + lease id). One
# urandom seed per PROCESS feeds a userspace PRNG instead; distinct
# processes get distinct seeds, so cross-process uniqueness matches
# urandom's for our id widths. Re-seeded when the pid changes: a forked
# child inheriting the parent's PRNG state would mint the parent's
# exact id stream.
_rng: Optional[random.Random] = None
_rng_pid: Optional[int] = None


def rand_bytes(n: int) -> bytes:
    """Fast unique-id entropy (NOT for cryptographic use)."""
    global _rng, _rng_pid
    pid = os.getpid()
    rng = _rng
    if rng is None or _rng_pid != pid:
        rng = _rng = random.Random(os.urandom(16))
        _rng_pid = pid
    return rng.getrandbits(n * 8).to_bytes(n, "big")


class BaseID:
    SIZE = 16
    __slots__ = ("_bin", "_hex")

    def __init__(self, binary: bytes):
        if len(binary) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, got {len(binary)}")
        self._bin = binary
        self._hex: Optional[str] = None

    @classmethod
    def from_random(cls) -> "BaseID":
        return cls(rand_bytes(cls.SIZE))

    @classmethod
    def from_hex(cls, hex_str: str) -> "BaseID":
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls) -> "BaseID":
        return cls(b"\x00" * cls.SIZE)

    def is_nil(self) -> bool:
        return self._bin == b"\x00" * self.SIZE

    def binary(self) -> bytes:
        return self._bin

    def hex(self) -> str:
        # memoized: ids are hashed into dict keys on every control-plane
        # hop, ~20x per task submission
        h = self._hex
        if h is None:
            h = self._hex = self._bin.hex()
        return h

    def __hash__(self) -> int:
        return hash((type(self).__name__, self._bin))

    def __eq__(self, other: object) -> bool:
        return type(other) is type(self) and other._bin == self._bin  # type: ignore

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self.hex()[:12]}…)"

    def __reduce__(self):
        return (type(self), (self._bin,))


class JobID(BaseID):
    SIZE = 4


class NodeID(BaseID):
    SIZE = 16


class WorkerID(BaseID):
    SIZE = 16


class ActorID(BaseID):
    """12 random bytes + 4-byte job id."""

    SIZE = 16

    @classmethod
    def of(cls, job_id: JobID) -> "ActorID":
        return cls(rand_bytes(12) + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._bin[12:16])


class TaskID(BaseID):
    """12 random bytes + 4-byte job id."""

    SIZE = 16

    @classmethod
    def of(cls, job_id: JobID) -> "TaskID":
        return cls(rand_bytes(12) + job_id.binary())

    @classmethod
    def for_actor_creation(cls, actor_id: ActorID) -> "TaskID":
        return cls(actor_id.binary()[:12] + actor_id.job_id().binary())

    def job_id(self) -> JobID:
        return JobID(self._bin[12:16])


class ObjectID(BaseID):
    """TaskID (16) + 4-byte index: which return/put of the task."""

    SIZE = 20
    _IDX = struct.Struct(">I")

    @classmethod
    def for_task_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        return cls(task_id.binary() + cls._IDX.pack(index))

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int) -> "ObjectID":
        # High bit marks puts, distinguishing them from returns.
        return cls(task_id.binary() + cls._IDX.pack(put_index | 0x80000000))

    def task_id(self) -> TaskID:
        return TaskID(self._bin[:16])

    def return_index(self) -> int:
        return self._IDX.unpack(self._bin[16:20])[0] & 0x7FFFFFFF

    def is_put(self) -> bool:
        return bool(self._IDX.unpack(self._bin[16:20])[0] & 0x80000000)


class PlacementGroupID(BaseID):
    SIZE = 16


def format_id(id_or_none: Optional[BaseID]) -> str:
    return "nil" if id_or_none is None else id_or_none.hex()[:12]
