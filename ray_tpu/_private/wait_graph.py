"""Runtime wait-graph: actor-level deadlock detection.

An actor whose method blocks in `ray_tpu.get()` on another actor's
pending result registers a `waiter -> target` edge here (hosted by the
GCS). Adding an edge that would close a cycle means every actor on the
path is waiting on the next one with its executor thread held — the
classic nested-get deadlock the static rule RT001 flags at lint time.
Instead of hanging forever, the registering get() raises DeadlockError
carrying the cycle, which unwinds one waiter and lets the rest of the
cycle drain.

reference parity: none — upstream ray hangs on mutual gets; this is the
paper repo's production-readiness addition, surfaced via the dashboard
(`/api/wait_graph`).

Edges are per-actor, not per-thread, so workers only register an edge
when the blocking get holds the last idle executor thread of its
concurrency group (_Executor.has_spare_capacity): an actor with spare
group threads can still field calls from cycle peers and is not a hard
node in the graph. Registration waits out a short grace period first,
so fast gets never involve the GCS at all.

Every edge carries a caller-chosen token, which makes add/remove
idempotent under RPC retry: a retried add that already recorded returns
its original verdict instead of double-counting, and a retried remove
of a gone token is a no-op. A cycle verdict records nothing, so
re-running it on retry is also safe.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple
from ray_tpu.util.locks import TracedLock


class WaitGraph:
    """Directed waits-for graph over actor ids, with cycle-at-insert
    detection. Edges are keyed by token; concurrent gets from one actor
    to the same target stack and unwind independently."""

    def __init__(self) -> None:
        self._lock = TracedLock("wait_graph")
        # waiter hex -> {target hex: outstanding edge count}
        self._edges: Dict[str, Dict[str, int]] = {}
        # token -> (waiter hex, target hex, registered_at monotonic) —
        # the age feeds the metrics watchdog's stuck-wait probe
        self._tokens: Dict[str, Tuple[str, str, float]] = {}
        self.deadlocks_detected = 0

    def add(self, waiter: str, target: str,
            token: str) -> Optional[List[str]]:
        """Register waiter->target under token. Returns None and records
        the edge, or — when the edge would close a cycle — returns the
        cycle path `[waiter, target, ..., waiter]` WITHOUT recording it
        (the caller raises instead of blocking, so the edge never
        materializes)."""
        if waiter == target:
            return [waiter, waiter]
        with self._lock:
            if token in self._tokens:
                return None  # idempotent RPC retry of a recorded add
            path = self._find_path(target, waiter)
            if path is not None:
                self.deadlocks_detected += 1
                return [waiter] + path
            targets = self._edges.setdefault(waiter, {})
            targets[target] = targets.get(target, 0) + 1
            self._tokens[token] = (waiter, target, time.monotonic())
        return None

    def remove(self, token: str) -> None:
        with self._lock:
            edge = self._tokens.pop(token, None)
            if edge is None:
                return  # unknown/already-removed token: idempotent
            self._drop_edge_locked(edge[0], edge[1])

    def _drop_edge_locked(self, waiter: str, target: str) -> None:
        targets = self._edges.get(waiter)
        if not targets:
            return
        n = targets.get(target, 0) - 1
        if n <= 0:
            targets.pop(target, None)
            if not targets:
                self._edges.pop(waiter, None)
        else:
            targets[target] = n

    def drop_actor(self, actor: str) -> None:
        """Forget a dead actor: its outgoing edges (its gets died with
        it) and edges pointing at it (waiters get ActorDiedError)."""
        with self._lock:
            self._edges.pop(actor, None)
            for targets in self._edges.values():
                targets.pop(actor, None)
            self._tokens = {tok: rec
                            for tok, rec in self._tokens.items()
                            if rec[0] != actor and rec[1] != actor}

    def _find_path(self, src: str, dst: str) -> Optional[List[str]]:
        """DFS path src -> dst following edges; None if unreachable.
        Called under self._lock."""
        stack = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in self._edges.get(node, {}):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            now = time.monotonic()
            oldest: Dict[Tuple[str, str], float] = {}
            for w, t, t0 in self._tokens.values():
                age = now - t0
                if age > oldest.get((w, t), -1.0):
                    oldest[(w, t)] = age
            edges = [{"waiter": w, "target": t, "count": c,
                      "age_s": oldest.get((w, t), 0.0)}
                     for w, targets in self._edges.items()
                     for t, c in targets.items()]
            return {"edges": edges,
                    "max_edge_age_s": max(oldest.values(), default=0.0),
                    "deadlocks_detected": self.deadlocks_detected}


def format_cycle(cycle: List[str],
                 class_names: Optional[Dict[str, str]] = None) -> str:
    """Human-readable cycle: `Learner(a1b2c3) -> Runner(d4e5f6) -> ...`."""
    names = class_names or {}
    parts = []
    for hex_id in cycle:
        cls = names.get(hex_id)
        short = hex_id[:12]
        parts.append(f"{cls}({short})" if cls else short)
    return " -> ".join(parts)
