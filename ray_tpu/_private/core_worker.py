"""CoreWorker: embedded in every driver and worker process.

reference parity: src/ray/core_worker/core_worker.h:287 — task submission
(SubmitTask core_worker.cc:1887), actor creation/calls (:1958, :2193), object
put/get (:1148, :1360), ownership + reference counting (reference_count.h:61),
retries (task_manager.h:192) and the executor side (ExecuteTask :2598). The
direct task transports (transport/direct_task_transport.cc,
direct_actor_task_submitter.h) map to the lease + direct-push flow here; the
actor receiver's sequencing queue (actor_scheduling_queue.h:40) maps to the
per-caller seq reordering buffer in _ActorExecutor.
"""

from __future__ import annotations

import collections
import inspect
import logging
import os
import pickle
import queue
import threading
import time
import traceback
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu import exceptions as exc
from ray_tpu._private import chaos as chaos_lib
from ray_tpu._private import log_plane as _log_plane
from ray_tpu._private import memory_plane as _memory_plane
from ray_tpu._private import metrics_plane as _metrics_plane
from ray_tpu._private import ownership as _ownership
from ray_tpu._private import profiler as _profiler
from ray_tpu._private import rpc as rpc_lib
from ray_tpu._private import serialization as ser
from ray_tpu._private import shm_channel as _shm
from ray_tpu._private import spans as _spans
from ray_tpu._private.config import Config
from ray_tpu._private.ids import (ActorID, JobID, ObjectID, TaskID, WorkerID,
                                  rand_bytes as _rand_bytes)
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.object_store import ObjectStoreFullError, StoreClient
from ray_tpu._private.state import TaskSpec, TaskType
from ray_tpu._private.task_events import TaskEventBuffer, now as _ev_now
from ray_tpu.util import locks as _locks_util
from ray_tpu.util.locks import TracedLock, TracedRLock

logger = logging.getLogger(__name__)

# Object location tags (owner's object directory entries)
INLINE, STORE, ERROR, PENDING, FREED = "inline", "store", "error", "pending", "freed"
# the ownership protocol module validates location edges against the
# same tags; a drift between the two would corrupt its state machine
assert (INLINE, STORE, ERROR, PENDING, FREED) == (
    _ownership.INLINE, _ownership.STORE, _ownership.ERROR,
    _ownership.PENDING, _ownership.FREED)

# the package root, for callsite capture: the creation site reported by
# `ray_tpu memory --group-by callsite` is the first frame OUTSIDE the
# framework (the user's put()/.remote() line, not our plumbing)
_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _capture_callsite() -> Optional[str]:
    import sys as _sys
    try:
        f = _sys._getframe(2)
    except ValueError:
        return None
    while f is not None:
        path = f.f_code.co_filename
        if not path.startswith(_PKG_ROOT):
            return f"{path}:{f.f_lineno} in {f.f_code.co_name}"
        f = f.f_back
    return None

# Sentinel: materialization must be retried after in-flight recovery.
_RETRY = object()

# CoreWorker instance epochs (see CoreWorker.epoch / ObjectRef.__del__)
import itertools as _itertools  # noqa: E402
_CW_EPOCH = _itertools.count(1)

# Lazy transport metrics (util.metrics registers per-process; created on
# first use so importing this module costs nothing).
_TRANSPORT_COUNTER = None

# Owner-side task outcome counters, harvested cluster-wide by the
# metrics plane (the Grafana "Tasks finished/sec" panel's series).
_TASK_COUNTERS: Dict[str, Any] = {}


def _count_task_outcome(outcome: str) -> None:
    c = _TASK_COUNTERS.get(outcome)
    if c is None:
        try:
            from ray_tpu.util.metrics import Counter, get_or_create
            c = get_or_create(
                Counter, f"ray_tpu_tasks_{outcome}_total",
                description=f"tasks {outcome} as seen by their owner")
        except Exception:  # noqa: BLE001 - metrics are best-effort
            return
        _TASK_COUNTERS[outcome] = c
    try:
        c.inc()
    except Exception:  # noqa: BLE001 - metrics are best-effort
        pass


def _transport_bytes(n: int, site: str) -> None:
    """Count payload bytes copied on the transport plane, by site
    (put = scatter-write into shm, pull = cross-node replica stream)."""
    global _TRANSPORT_COUNTER
    c = _TRANSPORT_COUNTER
    if c is None:
        try:
            from ray_tpu.util.metrics import get_or_create, Counter
            c = get_or_create(
                Counter, "ray_tpu_transport_bytes_copied_total",
                description="payload bytes copied by the object "
                            "transport plane, by site",
                tag_keys=("site",))
        except Exception:  # noqa: BLE001 - metrics are best-effort
            return
        _TRANSPORT_COUNTER = c
    try:
        c.inc(n, tags={"site": site})
    except Exception:  # noqa: BLE001 - metrics are best-effort
        pass


@dataclass
class _TaskEntry:
    spec: TaskSpec
    retries_left: int
    return_ids: List[ObjectID]
    # submission order (monotonic per owner): failure batches re-enqueue
    # in THIS order — submission order is topological for data
    # dependencies, while an arbitrary (hex-sorted) order can queue a
    # dependent ahead of its dependency and deadlock a pipelined lease
    submit_seq: int = 0
    lease_node: Optional[Tuple[str, int]] = None
    node_id_hex: Optional[str] = None  # node the lease was granted on
    sched_key: Optional[bytes] = None  # scheduling-key for lease reuse
    # True while this task's hex sits in its key's queue: retry paths
    # must not append a second copy (double execution)
    in_key_queue: bool = False
    done: bool = False
    # streaming generator returns: children reported incrementally,
    # KEYED by return index (reference StreamingObjectRefGenerator,
    # _raylet.pyx:269) — index keying makes retries/recovery re-reports
    # idempotent instead of appending duplicates
    dynamic_arrived: Dict[int, ObjectID] = field(default_factory=dict)
    # LAZY: created by the first ObjectRefGenerator waiter (under the
    # owner's lock), not per entry — a threading.Event costs ~0.5KB and
    # the 250k-task scale envelope holds an entry per queued task. The
    # completion paths set it only when present; the waiter's 1s wait
    # timeout covers the (setter saw None / waiter just created it)
    # race without any extra locking.
    dynamic_event: Optional[threading.Event] = None

    def wake_dynamic(self) -> None:
        ev = self.dynamic_event
        if ev is not None:
            ev.set()


# Owner-side per-scheduling-key submission state lives in the ownership
# protocol module (ownership.LeaseState): tasks of one shape share a
# queue, lease request slots cover the backlog up to a cap, and leased
# workers are reused back-to-back while the queue has work — one push
# RPC per task instead of a lease round trip per task. All slot/parked/
# lease/pipeline counts mutate through LeaseTable methods (RT018).


@dataclass
class _ActorState:
    actor_id: ActorID
    address: Optional[Tuple[str, int]] = None
    last_address: Optional[Tuple[str, int]] = None
    dead: bool = False
    death_cause: str = ""
    seq: int = 0
    incarnation: int = 0
    queue: List[TaskSpec] = field(default_factory=list)
    # task hex -> incarnation it was pushed to (for failing in-flight tasks
    # of a dead incarnation; reference: direct_actor_task_submitter
    # DisconnectActor fails inflight requests)
    pushed: Dict[str, int] = field(default_factory=dict)
    resolving: bool = False
    # node the live incarnation runs on (from get_actor_info): a push to
    # an actor on the caller's own node takes the shm ring, not loopback
    node_id_hex: Optional[str] = None


class CoreWorker:
    def __init__(self, *, mode: str, job_id: JobID,
                 gcs_address: Tuple[str, int],
                 node_manager_address: Tuple[str, int],
                 store_address: Tuple[str, int],
                 node_id_hex: str,
                 worker_id: Optional[WorkerID] = None,
                 host: str = "127.0.0.1"):
        assert mode in ("driver", "worker")
        # instance epoch: ObjectRefs bind their refcount registration to
        # the CoreWorker instance that counted it (object_ref.__del__) —
        # a stale ref from a shut-down cluster must not release against
        # a successor instance's reference table
        self.epoch = next(_CW_EPOCH)
        self.mode = mode
        self.job_id = job_id
        self.worker_id = worker_id or WorkerID.from_random()
        self.node_id_hex = node_id_hex
        self.gcs_address = tuple(gcs_address)
        self.nm_address = tuple(node_manager_address)
        self._gcs = rpc_lib.RpcClient(self.gcs_address, timeout=120)
        self._nm = rpc_lib.RpcClient(self.nm_address, timeout=120)
        self._pool = rpc_lib.ClientPool(timeout=120)
        self.store = StoreClient(store_address)
        # placement group of the currently-executing task/actor, if any
        self.current_placement_group_id = None

        self._lock = TracedRLock("core_worker")
        # Ownership protocol state (_private/ownership.py): the explicit
        # RefState/LeaseState machines behind this worker's reference
        # counting and lease bookkeeping. The aliases below preserve the
        # historical read surface (memory/metrics planes, tests); every
        # MUTATION goes through the tables' methods, which funnel into
        # ownership.transition() — the choke point that validates legal
        # edges and records the transition ring `ray_tpu ownership`
        # serves. Mutations are made under self._lock (tables don't
        # lock; see ownership.py's locking contract).
        self._own = _ownership.RefTable()
        self._ltab = _ownership.LeaseTable()
        # Owner-side object directory: oid hex -> (tag, ...) location
        self.objects: Dict[str, Tuple] = self._own.objects
        self.object_events: Dict[str, threading.Event] = {}
        # oid hex -> [callback]: fired once when the object becomes ready
        # (value or error), without a blocking get (used by handle-style
        # consumers to observe completion cheaply).
        self._done_callbacks: Dict[str, List[Any]] = {}
        # Reference counting (reference reference_count.h): local refs,
        # submitted-task arg pins, and borrower registration — a process
        # holding a ref it doesn't own registers a pin with the owner
        # (cw_add_ref) on first local ref and releases it (cw_remove_ref)
        # when its last local ref drops, so the object outlives the owner's
        # own release while borrowed.
        self.local_refs: Dict[str, int] = self._own.local_refs
        self.arg_pins: Dict[str, int] = self._own.arg_pins
        # oid hex -> owner addr
        self.borrowed: Dict[str, Tuple[str, int]] = self._own.borrowed
        # oid hex -> reader-lease count held on the LOCAL store's pulled
        # replica (zero-copy views stay valid while leased); released
        # when this process's last local ref to the object drops
        self._replica_leases: Dict[str, int] = self._own.replica_leases
        # Owner-side borrower accounting: oid hex -> {borrower addr: count}.
        # A liveness sweep drops pins of borrowers that died without
        # releasing (reference: ReferenceCounter detects borrower failure
        # via the WaitForRefRemoved long-poll connection breaking).
        self.borrower_pins: Dict[str, Dict[Tuple[str, int], int]] = \
            self._own.borrower_pins
        # One long-lived drainer for borrow releases instead of a thread
        # per dropped ref (releases are fire-and-forget, order irrelevant).
        self._borrow_release_queue: "queue.Queue" = queue.Queue()
        # LOCAL store deletes pending on the drainer (guarded by
        # self._lock). Kept OUT of the FIFO queue: a remote release to
        # a dead node can block one queue item for the pool's full
        # connect timeout, and local frees must not strand store bytes
        # behind it — the drainer batch-flushes this list every
        # iteration, so local eviction lags by at most one item.
        self._local_free_pending: List[str] = []
        # (ready_time, item) releases that failed transiently, waiting
        # out their backoff before re-entering the release queue
        self._release_retries: List[Tuple[float, Tuple]] = []
        self._last_borrower_sweep = time.monotonic()
        # enclosing-result oid hex -> [(owner_addr, nested oid hex)]
        # eager borrows on refs embedded in task results (see
        # _register_nested_borrows)
        self._nested_borrows: Dict[str, List[Tuple]] = \
            self._own.nested_borrows
        # (deadline, local hexes, remote (addr, hex)) transit pins on
        # refs embedded in results this EXECUTOR shipped (see
        # pin_refs_with_ttl); expired by the borrow-release loop
        self._ttl_pins: List[Tuple] = self._own.ttl_pins
        self.tasks: Dict[str, _TaskEntry] = {}
        self.actors: Dict[str, _ActorState] = {}
        self._sched_keys: Dict[bytes, _ownership.LeaseState] = \
            self._ltab.keys
        # lease_id -> set of task hexes pushed-but-incomplete on that
        # lease (worker death reports fail exactly these under lease
        # reuse + pipelining)
        self._lease_running: Dict[str, set] = self._ltab.running
        # actor id hex -> submitted-but-unfinished calls from THIS
        # process (max_pending_calls backpressure is per caller, like
        # the reference's submit-queue bound)
        self._actor_pending: Dict[str, int] = {}
        self._store_map_cache = (0.0, {})
        self._put_index = 0
        # memory attribution (memory_plane.py): creation callsites of
        # owned objects (opt-in, Config.memory_callsite_capture) and a
        # short ring of store-resident objects this owner freed — the
        # refcount-vs-residency leak probe's "should be gone" list
        self._callsites: "collections.OrderedDict[str, str]" = \
            collections.OrderedDict()
        self._recently_freed: "collections.deque" = \
            collections.deque(maxlen=256)
        self._fn_cache: Dict[str, Any] = {}
        self._subscriptions: Dict[Tuple[str, str], Any] = {}
        self._tls = threading.local()
        self._shutdown = False
        threading.Thread(target=self._borrow_release_loop, daemon=True,
                         name="borrow-release").start()
        # lease-request tickets (key, nslots, nm) drained by the
        # requester thread — see _maybe_request_leases
        self._lease_req_q: "queue.Queue" = queue.Queue()
        threading.Thread(target=self._lease_request_loop, daemon=True,
                         name="lease-request").start()
        # Task state transitions → GCS task sink (reference
        # task_event_buffer.h:206 flushed to GcsTaskManager).
        self.task_events = TaskEventBuffer(rpc_lib.RpcClient(
            self.gcs_address, timeout=30))

        # Driver's root "task" context for put ids
        self._root_task_id = TaskID.of(job_id)

        handlers = {
            "cw_lease_granted": self._on_lease_granted,
            "cw_lease_granted_batch": self._on_lease_granted_batch,
            "cw_lease_respill": self._on_lease_respill,
            "cw_task_done": self._on_task_done,
            "cw_task_done_batch": self._on_task_done_batch,
            "cw_task_failed": self._on_task_failed,
            "cw_dynamic_child": self._on_dynamic_child,
            "cw_get_object": self._on_get_object,
            "cw_wait_object": self._on_wait_object,
            "cw_recover_object": self._on_recover_object,
            "cw_add_ref": self._on_add_ref,
            "cw_remove_ref": self._on_remove_ref,
            # anti-entropy: owners ask whether this process still claims
            # pinned objects (the lost-release safety net; see
            # _sweep_dead_borrowers)
            "cw_claims": self._on_claims,
            "cw_pubsub_push": self._on_pubsub_push,
            "cw_kill_self": self._on_kill_self,
            "cw_can_exit": self._on_can_exit,
            "cw_ping": lambda: "pong",
            # flight-recorder gather point (ray_tpu timeline --spans)
            "cw_spans_snapshot": _spans.snapshot,
            # metrics-plane gather point (dashboard /metrics,
            # `ray_tpu metrics dump`; see _private/metrics_plane.py)
            "cw_metrics_snapshot": _metrics_plane.snapshot_process,
            # debug-plane gather point (`ray_tpu logs`; see
            # _private/log_plane.py) — drivers live outside any node
            # manager's log dir, so the GCS pulls their tails directly
            "cw_logs_snapshot": _log_plane.snapshot,
            # profiling plane (_private/profiler.py): sampler control,
            # one-shot collect (start→sleep→snapshot, singleflight so
            # the concurrent NM+GCS fan-out never double-samples), and
            # device-side xplane traces
            "cw_profile_start":
                lambda hz=100.0: _profiler.sampler().start(hz),
            "cw_profile_stop": lambda: _profiler.sampler().stop(),
            "cw_profile_snapshot":
                lambda reset=False: _profiler.sampler().snapshot(
                    reset=reset),
            "cw_profile_collect":
                lambda duration_s=5.0, hz=100.0, device=False:
                (_profiler.device_profile(duration_s) if device
                 else _profiler.collect_local(duration_s, hz)),
            "cw_device_profile": _profiler.device_profile,
            # memory attribution plane (_private/memory_plane.py):
            # owner-side reference-table dump for `ray_tpu memory`
            "cw_memory_snapshot": self.memory_snapshot,
            # ownership protocol plane (_private/ownership.py): live
            # RefState/LeaseState + transition-ring tail for
            # `ray_tpu ownership` / /api/ownership
            "cw_ownership_snapshot": self.ownership_snapshot,
            # lockdep plane (ray_tpu/util/locks.py): traced-lock
            # snapshot for `ray_tpu locks` / /api/locks
            "cw_locks_snapshot": _locks_util.snapshot,
        }
        self.executor: Optional[_Executor] = None
        if mode == "worker":
            self.executor = _Executor(self)
            handlers["w_push_task"] = self.executor.push_task
            handlers["w_cancel_task"] = self.executor.cancel_task
        # Same-node shm task channel (_private/shm_channel.py): messages
        # from local peers arrive over arena-backed rings and dispatch
        # into this same handler table; shm_doorbell is the only part
        # that rides the socket. Senders are created lazily per peer in
        # _shm_send.
        self._shm_senders: Dict[Tuple[str, int], _shm.Sender] = {}
        self._shm_lock = threading.Lock()
        self._shm_rx: Optional[_shm.Receiver] = None
        # Spec-blob interning (scale envelope, ROADMAP item 1): 250k
        # queued submissions of the same closure/args hold ONE bytes
        # object instead of 250k identical pickles. Keyed by the blob
        # itself — dict hashing + equality beats a crypto digest at
        # these sizes and collisions are impossible by construction.
        self._blob_cache: "collections.OrderedDict[bytes, bytes]" = \
            collections.OrderedDict()
        self._blob_cache_lock = threading.Lock()
        self.blob_cache_hits = 0
        if Config.shm_task_channel:
            # chaos server hook runs here too: a fault rule (delay /
            # kill_worker / stall) must fire identically whether the
            # message rode the ring or the socket
            def _shm_dispatch(method, kw, _handlers=handlers):
                chaos_lib.on_server_dispatch(method)
                return _handlers[method](**kw)
            self._shm_rx = _shm.Receiver(_shm_dispatch)
            handlers["shm_doorbell"] = self._shm_rx.on_doorbell
        self.server = rpc_lib.RpcServer(handlers, host=host)
        self.address = self.server.address
        # one trace row per process in the merged timeline
        _spans.set_process_label(f"{mode}-{self.worker_id.hex()[:8]}",
                                 node_id=node_id_hex)
        # full worker identity for the profiling plane (`ray_tpu
        # profile --worker` matches by id prefix; labels only carry 8
        # hex chars)
        _profiler.set_process_worker(self.worker_id.hex())
        # debug plane: log-line stamps read the current task/actor/trace
        # from this worker's TLS; drivers additionally capture their own
        # `logging` output into the in-process tail ring so `ray_tpu
        # logs` answers for them too (workers already stamp via the
        # worker_main stream redirection)
        _log_plane.set_context_provider(self._log_context)
        if mode == "driver":
            _log_plane.install_capture("driver")
        # lease/executor gauges exported at harvest time (pull-based:
        # the submission hot path never touches the registry); the
        # watchdog's lease_slot_balance probe reads exactly these
        _metrics_plane.register_sampler("core_worker",
                                        self._sample_metric_gauges)
        # compact memory digest on every metrics harvest: the input the
        # watchdog's leak probes compare store residency against, so a
        # leaked pin alerts within two harvest intervals with no extra
        # fan-out (memory_plane.py)
        _metrics_plane.register_snapshot_extra(
            _memory_plane.PROC_DIGEST_KEY, self._memory_digest)
        # Owner-side node-failure detection (reference: the raylet notifies
        # owners via the object directory / lease failures; here the GCS
        # node channel is the death signal). Without it, tasks in flight
        # on a SIGKILLed node would hang their owner forever.
        try:
            self.subscribe("node", self._on_node_event)
            # Actor channel: fail in-flight calls when an actor dies
            # (reference: direct_actor_task_submitter DisconnectActor via
            # the GCS actor pubsub). Without it a caller blocked in get()
            # on a call pushed to a crashed actor hangs forever.
            self.subscribe("actor", self._on_actor_event)
        except Exception:  # noqa: BLE001
            logger.warning("could not subscribe to GCS events",
                           exc_info=True)
        # Chaos plane (_private/chaos.py): identify this process to the
        # fault-injection hooks, pick up the current policy (pubsub only
        # reaches processes alive at publish time), and follow updates.
        from ray_tpu._private import chaos as chaos_lib
        chaos_lib.client().set_context(
            node_id=node_id_hex, is_worker=(mode == "worker"),
            gcs_address=self.gcs_address)
        if mode == "worker":
            # black-box flight dump: a chaos self-kill writes this
            # worker's span-ring tail + recent log records to a sidecar
            # the node manager folds into the crash postmortem
            chaos_lib.client().set_predeath_hook(
                _log_plane.write_flight_dump)
        chaos_lib.fetch_policy(self._gcs.call)
        try:
            self.subscribe("chaos", chaos_lib.on_policy_message)
        except Exception:  # noqa: BLE001 - degrades to fetched policy
            pass

    # ------------------------------------------------------------------
    # Context
    # ------------------------------------------------------------------

    def _sample_metric_gauges(self) -> None:
        """Export point-in-time submission-state gauges for the metrics
        harvest. The lease gauges encode the scheduling invariant the
        watchdog checks: every in-flight request slot must either be
        parked at an NM awaiting a grant or have queued work driving
        it — a slot with neither, held across harvests, is the leak
        ADVICE round 5 found (in_flight - parked > 0 with an empty
        queue)."""
        from ray_tpu.util.metrics import Gauge, get_or_create
        with self._lock:
            in_flight = sum(ks.requests_in_flight
                            for ks in self._sched_keys.values())
            parked = sum(max(0, n)
                         for ks in self._sched_keys.values()
                         for n in ks.parked_at.values())
            queued = sum(len(ks.queue)
                         for ks in self._sched_keys.values())
            leases = sum(len(ks.leases)
                         for ks in self._sched_keys.values())
        get_or_create(
            Gauge, "ray_tpu_lease_requests_in_flight",
            description="outstanding lease requests across scheduling "
                        "keys (owner side)").set(float(in_flight))
        get_or_create(
            Gauge, "ray_tpu_lease_requests_parked",
            description="lease requests parked at a node manager "
                        "awaiting an async grant").set(float(parked))
        get_or_create(
            Gauge, "ray_tpu_lease_queued_tasks",
            description="tasks queued for a lease across scheduling "
                        "keys (owner side)").set(float(queued))
        get_or_create(
            Gauge, "ray_tpu_lease_active_leases",
            description="worker leases currently held by this "
                        "process").set(float(leases))
        ex = self.executor
        get_or_create(
            Gauge, "ray_tpu_executor_queue_depth",
            description="queued + running tasks on this worker's "
                        "executor across all concurrency groups "
                        "(serve replica saturation signal)"
        ).set(float(ex.total_queue_depth() if ex is not None else 0))

    def current_task_id(self) -> TaskID:
        return getattr(self._tls, "task_id", None) or self._root_task_id

    def _log_context(self) -> Tuple[Optional[str], Optional[str],
                                    Optional[str]]:
        """(task, actor, trace) for the debug plane's line stamps —
        read on every stamped write, so: TLS lookups only."""
        tid = getattr(self._tls, "task_id", None)
        aid = self.executor.actor_id if self.executor is not None else None
        return (tid.hex() if tid is not None else None,
                aid.hex() if aid is not None else None,
                getattr(self._tls, "trace_id", None))

    def set_current_task(self, task_id: Optional[TaskID]) -> None:
        self._tls.task_id = task_id
        # mirror into the profiler's cross-thread context registry:
        # threading.local is invisible to the sampler thread, a plain
        # dict write is not (and costs ~100ns per task transition)
        _profiler.set_thread_task(task_id.hex()
                                  if task_id is not None else None)

    # ---- tracing (reference tracing_helper.py context propagation) ---

    def current_trace_id(self) -> Optional[str]:
        return getattr(self._tls, "trace_id", None)

    def current_trace_name(self) -> Optional[str]:
        return getattr(self._tls, "trace_name", None)

    def set_current_trace(self, trace_id: Optional[str],
                          name: Optional[str] = None) -> None:
        self._tls.trace_id = trace_id
        self._tls.trace_name = name
        # mirror into the flight recorder so span records carry the
        # trace, and into the profiler so samples do too
        _spans.set_current_trace(trace_id)
        _profiler.set_thread_trace(trace_id)

    def _attach_trace(self, spec: TaskSpec) -> None:
        """Child tasks inherit the caller's trace; a driver-side submit
        outside any trace starts a fresh one."""
        spec.trace_id = self.current_trace_id() or _rand_bytes(8).hex()
        parent = getattr(self._tls, "task_id", None)
        if parent is not None:
            spec.parent_task_id = parent.hex()
        # the start_trace(name) label rides on this submitter's events
        name = self.current_trace_name()
        if name:
            self.task_events.record(spec.task_id.hex(), trace_name=name)

    def next_put_index(self) -> int:
        with self._lock:
            self._put_index += 1
            return self._put_index

    # ------------------------------------------------------------------
    # Memory attribution (memory_plane.py)
    # ------------------------------------------------------------------

    def _note_callsite(self, oid_hexes: List[str]) -> None:
        """Record the user-code line that created these objects (put /
        .remote()); only called when Config.memory_callsite_capture is
        on — a stack walk per creation is real cost on the put path."""
        site = _capture_callsite()
        if site is None:
            return
        with self._lock:
            for h in oid_hexes:
                self._callsites[h] = site
            while len(self._callsites) > 8192:
                self._callsites.popitem(last=False)

    def memory_snapshot(self, max_objects: Optional[int] = None
                        ) -> Dict[str, Any]:
        """This process's reference table, wire form: everything that
        holds an object alive from here — local refs, submitted-arg
        pins, borrows held (we pinned at a remote owner), borrower pins
        granted (remote processes pinned with us), reader leases on
        pulled replicas, transit pins — plus owned objects' recorded
        location and (opt-in) creation callsite. The GCS joins these
        with store residency into the cluster object table."""
        cap = int(Config.memory_snapshot_max_objects
                  if max_objects is None else max_objects)
        executor = self.executor
        actor_id = executor.actor_id.hex() \
            if executor is not None and executor.actor_id is not None \
            else None
        with self._lock:
            oids = (set(self.objects) | set(self.local_refs)
                    | set(self.arg_pins) | set(self.borrowed)
                    | set(self._replica_leases) | set(self.borrower_pins))
            transit_pins = sum(len(p[1]) + len(p[2])
                               for p in self._ttl_pins)
            records: Dict[str, Dict[str, Any]] = {}
            for h in oids:
                loc = self.objects.get(h)
                tag = loc[0] if loc is not None else None
                if tag == STORE:
                    size: Optional[int] = int(loc[2])
                elif tag in (INLINE, ERROR):
                    size = len(loc[1])
                else:
                    size = None
                records[h] = {
                    "owned": loc is not None and h not in self.borrowed,
                    "loc": tag,
                    "store_addr": (list(loc[1]) if tag == STORE
                                   else None),
                    "size": size,
                    "local_refs": self.local_refs.get(h, 0),
                    "arg_pins": self.arg_pins.get(h, 0),
                    "borrowed_from": (list(self.borrowed[h])
                                      if h in self.borrowed else None),
                    "replica_leases": self._replica_leases.get(h, 0),
                    "borrower_pins": {
                        f"{a[0]}:{a[1]}": n for a, n in
                        self.borrower_pins.get(h, {}).items()},
                    "callsite": self._callsites.get(h),
                }
            dropped = 0
            if len(records) > cap:
                # bounded: keep the held-alive end (store-resident,
                # pinned, borrowed, leased) and count the rest out
                def _weight(item):
                    r = item[1]
                    return ((r["loc"] == STORE) * 4
                            + bool(r["borrower_pins"])
                            + bool(r["replica_leases"])
                            + bool(r["arg_pins"]),
                            r["size"] or 0)
                kept = sorted(records.items(), key=_weight,
                              reverse=True)[:cap]
                dropped = len(records) - cap
                records = dict(kept)
            freed = [oid for oid, _t in self._recently_freed]
        return {
            "proc_uid": _spans.PROC_UID,
            "pid": os.getpid(),
            "label": _spans.process_label(),
            "node_id": self.node_id_hex,
            "worker_id": self.worker_id.hex(),
            "actor_id": actor_id,
            "mode": self.mode,
            "wall_time": time.time(),
            "objects": records,
            "transit_pins": transit_pins,
            "recently_freed": freed,
            "objects_dropped": dropped,
        }

    def _memory_digest(self) -> Dict[str, Any]:
        """Compact form riding every metrics harvest (the leak probes'
        view of who claims what; see memory_plane.py). Computed
        directly from the held-alive sets — NOT via memory_snapshot(),
        whose full record build over the whole object directory
        (including long-dead FREED entries) is too heavy for a 2s
        cadence and would trip the digest cap on long-lived drivers,
        silently disabling the probes."""
        cap = int(Config.memory_digest_max_objects)
        now = time.monotonic()
        with self._lock:
            owned_store = [h for h, loc in self.objects.items()
                           if loc[0] == STORE and h not in self.borrowed]
            leases = dict(self._replica_leases)
            # hold a just-freed object back until its queued remote
            # delete has had time to drain (it rides the borrow-release
            # drainer) — reporting it instantly would race the delete
            # into a false residency-mismatch alert
            freed = [oid for oid, t in self._recently_freed
                     if now - t >= self.FREED_REPORT_GRACE_S]
        return {"kind": self.mode,
                "owned_store": owned_store[:cap],
                "leases": leases,
                "freed": freed,
                "dropped": max(0, len(owned_store) - cap)}

    FREED_REPORT_GRACE_S = 3.0

    # ------------------------------------------------------------------
    # Reference counting
    # ------------------------------------------------------------------

    def add_local_ref(self, ref: ObjectRef) -> None:
        h = ref.hex()
        register_borrow = False
        with self._lock:
            n = self._own.incr_local(h)
            if n == 1 and not self._is_own(ref) and h not in self.borrowed:
                self._own.note_borrow(h, tuple(ref.owner_address))
                register_borrow = True
        if register_borrow:
            # Synchronous so the borrower pin lands before the task that
            # carried this ref completes (its completion releases the
            # sender's in-flight arg pin at the same owner).
            try:
                self._pool.get(tuple(ref.owner_address)).call(
                    "cw_add_ref", oid_hex=h, borrower=self.address)
            except Exception:  # noqa: BLE001 - owner gone; get() will surface
                # Roll back the borrow record: without a registered pin, a
                # later cw_remove_ref would decrement a pin some OTHER
                # borrower legitimately holds.
                with self._lock:
                    self._own.drop_borrow(h, event="borrow_rollback")

    def remove_local_ref(self, ref: ObjectRef) -> None:
        if self._shutdown:
            return
        release_borrow = None
        with self._lock:
            h = ref.hex()
            # strict: a second release of the same ObjectRef is exactly
            # the double-release class the protocol exists to catch
            n = self._own.decr_local(h)
            if n > 0:
                return
            release_borrow = self._own.drop_borrow(h)
            lease_count = self._own.pop_replica_leases(h)
            # owner-side free runs regardless of replica leases: an owned
            # ref whose value was pulled from a remote store still must
            # free on last drop (the lease release below is independent)
            if release_borrow is None and self.arg_pins.get(h, 0) == 0:
                self._maybe_free_locked(h)
        if lease_count:
            # release the local replica's reader lease(s): the arrays a
            # get() handed out die with the last ObjectRef, so the store
            # may evict the block again
            try:
                self.store.unpin(h, count=lease_count)
            except Exception:  # noqa: BLE001 - store gone; lease moot
                pass
        if release_borrow is not None:
            self._borrow_release_queue.put((release_borrow, h))

    def _maybe_free_locked(self, oid_hex: str,
                           force: bool = False) -> None:
        loc = self.objects.get(oid_hex)
        if loc is None or loc[0] in (PENDING, FREED):
            return  # in flight (keep until completion) / already freed
        if loc[0] == STORE:
            # the delete must reach the store that HOLDS the primary:
            # a task result created pinned in the executing worker's
            # node store used to be freed only from the OWNER's local
            # store, leaking the remote primary forever (found by the
            # memory plane's residency-mismatch probe). Queued onto the
            # borrow-release drainer, NOT sent here — a connect to a
            # dead node can block for the pool's full timeout, and this
            # runs under self._lock (loss just means the probe flags
            # the stranded copy).
            primary_addr = tuple(loc[1])
            if primary_addr != self.store.address:
                self._borrow_release_queue.put(
                    ("store_delete", primary_addr, oid_hex))
            try:
                # client-side mmap release only (no RPC): local views
                # die with the ref. The LOCAL store's delete is an RPC
                # round trip too (StoreClient.delete -> store_delete),
                # and under self._lock it stalled every worker
                # operation whenever the store server was slow
                # (RT015); the drainer batch-flushes it off the lock.
                self.store.release_views([oid_hex])
            except Exception:  # noqa: BLE001 - store gone; probe flags leftovers
                pass
            self._local_free_pending.append(oid_hex)
            self._borrow_release_queue.put(("local_free",))
            # residency-mismatch probe input: this object SHOULD now be
            # gone from every store. Timestamped so the digest can hold
            # a just-freed object back while the queued remote delete
            # drains (memory_plane.py)
            self._recently_freed.append((oid_hex, time.monotonic()))
        self._callsites.pop(oid_hex, None)
        # the RefState machine rejects free-while-pinned here unless
        # forced (ray.free's explicit "free even though referenced")
        self._own.set_location(oid_hex, (FREED,), event="free",
                               force=force)
        # wake + retire any parked waiter event (waiters re-check the
        # location and see FREED; events are waiter-created and bounded
        # by live waits, never by object count)
        ev = self.object_events.pop(oid_hex, None)
        if ev is not None:
            ev.set()
        # release eager borrows on refs nested inside this result (see
        # _register_nested_borrows): remote owners via the async release
        # queue; locally-owned nested objects unpin (and may free) here
        nested = self._own.pop_nested(oid_hex)
        if nested:
            for owner_addr, ref_hex in nested:
                if owner_addr == self.address:
                    n = self._own.unpin_arg(ref_hex,
                                            event="nested_unpin")
                    if n <= 0 and self.local_refs.get(ref_hex, 0) == 0:
                        self._maybe_free_locked(ref_hex)
                else:
                    self._borrow_release_queue.put((owner_addr, ref_hex))

    def _register_nested_borrows(self, outer_hex: str,
                                 nested_refs: List[Tuple]) -> None:
        """Eagerly borrow refs embedded in a task result, keyed to the
        enclosing result object: kept exactly as long as the result
        itself, independent of when (or whether) this process
        deserializes it. Deserialization's own add_local_ref stacks a
        second, independently-released count on the same owner pins."""
        recorded = []
        for oid, owner_addr in nested_refs:
            addr = tuple(owner_addr)
            if addr == self.address:
                with self._lock:
                    self._own.pin_arg(oid.hex(), event="nested_pin")
            else:
                # transit claim bridges the gap until note_nested below
                # records the durable claim (the owner's reconciliation
                # sweep must never see a claimless pin)
                with self._lock:
                    self._own.add_transit_out(oid.hex())
                try:
                    self._pool.get(addr).call(
                        "cw_add_ref", oid_hex=oid.hex(),
                        borrower=self.address)
                except Exception:  # noqa: BLE001 — owner gone; the get
                    with self._lock:  # will surface the loss
                        self._own.drop_transit_out(oid.hex())
                    continue
            recorded.append((addr, oid.hex()))
        if recorded:
            with self._lock:
                self._own.note_nested(outer_hex, recorded)
                for addr, h in recorded:
                    if addr != self.address:
                        self._own.drop_transit_out(h)

    def add_done_callback(self, ref: ObjectRef, cb: Any) -> None:
        """Invoke cb() once when the owned object is no longer pending.
        Fires immediately if already resolved. Callbacks must be cheap
        (they run on completion-handling threads)."""
        h = ref.hex()
        with self._lock:
            loc = self.objects.get(h)
            if loc is None or loc[0] != PENDING:
                fire_now = True
            else:
                self._done_callbacks.setdefault(h, []).append(cb)
                fire_now = False
        if fire_now:
            try:
                cb()
            except Exception:  # noqa: BLE001
                logger.exception("done callback failed")

    def _fire_done_callbacks(self, oid_hexes) -> None:
        cbs: List[Any] = []
        with self._lock:
            for h in oid_hexes:
                cbs.extend(self._done_callbacks.pop(h, []))
        for cb in cbs:
            try:
                cb()
            except Exception:  # noqa: BLE001
                logger.exception("done callback failed")

    def _drain_local_frees(self) -> None:
        """Flush pending LOCAL store deletes in one batched ONE-WAY
        send. Runs on the drainer thread (never under self._lock) at
        every loop iteration, so local frees overtake remote releases
        that may be blocked connecting to dead nodes. Deliberately NOT
        StoreClient.delete: that client's channel is shared with the
        put/get hot path, and a slow store_delete handler would hold
        its per-call lock against the next put for the handler's full
        duration — the pool connection (the one the remote-primary
        delete path already uses) keeps the stall off the data path,
        and a one-way send never waits on the handler at all."""
        with self._lock:
            batch, self._local_free_pending = \
                self._local_free_pending, []
        if batch:
            try:
                self._pool.get(self.store.address).send_oneway(
                    "store_delete", object_ids=batch)
            except Exception:  # noqa: BLE001 - store gone; the
                pass           # residency probe flags leftovers

    # Transient-failure budget for protocol releases riding the drainer
    # (borrow releases, remote-primary deletes): a dropped connection
    # must not leak the pin/copy forever — the item re-queues with
    # backoff and only a peer that stays unreachable this long loses it
    # (the dead-borrower sweep / leak probes then own the cleanup).
    RELEASE_RETRY_ATTEMPTS = 4
    RELEASE_RETRY_BACKOFF_S = 0.5

    def _requeue_release(self, item: Tuple, attempts: int) -> None:
        if attempts >= self.RELEASE_RETRY_ATTEMPTS:
            logger.warning("giving up on protocol release %s after %d "
                           "attempts", item[:2], attempts)
            return
        with self._lock:
            self._release_retries.append(
                (time.monotonic()
                 + self.RELEASE_RETRY_BACKOFF_S * (attempts + 1), item))

    def _drain_release_retries(self) -> None:
        now = time.monotonic()
        with self._lock:
            due = [it for t, it in self._release_retries if t <= now]
            self._release_retries = [
                (t, it) for t, it in self._release_retries if t > now]
        for it in due:
            self._borrow_release_queue.put(it)

    def _borrow_release_loop(self) -> None:
        while not self._shutdown:
            try:
                self._expire_ttl_pins()
            except Exception:  # noqa: BLE001
                logger.exception("ttl pin expiry failed")
            try:
                self._drain_local_frees()
                self._drain_release_retries()
            except Exception:  # noqa: BLE001
                logger.exception("local free drain failed")
            try:
                item = self._borrow_release_queue.get(timeout=2.0)
            except queue.Empty:
                # Idle: sweep for borrowers that died without releasing.
                # (Sweep cadence rides the queue timeout; retries above
                # need the shorter tick.)
                now = time.monotonic()
                if now - self._last_borrower_sweep >= 10.0:
                    self._last_borrower_sweep = now
                    try:
                        self._sweep_dead_borrowers()
                    except Exception:  # noqa: BLE001
                        logger.exception("borrower sweep failed")
                    # idle gc: refcounting rides __del__, but ObjectRefs
                    # captured in exception-traceback CYCLES (a failed
                    # task's frames hold its arg refs) wait for the gc —
                    # and an idle worker may not allocate enough to
                    # trigger one for minutes, pinning objects at their
                    # owners the whole time (reference: Ray triggers
                    # worker gc under plasma pressure for the same
                    # reason)
                    try:
                        import gc as _gc
                        _gc.collect()
                    # a finalizer crashing mid-collection must not kill
                    # the drainer; the cycle just waits for the next tick
                    except Exception:  # noqa: BLE001  graftlint: disable=RT013
                        pass
                continue
            if item is None:
                return
            if len(item) == 1:
                continue  # local_free wake: drained at loop top
            if item[0] == "store_delete":
                # remote-primary free queued by _maybe_free_locked (the
                # connect must happen OFF the CoreWorker lock)
                _tag, store_addr, oid_hex = item[:3]
                attempts = item[3] if len(item) > 3 else 0
                try:
                    self._pool.get(store_addr).send_oneway(
                        "store_delete", object_ids=[oid_hex])
                except Exception:  # noqa: BLE001 - transient: retry with
                    # backoff; a node that stays gone loses the copy and
                    # the residency probe flags any stranded one
                    self._requeue_release(
                        ("store_delete", store_addr, oid_hex,
                         attempts + 1), attempts)
                continue
            owner_addr, oid_hex = item[:2]
            attempts = item[2] if len(item) > 2 else 0
            try:
                self._pool.get(owner_addr).call("cw_remove_ref",
                                                oid_hex=oid_hex,
                                                borrower=self.address)
            except Exception:  # noqa: BLE001 - transient: retry with
                # backoff so a dropped connection doesn't leak the pin
                # at a LIVE owner forever (a dead owner has nothing to
                # free)
                self._requeue_release((owner_addr, oid_hex, attempts + 1),
                                      attempts)

    def pin_refs(self, refs: List[Any]) -> Tuple[List[str], List[Tuple]]:
        """Pin objects across a result/report hand-off window: locally
        (arg_pins) for objects we own, one-way borrower-pin at the
        remote owner otherwise. Returns a (local hexes, remote keys)
        handle for release_pins_now / release_pins_after. A remote key
        is recorded ONLY when its cw_add_ref send succeeded — recording
        a failed send would make the later release emit an unmatched
        cw_remove_ref that decrements a pin some OTHER borrower
        legitimately holds, freeing a live object (ADVICE r5)."""
        local: List[str] = []
        remote_keys: List[Tuple] = []
        for ref in refs:
            if self._is_own(ref):
                local.append(ref.hex())
            else:
                remote_keys.append((tuple(ref.owner_address), ref.hex()))
        with self._lock:
            for h in local:
                self._own.pin_arg(h, event="transit_pin")
        remote_sent: List[Tuple] = []
        for addr, h in remote_keys:
            # claim evidence for cw_claims BEFORE the send: the owner's
            # reconciliation sweep must never observe the pin without
            # the claim that protects it
            with self._lock:
                self._own.add_transit_out(h)
            try:
                self._pool.get(addr).send_oneway(
                    "cw_add_ref", oid_hex=h, borrower=self.address)
            except Exception:  # noqa: BLE001 — owner gone; the consumer's
                with self._lock:   # get surfaces the loss
                    self._own.drop_transit_out(h)
                continue
            remote_sent.append((addr, h))
        return (local, remote_sent)

    def release_pins_now(self, handle: Tuple[List[str], List[Tuple]]
                         ) -> None:
        """Release a pin_refs handle immediately (the consumer acked:
        its own eager borrows are registered)."""
        local, remote_keys = handle
        with self._lock:
            self._release_local_pins_locked(local)
            for _addr, h in remote_keys:
                self._own.drop_transit_out(h)
        for addr, h in remote_keys:
            self._borrow_release_queue.put((addr, h))

    def release_pins_after(self, handle: Tuple[List[str], List[Tuple]],
                           ttl_s: float) -> None:
        """Schedule a pin_refs handle for TTL release (the fallback when
        no ack will come). Expiry rides the borrow-release loop (≤10s
        granularity) rather than one timer thread per result."""
        local, remote_keys = handle
        with self._lock:
            self._own.add_ttl_pins(time.monotonic() + ttl_s, local,
                                   remote_keys)

    def pin_refs_with_ttl(self, refs: List[Any],
                          ttl_s: float = 30.0) -> None:
        """pin_refs + TTL-scheduled release in one step (callers without
        an ack path)."""
        self.release_pins_after(self.pin_refs(refs), ttl_s)

    def _release_local_pins_locked(self, hexes: List[str]) -> None:
        for h in hexes:
            n = self._own.unpin_arg(h, event="transit_unpin")
            if n <= 0 and self.local_refs.get(h, 0) == 0:
                self._maybe_free_locked(h)

    def _expire_ttl_pins(self) -> None:
        now = time.monotonic()
        with self._lock:
            due = self._own.pop_due_ttl(now)
            if not due:
                return
            for _, local, remote_keys in due:
                self._release_local_pins_locked(local)
                for _addr, h in remote_keys:
                    self._own.drop_transit_out(h)
        for _, _, remote_keys in due:
            for addr, h in remote_keys:
                self._borrow_release_queue.put((addr, h))

    def _pin_args(self, refs: List[ObjectID]) -> None:
        with self._lock:
            for oid in refs:
                self._own.pin_arg(oid.hex(), event="arg_pin")

    def _unpin_args(self, refs: List[ObjectID]) -> None:
        with self._lock:
            for oid in refs:
                h = oid.hex()
                n = self._own.unpin_arg(h, event="arg_unpin")
                if n <= 0 and self.local_refs.get(h, 0) == 0:
                    self._maybe_free_locked(h)

    # ------------------------------------------------------------------
    # Put / Get / Wait / Free
    # ------------------------------------------------------------------

    def put(self, value: Any) -> ObjectRef:
        oid = ObjectID.for_put(self.current_task_id(), self.next_put_index())
        h = oid.hex()
        if Config.memory_callsite_capture:
            self._note_callsite([h])
        loc = self.store_value(h, value)
        with self._lock:
            self._own.set_location(h, loc, event="put")
            ev = self.object_events.pop(h, None)
            if ev is not None:
                ev.set()
        return ObjectRef(oid, self.address)

    def store_value(self, oid_hex: str, value: Any) -> Tuple:
        """Serialize + store a value with ONE copy of its buffers: the
        envelope is sized up front and header/meta/arrays scatter-write
        directly into the shm block `store.create` returns (no joined
        intermediate blob). Small envelopes stay inline (zero store
        RPCs); returns the location tuple."""
        _t0 = _spans.begin()
        total = 0
        try:
            meta, buffers = ser.serialize(value)
            raws = ser.raw_buffers(buffers)
            total, offsets = ser.plan_envelope(meta, raws)
            if total <= Config.max_inline_object_size:
                out = bytearray(total)
                ser.write_envelope(out, meta, raws, offsets)
                return (INLINE, bytes(out))
            buf = self.store.create(oid_hex, total)
            try:
                ser.write_envelope(buf, meta, raws, offsets)
                self.store.seal(oid_hex)
            except BaseException:
                # reclaim the block: a fast-path allocation the server
                # never saw would otherwise leak arena space until store
                # teardown
                self.store.abort_create(oid_hex)
                raise
            _transport_bytes(total, "put")
            return (STORE, self.store.address, total)
        finally:
            _spans.end("cw.store_value", _t0, bytes=total)

    def store_blob(self, oid_hex: str, blob: bytes) -> Tuple:
        """Write an already-serialized envelope inline or to the local
        shm store; returns its location tuple. Prefer store_value, which
        skips the intermediate blob entirely."""
        if len(blob) <= Config.max_inline_object_size:
            return (INLINE, blob)
        buf = self.store.create(oid_hex, len(blob))
        try:
            buf[:len(blob)] = blob
            self.store.seal(oid_hex)
        except BaseException:
            self.store.abort_create(oid_hex)
            raise
        _transport_bytes(len(blob), "put")
        return (STORE, self.store.address, len(blob))

    def get(self, refs: List[ObjectRef], timeout: Optional[float] = None
            ) -> List[Any]:
        """Batched multi-ref get: resolve every ref's location first
        (per-ref wait-graph edges, removed the moment that ref
        resolves), then materialize the whole batch — all local store
        objects in ONE store_wait RPC, remote replicas via pipelined
        concurrent pulls, inline values with zero RPCs."""
        deadline = None if timeout is None else time.monotonic() + timeout
        blocked_notified = False
        _t0 = _spans.begin()
        try:
            hexes = [ref.hex() for ref in refs]
            locs: List[Optional[Tuple]] = [None] * len(refs)
            for i, ref in enumerate(refs):
                need_wait = not self._ready_nowait(ref)
                if need_wait and self.mode == "worker" and not blocked_notified \
                        and getattr(self._tls, "task_id", None) is not None:
                    blocked_notified = True
                    try:
                        self._nm.call("nm_worker_blocked",
                                      worker_id_hex=self.worker_id.hex())
                    except Exception:  # noqa: BLE001 - blocked hint is advisory only
                        pass
                # may raise DeadlockError instead of blocking forever
                edge = self._register_wait_edge(ref) if need_wait else None
                try:
                    locs[i] = self._await_location(ref, hexes[i], deadline)
                finally:
                    # removed the moment THIS ref resolves: an edge held
                    # until the whole multi-ref get returned could close
                    # a false cycle against a peer we no longer wait on
                    if edge is not None:
                        self._remove_wait_edge(edge)
            return self._materialize_many(refs, hexes, locs, deadline)
        finally:
            # single-ref fast gets are 1:1 with their store_wait RPC
            # (already spanned client-side); record the umbrella span
            # only when it adds information — batching, or a get that
            # actually waited
            if len(refs) > 1 or _spans.perf_counter() - _t0 >= 0.001:
                _spans.end("cw.get", _t0, nrefs=len(refs))
            if blocked_notified:
                try:
                    self._nm.call("nm_worker_unblocked",
                                  worker_id_hex=self.worker_id.hex())
                except Exception:  # noqa: BLE001 - unblock hint is advisory only
                    pass

    def _materialize_many(self, refs: List[ObjectRef], hexes: List[str],
                          locs: List[Optional[Tuple]],
                          deadline: Optional[float]) -> List[Any]:
        """Materialize resolved locations as a batch. Local-store refs
        share one store_wait RPC; distinct remote replicas are pulled
        concurrently (pipelined instead of serial ~300µs round trips);
        anything that misses the fast path (inline, errors, lost objects
        needing lineage recovery) falls back to the per-ref path."""
        prefetched: Dict[str, memoryview] = {}
        local_ids = []
        remote: Dict[str, Tuple] = {}
        for h, loc in zip(hexes, locs):
            if loc is None or loc[0] != STORE or h in remote:
                continue
            store_addr = tuple(loc[1])
            if store_addr == self.store.address:
                local_ids.append(h)
            else:
                remote[h] = (store_addr, int(loc[2]))
        if len(local_ids) > 1:
            try:
                prefetched = self.store.get(
                    list(dict.fromkeys(local_ids)), timeout=5)
            except Exception:  # noqa: BLE001 - per-ref path surfaces it
                prefetched = {}
        if len(remote) > 1:
            # pipeline the pulls: each replica streams on its own thread
            # while the others are in flight (leased for zero-copy use,
            # released when this process's last local ref drops)
            import concurrent.futures as _fut
            with _fut.ThreadPoolExecutor(
                    max_workers=min(8, len(remote))) as pool:
                futs = {
                    h: pool.submit(self._pull_replica, h, addr, size)
                    for h, (addr, size) in remote.items()}
            for h, f in futs.items():
                try:
                    prefetched[h] = f.result()
                except Exception:  # noqa: BLE001 - per-ref path retries
                    pass
        out: List[Any] = []
        for ref, h, loc in zip(refs, hexes, locs):
            buf = prefetched.get(h)
            if buf is not None:
                try:
                    out.append(ser.unpack(buf))
                    continue
                except Exception:  # noqa: BLE001 - torn/evicted: re-get
                    logger.warning("batched unpack of %s failed; "
                                   "refetching", h[:16], exc_info=True)
            out.append(self._get_one(ref, deadline))
        return out

    def _pull_replica(self, oid_hex: str, store_addr: Tuple[str, int],
                      size: int) -> memoryview:
        """Pull + lease a remote object's replica into the local store;
        the lease (released with the last local ref, see
        remove_local_ref) keeps the zero-copy view valid."""
        view = self.store.pull(oid_hex, store_addr, size, pin=True)
        with self._lock:
            self._own.add_replica_lease(oid_hex)
        _transport_bytes(size, "pull")
        return view

    def _remove_wait_edge(self, token: str) -> None:
        # token-keyed and idempotent: the rpc layer retries it through
        # connection blips, so a stale edge can't outlive this get
        try:
            self._gcs.call("wait_graph_remove", token=token)
        except Exception:  # noqa: BLE001 - GCS gone; edge moot
            pass

    # Blocking this long before an edge is registered keeps the GCS off
    # the hot path (gets that resolve quickly — the common trajectory
    # plane — never call it) and closes the remove/add race: a peer that
    # just stopped waiting on us has long since sent its removal by the
    # time our registration lands.
    WAIT_EDGE_GRACE_S = 0.2

    def _register_wait_edge(self, ref: ObjectRef) -> Optional[str]:
        """Actor-context blocking get on another actor's pending result:
        register a waits-for edge with the GCS wait graph BEFORE
        blocking; returns the edge's token to remove once the ref
        resolves, or None when no edge applies. If the edge would
        close a cycle, every actor on it is waiting on the next with
        its executor thread held — raise DeadlockError (with the cycle)
        instead of joining the hang. Best-effort: an unreachable GCS
        only costs detection, not the get itself."""
        ex = self.executor
        if ex is None or ex.actor_id is None:
            return None
        if ex.has_spare_capacity():
            # an idle executor thread can still serve calls from cycle
            # peers (async actors, max_concurrency > 1): not a hard
            # deadlock, so don't contribute an edge
            return None
        waiter = ex.actor_id.hex()
        with self._lock:
            entry = self.tasks.get(ref.task_id().hex())
            target = entry.spec.actor_id if entry is not None else None
        if target is None:
            return None  # not an actor task we submitted; no actor edge
        target_hex = target.hex()
        if target_hex == waiter:
            # re-entrant self-get surfaces as a plain hang/timeout
            return None
        # Grace wait on the local completion event (the target came from
        # our own task table, so we own the ref and its event): fast
        # results never involve the GCS at all.
        with self._lock:
            loc = self.objects.get(ref.hex())
            # events are lazy: create one here (same lock as the
            # completion setters) so the grace wait below has something
            # to wait on even when no getter has parked yet
            ev = self.object_events.setdefault(
                ref.hex(), threading.Event()) \
                if loc is not None and loc[0] == PENDING else None
        if loc is None or loc[0] != PENDING:
            return None  # already resolved
        if ev is not None and ev.wait(timeout=self.WAIT_EDGE_GRACE_S):
            return None  # resolved within the grace window
        token = os.urandom(8).hex()
        try:
            cycle = self._gcs.call("wait_graph_add", waiter_hex=waiter,
                                   target_hex=target_hex, token=token)
        except Exception:  # noqa: BLE001 - detection is advisory
            return None
        if cycle is not None:
            from ray_tpu._private.wait_graph import format_cycle
            names = {e["actor_id"]: e["class_name"] for e in cycle}
            path = format_cycle([e["actor_id"] for e in cycle], names)
            raise exc.DeadlockError(
                f"blocking get() would deadlock: waits-for cycle "
                f"{path} (every actor on the cycle holds its executor "
                f"thread; return the ObjectRef, use an async method, or "
                f"raise max_concurrency)",
                cycle=[e["actor_id"] for e in cycle])
        return token

    def _is_own(self, ref: ObjectRef) -> bool:
        return ref.owner_address in (None, self.address)

    def _ready_nowait(self, ref: ObjectRef) -> bool:
        h = ref.hex()
        with self._lock:
            loc = self.objects.get(h)
        if loc is not None and loc[0] != PENDING:
            return True
        if self._is_own(ref):
            return False
        try:
            loc = self._owner_client(ref).call("cw_get_object", oid_hex=h)
        except Exception:  # noqa: BLE001
            return False
        if loc[0] in (PENDING, "unknown"):
            return False
        with self._lock:
            self.objects.setdefault(h, loc)
        return True

    def _owner_client(self, ref: ObjectRef) -> rpc_lib.RpcClient:
        assert ref.owner_address is not None
        return self._pool.get(ref.owner_address)

    def _recover_object(self, oid_hex: str) -> bool:
        """Lineage reconstruction: re-execute the task that created a lost
        object (reference object_recovery_manager.cc:22 RecoverObject →
        task_manager.cc:255 ResubmitTask). Returns True if recovery is in
        flight (or the object is already being recomputed)."""
        oid = ObjectID(bytes.fromhex(oid_hex))
        if oid.is_put():
            return False  # puts have no lineage; their data is gone
        # Verify actual loss first: a borrower's transient pull failure must
        # not trigger a duplicate re-execution over a live primary copy.
        with self._lock:
            loc = self.objects.get(oid_hex)
        if loc is not None and loc[0] == STORE:
            try:
                if self._pool.get(tuple(loc[1])).call(
                        "store_contains", object_id=oid_hex):
                    return True  # primary alive; caller should retry its pull
            except Exception:  # noqa: BLE001 - store/node really gone
                pass
        with self._lock:
            entry = self.tasks.get(oid.task_id().hex())
            if entry is None or entry.spec.task_type != TaskType.NORMAL_TASK:
                return False  # actor tasks aren't safely replayable
            loc = self.objects.get(oid_hex)
            if loc is not None and loc[0] == PENDING:
                return True  # already recomputing
            if loc is not None and loc[0] in (FREED, ERROR):
                return False
            if not entry.done:
                return True  # original execution still in flight
            entry.done = False
            # reset every object this task produced — declared returns AND
            # dynamic-return children (any oid embedding this task id) —
            # so getters wait for the recomputation instead of re-failing
            # on the stale location
            task_prefix = oid.task_id().hex()
            produced = [rid.hex() for rid in entry.return_ids]
            produced += [h2 for h2 in self.objects
                         if h2.startswith(task_prefix)
                         and h2 not in produced]
            for rh in produced:
                if self.objects.get(rh, (PENDING,))[0] not in (FREED, INLINE,
                                                              ERROR):
                    self._own.set_location(rh, (PENDING,),
                                           event="recover")
                    self.object_events.setdefault(rh, threading.Event()).clear()
        logger.info("recovering object %s by resubmitting task %s",
                    oid_hex[:16], entry.spec.function_name)
        # Re-pin args for the re-execution; if an arg object was itself
        # evicted, the executing worker's get() triggers recursive recovery.
        self._pin_args(entry.spec.arg_object_refs)
        threading.Thread(target=self._enqueue_for_lease,
                         args=(entry.spec.task_id.hex(), entry),
                         daemon=True, name="lineage-recover").start()
        return True

    def _on_recover_object(self, oid_hex: str) -> bool:
        return self._recover_object(oid_hex)

    def _await_location(self, ref: ObjectRef, h: str,
                        deadline: Optional[float]) -> Tuple:
        """Block until the ref has a resolved (non-PENDING) location and
        return it — the waiting half of a get, RPC-free for own refs."""
        # Long-polls park server-side for up to 30s; a dedicated
        # per-get connection keeps them off the shared pooled client,
        # where they would head-of-line-block every other call to that
        # owner from this process (RpcClient serializes on one socket).
        longpoll_client: Optional[rpc_lib.RpcClient] = None
        try:
            while True:
                with self._lock:
                    loc = self.objects.get(h)
                    if loc is not None and loc[0] == PENDING:
                        ev = self.object_events.setdefault(
                            h, threading.Event())
                    else:
                        ev = None
                if loc is not None and loc[0] != PENDING:
                    return loc
                if self._is_own(ref):
                    if loc is None:
                        raise exc.ObjectLostError(
                            f"object {h[:16]} unknown to its owner "
                            "(freed?)")
                    # our own pending task result: wait on event
                    remaining = None if deadline is None \
                        else deadline - time.monotonic()
                    if remaining is not None and remaining <= 0:
                        raise exc.GetTimeoutError(
                            f"get timed out waiting for {h[:16]}")
                    ev.wait(timeout=min(remaining, 1.0)
                            if remaining is not None else 1.0)
                    continue
                # borrower: long-poll the owner (reference pubsub
                # long-poll; a 5ms busy-poll collapses at scale)
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise exc.GetTimeoutError(
                        f"get timed out waiting for {h[:16]}")
                try:
                    if longpoll_client is None:
                        longpoll_client = rpc_lib.RpcClient(
                            ref.owner_address, timeout=120)
                    loc = longpoll_client.call(
                        "cw_wait_object", oid_hex=h,
                        timeout=min(remaining or 30.0, 30.0))
                except rpc_lib.ConnectionLost:
                    raise exc.OwnerDiedError(
                        f"owner {ref.owner_address} of {h[:16]} died")
                if loc[0] in (PENDING, "unknown"):
                    if deadline is not None and time.monotonic() > deadline:
                        raise exc.GetTimeoutError(
                            f"get timed out waiting for {h[:16]}")
                    time.sleep(0.05 if loc[0] == "unknown" else 0.0)
                    continue
                with self._lock:
                    self.objects.setdefault(h, loc)
                return loc
        finally:
            if longpoll_client is not None:
                longpoll_client.close()

    def _get_one(self, ref: ObjectRef, deadline: Optional[float]) -> Any:
        h = ref.hex()
        recover_attempts = [0]
        while True:
            loc = self._await_location(ref, h, deadline)
            result = self._materialize_with_recovery(
                ref, h, loc, recover_attempts)
            if result is _RETRY:
                continue
            return result

    def _materialize_with_recovery(self, ref, h, loc,
                                   recover_attempts: List[int]) -> Any:
        """Materialize, attempting lineage reconstruction on loss. Returns
        _RETRY when recovery is in flight — the caller's loop re-reads the
        (now PENDING) location and waits for the recomputed value."""
        try:
            return self._materialize(h, loc)
        except exc.ObjectFreedError:
            raise
        except exc.ObjectLostError:
            recover_attempts[0] += 1
            if recover_attempts[0] > 3:
                raise
            if self._is_own(ref):
                if not self._recover_object(h):
                    raise
            else:
                with self._lock:
                    self.objects.pop(h, None)  # drop stale cached loc
                try:
                    ok = self._owner_client(ref).call(
                        "cw_recover_object", oid_hex=h)
                except Exception:  # noqa: BLE001
                    raise exc.OwnerDiedError(
                        f"owner {ref.owner_address} of {h[:16]} "
                        "unreachable during recovery") from None
                if not ok:
                    raise
            time.sleep(0.01)
            return _RETRY

    def _materialize(self, oid_hex: str, loc: Tuple) -> Any:
        tag = loc[0]
        if tag == INLINE:
            return ser.unpack(memoryview(loc[1]))
        if tag == STORE:
            _, store_addr, size = loc
            store_addr = tuple(store_addr)
            try:
                if store_addr == self.store.address:
                    # Own/local objects are sealed before their location is
                    # recorded; a short wait distinguishes a momentary race
                    # from real loss (which lineage recovery then handles).
                    bufs = self.store.get([oid_hex], timeout=5)
                else:
                    # zero-copy view of the pulled replica, leased so
                    # eviction can't rewrite it under the deserialized
                    # arrays (released with our last local ref)
                    bufs = {oid_hex: self._pull_replica(
                        oid_hex, store_addr, size)}
            except ObjectStoreFullError:
                raise
            except Exception as e:  # noqa: BLE001 - peer store refused/died
                raise exc.ObjectLostError(
                    f"object {oid_hex[:16]} unavailable from store "
                    f"{store_addr}: {e}") from None
            if oid_hex not in bufs:
                raise exc.ObjectLostError(f"object {oid_hex[:16]} lost in store")
            return ser.unpack(bufs[oid_hex])
        if tag == ERROR:
            err = pickle.loads(loc[1])
            if isinstance(err, exc.RayTaskError):
                raise err.as_instanceof_cause()
            raise err
        if tag == FREED:
            raise exc.ObjectFreedError(f"object {oid_hex[:16]} was freed")
        raise exc.RaySystemError(f"bad object location {loc!r}")

    def wait(self, refs: List[ObjectRef], num_returns: int,
             timeout: Optional[float]) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        deadline = None if timeout is None else time.monotonic() + timeout
        ready: List[ObjectRef] = []
        pending = list(refs)
        while True:
            still = []
            for r in pending:
                (ready if self._ready_nowait(r) else still).append(r)
            pending = still
            if len(ready) >= num_returns or not pending:
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            time.sleep(0.005)
        # preserve input order
        ready_set = {r.hex() for r in ready}
        ordered_ready = [r for r in refs if r.hex() in ready_set][:num_returns]
        rest = [r for r in refs if r.hex() not in
                {x.hex() for x in ordered_ready}]
        return ordered_ready, rest

    def free(self, refs: List[ObjectRef]) -> None:
        with self._lock:
            for r in refs:
                if self._is_own(r):
                    # explicit ray.free contract: free even though
                    # references may still exist (forced transition)
                    self._maybe_free_locked(r.hex(), force=True)

    # ------------------------------------------------------------------
    # Function export/import (reference _private/function_manager.py)
    # ------------------------------------------------------------------

    def export_function(self, fn: Any) -> str:
        blob = ser.dumps_function(fn)
        import hashlib
        key = f"fn:{self.job_id.hex()}:{hashlib.sha1(blob).hexdigest()}"
        if key not in self._fn_cache:
            self._gcs.call("kv_put", key=key, value=blob, overwrite=False)
            self._fn_cache[key] = fn
        return key

    def import_function(self, key: str) -> Any:
        fn = self._fn_cache.get(key)
        if fn is None:
            blob = self._gcs.call("kv_get", key=key)
            if blob is None:
                raise exc.RaySystemError(f"function {key} not found in GCS")
            fn = ser.loads_function(blob)
            self._fn_cache[key] = fn
        return fn

    # ------------------------------------------------------------------
    # Normal task submission
    # ------------------------------------------------------------------

    # interning above this trades little dedup for LRU residency: big
    # arg blobs are rare and unlikely to repeat byte-identically
    _BLOB_INTERN_MAX = 64 * 1024

    def _intern_blob(self, blob: bytes) -> bytes:
        """Return a shared bytes object equal to `blob` (LRU-bounded by
        Config.spec_blob_cache_entries). A fan-out of N .remote() calls
        on the same function/args pickles N identical blobs; interning
        keeps one and lets the N-1 copies die young."""
        if not blob or len(blob) > self._BLOB_INTERN_MAX or \
                Config.spec_blob_cache_entries <= 0:
            return blob
        with self._blob_cache_lock:
            c = self._blob_cache
            got = c.get(blob)
            if got is not None:
                c.move_to_end(blob)
                self.blob_cache_hits += 1
                return got
            c[blob] = blob
            if len(c) > Config.spec_blob_cache_entries:
                c.popitem(last=False)
        return blob

    def submit_task(self, spec: TaskSpec) -> List[ObjectRef]:
        # lets a same-node executor report cw_task_done over the shm
        # ring instead of the loopback socket
        spec.owner_node_id = self.node_id_hex
        spec.args = self._intern_blob(spec.args)
        return_ids = [ObjectID.for_task_return(spec.task_id, i + 1)
                      for i in range(spec.num_returns)]
        entry = _TaskEntry(spec=spec, retries_left=spec.max_retries,
                           return_ids=return_ids,
                           sched_key=self._sched_key(spec),
                           submit_seq=self.next_put_index())
        with self._lock:
            for oid in return_ids:
                self._own.set_location(oid.hex(), (PENDING,),
                                       event="submit")
            self.tasks[spec.task_id.hex()] = entry
        # the caller's refs register BEFORE the task can complete: the
        # free-on-resolve check in _on_task_done reads local_refs == 0
        # as "nobody can ever reach this result" — a fast completion
        # racing a later registration would free a live result
        refs_out = [ObjectRef(oid, self.address) for oid in return_ids]
        if Config.memory_callsite_capture and return_ids:
            self._note_callsite([oid.hex() for oid in return_ids])
        self._attach_trace(spec)
        self.task_events.record(
            spec.task_id.hex(), state="SUBMITTED", ts_submitted=_ev_now(),
            name=spec.function_name, type="NORMAL_TASK",
            job_id=spec.job_id.hex(), trace_id=spec.trace_id,
            parent_task_id=spec.parent_task_id)
        spec.locality_hints, spec.arg_locations = \
            self._locality_info(spec.arg_object_refs)
        self._pin_args(spec.arg_object_refs)
        self._enqueue_for_lease(spec.task_id.hex(), entry)
        return refs_out

    @staticmethod
    def _sched_key(spec: TaskSpec):
        """Scheduling-key for owner-side lease reuse (reference
        direct_task_transport SchedulingKey): tasks may share a leased
        worker iff everything the lease depends on matches — resource
        shape, runtime env, scheduling strategy/PG slot, and the
        function (keeps max_calls accounting per-function simple)."""
        return spec.scheduling_key()

    def _enqueue_for_lease(self, task_hex: str, entry: _TaskEntry,
                           nm=None) -> None:
        """Queue a task under its scheduling key; at most one lease
        request per key is in flight (the grant/done paths keep draining
        the queue over leased workers and re-request while backlogged)."""
        key = entry.sched_key
        with self._lock:
            ks = self._ltab.state(key)
            if not entry.in_key_queue:
                # retry of a task still queued (e.g. node-death fail of
                # a queued lease head) must not enqueue a second copy —
                # the duplicate would execute concurrently
                ks.queue.append(task_hex)
                entry.in_key_queue = True
        self._maybe_request_leases(key, nm=nm)

    # Cap on outstanding lease requests per scheduling key (reference
    # direct_task_transport max_pending_lease_requests): enough to fan a
    # burst out over several workers, bounded so one key can't flood the
    # NM queue.
    MAX_PENDING_LEASE_REQUESTS = 4

    def _maybe_request_leases(self, key, nm=None) -> None:
        """Issue lease requests until outstanding requests cover the
        backlog (one per queued task, capped): parallelism comes from
        multiple leases, latency from per-lease pipelining.

        With task_lease_batching the NM round trip moves OFF this
        thread entirely: slots are claimed here (so the covered-by-
        backlog invariant holds at claim time), then the requester
        thread ships them — coalescing claims that pile up while one
        RPC is in flight into a single nm_lease_request_batch. The
        submit path's cost drops to local bookkeeping; this is the
        difference between ~1/RTT tasks/s and wire-speed submission."""
        while True:
            with self._lock:
                ks = self._ltab.get(key)
                if ks is None:
                    return
                desired = min(len(ks.queue),
                              self.MAX_PENDING_LEASE_REQUESTS)
                if ks.requests_in_flight >= desired:
                    return
                nslots = desired - ks.requests_in_flight \
                    if Config.task_lease_batching else 1
                for _ in range(nslots):
                    self._ltab.claim_slot(ks)
            if Config.task_lease_batching:
                self._lease_req_q.put((key, nslots, nm))
                return
            self._request_lease_for_key(key, nm=nm)
            nm = None

    def _lease_request_loop(self) -> None:
        """Requester thread: drains claimed-slot tickets, merges them
        per key, and issues the (batch) lease RPCs. Claims were made by
        the enqueuer, so nothing here races the slot accounting; one
        slow NM can only stall its own key's inline send (other keys of
        the same drain round go to short-lived threads)."""
        while not self._shutdown:
            try:
                item = self._lease_req_q.get(timeout=1.0)
            except queue.Empty:
                continue
            if item is None:
                return
            batch = [item]
            try:
                while True:
                    more = self._lease_req_q.get_nowait()
                    if more is not None:
                        batch.append(more)
            except queue.Empty:
                pass
            merged: Dict[bytes, List] = {}
            for key, nslots, nm in batch:
                cur = merged.get(key)
                if cur is None:
                    merged[key] = [nslots, nm]
                else:
                    cur[0] += nslots
                    if nm is not None:
                        cur[1] = nm
            spread = len(merged) > 1
            for key, (nslots, nm) in merged.items():
                if spread:
                    threading.Thread(
                        target=self._send_lease_requests,
                        args=(key, nslots, nm), daemon=True,
                        name="lease-request-key").start()
                else:
                    self._send_lease_requests(key, nslots, nm)

    def _send_lease_requests(self, key, nslots: int, nm=None) -> None:
        try:
            if nslots == 1:
                self._request_lease_for_key(key, nm=nm)
            else:
                self._request_lease_batch_for_key(key, nslots, nm=nm)
        except Exception:  # noqa: BLE001 - a stray error here must not
            # kill the requester thread; the slot-balance watchdog
            # surfaces any slots this leaks
            logger.exception("lease request for key %r failed", key)

    def _release_request_slot(self, key) -> None:
        with self._lock:
            ks = self._ltab.get(key)
            if ks is not None:
                self._ltab.release_slot(ks, event="slot_release")

    def _locality_info(self, arg_ids: List[ObjectID]):
        """(node id hex -> resident arg bytes, oid -> (store, size)) from
        the owner's location cache (reference lease_policy.h:56 +
        the per-arg locations the raylet's dependency manager pulls);
        inline args contribute nothing (they travel in the spec)."""
        if not arg_ids:
            return {}, {}
        store_to_node = self._store_to_node_map()
        hints: Dict[str, float] = {}
        locations: Dict[str, Any] = {}
        with self._lock:
            for oid in arg_ids:
                loc = self.objects.get(oid.hex())
                if loc is not None and loc[0] == STORE:
                    locations[oid.hex()] = (tuple(loc[1]), int(loc[2]))
                    node = store_to_node.get(tuple(loc[1]))
                    if node is not None:
                        hints[node] = hints.get(node, 0.0) + float(loc[2])
        return hints, locations

    def _store_to_node_map(self) -> Dict[Tuple[str, int], str]:
        ts, cached = self._store_map_cache
        if time.monotonic() - ts < 5.0:
            return cached
        try:
            nodes = self._gcs.call("get_all_nodes")
        except Exception:  # noqa: BLE001
            return cached
        mapping = {tuple(n.store_address): n.node_id.hex()
                   for n in nodes if n.alive}
        self._store_map_cache = (time.monotonic(), mapping)
        return mapping

    def _on_lease_respill(self, task_id: TaskID,
                          nm_address: Tuple[str, int],
                          from_address: Optional[Tuple[str, int]] = None
                          ) -> None:
        """Our local raylet re-routed a queued lease to another node that
        became feasible (e.g. a PG bundle committed there)."""
        with self._lock:
            entry = self.tasks.get(task_id.hex())
            if entry is not None:
                ks = self._ltab.get(entry.sched_key)
                if ks is not None:
                    # the queued request is gone at the sending NM: the
                    # slot we hold is no longer parked anywhere until
                    # the re-request below parks it again. The SENDER
                    # names itself — entry.lease_node is unreliable
                    # here, since a grant from another request may have
                    # already pushed this task elsewhere and overwritten
                    # it (older NMs omit from_address; fall back).
                    old = (tuple(from_address) if from_address
                           else tuple(entry.lease_node)
                           if entry.lease_node else None)
                    self._ltab.unpark(ks, old)
        if entry is None:
            return
        # The old queued request is gone at the NM: re-enter the request
        # path at the redirect target (request_in_flight stays held by
        # us). Even when the task is already done (cancelled/retried
        # while its request sat queued) we must NOT return early:
        # _key_head drains dead queue heads and releases the held slot —
        # an early return here leaked requests_in_flight permanently and
        # stalled the key once MAX_PENDING_LEASE_REQUESTS slots were
        # gone (ADVICE round 5; the metrics watchdog's
        # lease_slot_balance probe now alarms on exactly this).
        threading.Thread(
            target=self._request_lease_for_key,
            args=(entry.sched_key,),
            kwargs={"nm": self._pool.get(tuple(nm_address))},
            daemon=True, name="lease-respill").start()

    def _key_head(self, key: bytes):
        """(task_hex, entry) of the first live queued task of the key,
        without popping; releases the caller's request slot and returns
        None when the queue has no live work."""
        with self._lock:
            ks = self._ltab.get(key)
            if ks is None:
                return None
            while ks.queue:
                h = ks.queue[0]
                entry = self.tasks.get(h)
                if entry is not None and not entry.done:
                    return h, entry
                ks.queue.popleft()
                if entry is not None:
                    entry.in_key_queue = False
            self._ltab.release_slot(ks, event="slot_release_drained")
            return None

    def _key_heads(self, key: bytes, n: int):
        """Up to `n` distinct live queued (task_hex, entry) pairs of the
        key, front-drained like _key_head but WITHOUT popping the live
        ones (grants pop via _push_on_lease). The caller holds `n`
        request slots; surplus slots beyond the live work found are
        released here so slot accounting stays covered-by-backlog."""
        heads = []
        with self._lock:
            ks = self._ltab.get(key)
            if ks is None:
                return heads
            while ks.queue:
                h = ks.queue[0]
                entry = self.tasks.get(h)
                if entry is not None and not entry.done:
                    break
                ks.queue.popleft()
                if entry is not None:
                    entry.in_key_queue = False
            for h in ks.queue:
                entry = self.tasks.get(h)
                if entry is None or entry.done:
                    continue
                heads.append((h, entry))
                if len(heads) >= n:
                    break
            for _ in range(n - len(heads)):
                self._ltab.release_slot(
                    ks, event="slot_release_drained" if not heads
                    else "slot_release")
        return heads

    def _request_lease_batch_for_key(self, key: bytes, nslots: int,
                                     nm=None) -> None:
        """Multi-slot lease request: one nm_lease_request_batch RPC
        covers up to `nslots` queue heads (the caller claimed that many
        slots). Replies that queued park their slot at the NM exactly
        like the singleton path; spilled/infeasible replies — and any
        batch-level connection failure — fall back to the singleton
        path, which owns the full spill-following/backoff machinery,
        one claimed slot per remaining reply."""
        heads = self._key_heads(key, nslots)
        if not heads:
            return
        nm_cur = nm if nm is not None else self._nm
        with self._lock:
            for _h, entry in heads:
                # recorded BEFORE the request so an async grant arriving
                # first knows where to return the lease (same contract
                # as the singleton path)
                entry.lease_node = nm_cur.address
        try:
            replies = nm_cur.call(
                "nm_lease_request_batch",
                specs=[entry.spec for _h, entry in heads],
                reply_to=self.address)
        except Exception:  # noqa: BLE001 - connection-level failure:
            # not a task failure. Re-enter the singleton path per held
            # slot; it restarts from the local NM with its own
            # conn-failure budget.
            for _ in heads:
                self._request_lease_for_key(key)
            return
        fallbacks = 0
        spill_nm = None
        with self._lock:
            ks = self._ltab.get(key)
            for kind, payload in replies:
                if kind == "queued" and ks is not None:
                    self._ltab.park(ks, tuple(nm_cur.address))
                else:
                    # "spill"/"infeasible": this slot never parked; the
                    # singleton path below re-drives it (and follows the
                    # first spill target directly)
                    fallbacks += 1
                    if kind == "spill" and spill_nm is None:
                        spill_nm = tuple(payload)
        for i in range(fallbacks):
            self._request_lease_for_key(
                key, nm=self._pool.get(spill_nm)
                if i == 0 and spill_nm is not None else None)

    def _request_lease_for_key(self, key: bytes, nm=None) -> None:
        """Lease a worker for the key's queue head; follow spillback
        redirects (reference direct_task_transport.cc:349,505). Called
        with ONE request slot already claimed by the caller; every exit
        either leaves the request queued at an NM (the grant releases
        the slot) or releases it here. Iterates (not recurses) over
        queue heads so a long run of infeasible tasks fails them one by
        one without growing the stack."""
        while True:
            head = self._key_head(key)
            if head is None:
                return
            task_hex, entry = head
            spec = entry.spec
            attempt = 0
            conn_failures = 0
            nm_cur = nm if nm is not None else self._nm
            nm = None  # a respill redirect only applies to the first head
            verdict = None
            while attempt < 16:
                with self._lock:
                    # Recorded BEFORE the request so the async grant
                    # callback (which may arrive first) can find where to
                    # return it.
                    entry.lease_node = nm_cur.address
                try:
                    kind, payload = nm_cur.call(
                        "nm_request_lease", spec=spec,
                        reply_to=self.address, spill_count=attempt)
                except Exception as e:  # noqa: BLE001
                    # Connection-level failures are NOT task failures:
                    # a spill target died (stale cluster view) or the
                    # local NM hiccuped. Back off and restart from the
                    # local NM — its view drops the dead node once the
                    # GCS health check fires — without burning the
                    # task's retry budget (reference lease clients
                    # retry RPC errors; max_retries is for execution
                    # failures).
                    conn_failures += 1
                    if conn_failures <= 50:
                        time.sleep(0.2)
                        nm_cur = self._nm
                        attempt = 0
                        continue
                    self._release_request_slot(key)
                    self._fail_task(task_hex, "SCHEDULING_FAILED",
                                    f"lease request failed: {e}",
                                    retry=True)
                    return
                if kind == "queued":
                    # grant arrives async; request stays in flight,
                    # now parked at this NM (the grant or a respill
                    # unparks it)
                    with self._lock:
                        ks = self._ltab.get(key)
                        if ks is not None:
                            self._ltab.park(ks, tuple(nm_cur.address))
                    return
                if kind == "infeasible":
                    verdict = str(payload)
                    break
                nm_cur = self._pool.get(tuple(payload))  # spillback
                attempt += 1
            if verdict is None:
                verdict = "too many spillbacks"
            with self._lock:
                ks = self._ltab.get(key)
                if ks is not None:
                    try:
                        ks.queue.remove(task_hex)
                        entry.in_key_queue = False
                    except ValueError:
                        pass
            self._fail_task(task_hex, "SCHEDULING_FAILED", verdict,
                            retry=False)
            # loop: the rest of the queue gets its own verdict

    def _kick_key(self, key: bytes) -> None:
        """Ensure lease requests cover the key's queued work."""
        self._maybe_request_leases(key)

    def _on_lease_granted(self, lease_id: str, task_id: TaskID,
                          worker_address: Tuple[str, int],
                          worker_id: str, node_id: str,
                          nm_address: Optional[Tuple[str, int]] = None
                          ) -> None:
        with self._lock:
            fresh = self._ltab.note_grant(lease_id)
            named = self.tasks.get(task_id.hex())
        if not fresh:
            # at-least-once delivery: the NM re-queues a lease whose
            # reply failed transiently, but the first delivery may have
            # landed (reply lost after processing) and already done the
            # slot/park/lease bookkeeping — hand the duplicate straight
            # back instead of corrupting the counts
            self._return_lease(lease_id, None, nm_address=nm_address)
            return
        key = named.sched_key if named is not None else None
        if key is None:
            # Unknown task (e.g. owner restarted): just hand it back.
            self._return_lease(lease_id, named, nm_address=nm_address)
            return
        with self._lock:
            ks = self._ltab.state(key)
            self._ltab.release_slot(ks, event="slot_granted")
            # signed: may beat the request's own "queued" reply
            self._ltab.unpark(ks, tuple(nm_address) if nm_address
                              else None)
            self._ltab.add_lease(
                ks, lease_id, (tuple(worker_address),
                               tuple(nm_address) if nm_address
                               else None, node_id))
        # The grant names the task whose spec rode the request, but any
        # queued task of the same key may run on it (reference
        # OnWorkerIdle drains the SchedulingKey queue).
        self._push_on_lease(key, lease_id)
        # Keep one request in flight while backlog remains — on a THREAD:
        # this handler runs inside the NM's blocking cw_lease_granted
        # call, and a synchronous nm.call back from here can three-way
        # deadlock on the shared per-address RpcClient locks (owner
        # handler waits NM, NM's next grant waits the client lock our
        # caller holds).
        with self._lock:
            ks2 = self._sched_keys.get(key)
            backlog = ks2 is not None and bool(ks2.queue)
        if backlog:
            threading.Thread(target=self._kick_key, args=(key,),
                             daemon=True, name="lease-kick").start()

    def _on_lease_granted_batch(self, grants: List[Dict[str, Any]]) -> None:
        """Grouped grant replies from one NM dispatch pass: each element
        runs the full singleton handler (note_grant's dedup ring makes a
        replayed batch element a returned duplicate, not a double
        grant)."""
        for g in grants:
            self._on_lease_granted(**g)

    def ownership_snapshot(self, object_id: Optional[str] = None,
                           limit: int = 200) -> Dict[str, Any]:
        """This process's ownership-protocol view: live RefState rows
        (every object with a live claim), per-scheduling-key LeaseState
        summaries, and the transition ring's tail — the wire form
        behind `ray_tpu ownership` / /api/ownership / util.state."""
        with self._lock:
            if object_id:
                keys = {h for h in (set(self.objects)
                                    | set(self.local_refs)
                                    | set(self.arg_pins)
                                    | set(self.borrower_pins)
                                    | set(self._replica_leases)
                                    | set(self.borrowed))
                        if h.startswith(object_id)}
                objs = [self._own.describe(h) for h in sorted(keys)]
            else:
                objs = self._own.live_objects()
            lease_keys = self._ltab.summary()
            running = {lid: sorted(h[:16] for h in hs)
                       for lid, hs in self._lease_running.items()}
            ttl_count = len(self._ttl_pins)
        snap = _ownership.ring().snapshot(
            key_prefix=object_id or None, limit=limit)
        return {
            "proc_uid": _spans.PROC_UID,
            "pid": os.getpid(),
            "label": _spans.process_label(),
            "node_id": self.node_id_hex,
            "worker_id": self.worker_id.hex(),
            "mode": self.mode,
            "wall_time": time.time(),
            "objects": objs,
            "lease_keys": lease_keys,
            "running_leases": running,
            "ttl_pins": ttl_count,
            "transitions": snap["transitions"],
            "anomalies": snap["anomalies"],
        }

    # Tasks pushed-but-incomplete per lease: 2 = the worker always has
    # the next task queued locally when it finishes one, so the owner's
    # done→push round trip leaves the worker's critical path (the
    # reference worker submit queues give the same pipelining). The
    # worker executes normal tasks on ONE thread, so depth never
    # over-commits the lease's resources.
    LEASE_PIPELINE_DEPTH = 2

    def _shm_send(self, addr, peer_node_id, method: str,
                  kwargs: Dict[str, Any]) -> bool:
        """Try the same-node shm ring to the peer process at `addr`;
        False means not eligible / ring or arena full and the caller
        must use the socket path (the message was NOT enqueued). A
        doorbell send failure propagates — that is the same dead-peer
        signal a socket one-way raises."""
        if self._shutdown or not Config.shm_task_channel \
                or not peer_node_id or peer_node_id != self.node_id_hex:
            return False
        key = tuple(addr)
        s = self._shm_senders.get(key)
        if s is None:
            # the ring file lives next to the node's store arena — its
            # directory doubles as "the shared-memory place on this
            # node"; no arena means no shm fast path
            arena = self.store.shared_arena()
            if arena is None:
                return False
            with self._shm_lock:
                s = self._shm_senders.get(key)
                if s is None:
                    try:
                        s = _shm.Sender(
                            os.path.dirname(arena.path),
                            f"{self.worker_id.hex()[:12]}-{key[1]}",
                            int(Config.shm_ring_bytes),
                            doorbell=lambda path, _a=key:
                                self._pool.get(_a).send_oneway(
                                    "shm_doorbell", path=path))
                    except OSError:
                        return False
                    self._shm_senders[key] = s
        try:
            # chaos client hook: drop_connection / partition rules fire
            # on ring sends exactly as they would on the socket path
            # (ConnectionLost propagates to the same call sites)
            chaos_lib.on_client_call(method, key)
            s.send(method, kwargs)
            return True
        except _shm.ShmUnavailable:
            return False

    def _push_on_lease(self, key: bytes, lease_id: str,
                       fallback_entry: Optional[_TaskEntry] = None
                       ) -> None:
        """Keep the leased worker's local queue primed (up to
        LEASE_PIPELINE_DEPTH in-flight tasks); return the lease when the
        key's queue is drained and nothing is in flight.

        All lease-state reads and writes for one push happen under ONE
        lock acquisition: a split check/increment would race concurrent
        decrements from _settle_lease_slot (lost update → the drained
        lease is never returned) and concurrent pushers (over-depth)."""
        while True:
            with self._lock:
                ks = self._ltab.get(key)
                info = ks.leases.get(lease_id) if ks is not None else None
                inflight = ks.lease_inflight.get(lease_id, 0) \
                    if ks is not None else 0
                if info is None:
                    task = None
                    action = "return_untracked" if inflight == 0 else \
                        "noop"
                elif inflight >= self.LEASE_PIPELINE_DEPTH:
                    task = None
                    action = "noop"
                else:
                    worker_address, nm_addr, node_id = info
                    # pop the next live queued task (inline: same lock).
                    # When pipelining BEHIND a running task (inflight >=
                    # 1), never pick a task that PRODUCES a pending arg
                    # of anything running on this lease: the runner may
                    # be blocked in get() on exactly that object, and
                    # normal tasks execute on one thread — queueing the
                    # producer behind its blocked consumer deadlocks the
                    # worker permanently (found by the ownership
                    # fuzzer's kill schedules via retry re-ordering).
                    # Skipped candidates keep their queue position; a
                    # fresh lease (the enqueue path keeps request slots
                    # covering the backlog) runs them elsewhere.
                    unsafe_producers: set = set()
                    if inflight > 0:
                        # TRANSITIVE closure over pending args: the
                        # runner may wait X <- E <- F, and pushing F
                        # behind it deadlocks just as surely as pushing
                        # E (walk bounded by live dependency chains)
                        frontier = list(
                            self._lease_running.get(lease_id, ()))
                        seen_t = set(frontier)
                        while frontier:
                            re_ = self.tasks.get(frontier.pop())
                            if re_ is None:
                                continue
                            for aid in re_.spec.arg_object_refs:
                                if self.objects.get(
                                        aid.hex(),
                                        (None,))[0] != PENDING:
                                    continue
                                p = aid.task_id().hex()
                                if p not in seen_t:
                                    seen_t.add(p)
                                    unsafe_producers.add(p)
                                    frontier.append(p)
                    task = None
                    skipped: List[str] = []
                    while ks.queue:
                        h = ks.queue.popleft()
                        e2 = self.tasks.get(h)
                        if e2 is not None and not e2.done \
                                and h in unsafe_producers:
                            skipped.append(h)
                            continue
                        if e2 is not None:
                            e2.in_key_queue = False
                        if e2 is not None and not e2.done:
                            task = (h, e2)
                            break
                    for h in reversed(skipped):
                        ks.queue.appendleft(h)
                    if task is None:
                        if inflight == 0:
                            self._ltab.drop_lease(ks, lease_id)
                            action = "return_drained"
                        else:
                            # skipped-only backlog: make sure lease
                            # requests still cover it so the skipped
                            # producers run on ANOTHER worker (their
                            # blocked consumer holds this one)
                            action = "kick" if skipped else "noop"
                    elif getattr(task[1].spec, "max_calls", 0) \
                            and inflight >= 1:
                        # no pipelining under max_calls recycling: the
                        # worker may exit right after the current task,
                        # losing a pre-queued one to the death-report
                        # path needlessly
                        ks.queue.appendleft(task[0])
                        task[1].in_key_queue = True
                        task = None
                        action = "noop"
                    else:
                        task_hex, entry = task
                        entry.node_id_hex = node_id
                        if nm_addr is not None:
                            entry.lease_node = nm_addr
                        self._ltab.incr_inflight(ks, lease_id, task_hex)
                        action = "push"
            if action == "return_untracked":
                # lease not tracked (already dropped): return via the
                # last task's lease_node so a remote NM gets it back
                self._return_lease(lease_id, fallback_entry)
                return
            if action == "return_drained":
                self._return_lease(lease_id, None, nm_address=nm_addr)
                return
            if action == "kick":
                threading.Thread(target=self._kick_key, args=(key,),
                                 daemon=True,
                                 name="pipeline-skip-kick").start()
                return
            if action != "push":
                return
            task_hex, entry = task
            self.task_events.record(task_hex, state="SCHEDULED",
                                    node_id=node_id)
            try:
                # one-way (reference PushTask is async): a push buffered
                # into a dying worker is failed by the NM's worker-death
                # report (the task enters _lease_running under the same
                # lock that verified the lease is live, so a report
                # arriving any time after sees it); send failures fail
                # over right here. Same-node workers take the shm ring
                # (zero syscalls while hot) with the socket as spill.
                if not self._shm_send(tuple(worker_address), node_id,
                                      "w_push_task",
                                      dict(spec=entry.spec,
                                           lease_id=lease_id)):
                    self._pool.get(tuple(worker_address)).send_oneway(
                        "w_push_task", spec=entry.spec, lease_id=lease_id)
            except Exception as e:  # noqa: BLE001
                with self._lock:
                    self._ltab.drop_lease(ks, lease_id)
                    self._ltab.drop_running_task(lease_id, task_hex)
                self._return_lease(lease_id, entry)
                self._fail_task(task_hex, "WORKER_DIED",
                                f"push to leased worker failed: {e}",
                                retry=True)
                return

    def _return_lease(self, lease_id: str, entry: Optional[_TaskEntry],
                      nm_address: Optional[Tuple[str, int]] = None,
                      reuse: bool = True) -> None:
        if nm_address is not None:
            nm_addr = tuple(nm_address)
        elif entry is not None and entry.lease_node:
            nm_addr = entry.lease_node
        else:
            nm_addr = self.nm_address
        # a LOST return strands the lease at the NM: the worker stays
        # "leased" and its resources held until process death — so
        # transient send failures retry with backoff (nm_return_worker
        # releases a lease id at most once, duplicates are no-ops).
        # One-way (not .call): this runs inside NM-driven handler
        # threads, where a blocking call back to the NM can three-way
        # deadlock on the shared per-address client locks.
        for delay_s in (0.0, 0.1, 0.4):
            if delay_s:
                time.sleep(delay_s)
            try:
                self._pool.get(nm_addr).send_oneway(
                    "nm_return_worker", lease_id=lease_id, reuse=reuse)
                return
            except Exception:  # noqa: BLE001 - retried; an NM that
                continue       # stays gone took its leases with it

    def _on_task_done(self, task_id: TaskID, results: List[Tuple],
                      lease_id: Optional[str] = None,
                      dynamic_children: Optional[List[Tuple]] = None,
                      worker_exiting: bool = False,
                      nested_refs: Optional[List[Tuple]] = None) -> None:
        h = task_id.hex()
        with self._lock:
            entry = self.tasks.get(h)
            duplicate = entry is None or entry.done
            retrying = False
            if (not duplicate and results and not dynamic_children
                    and entry.retries_left > 0
                    and entry.spec.task_type == TaskType.NORMAL_TASK
                    and getattr(entry.spec, "retry_exceptions", False)
                    and all(r and r[0] == ERROR for r in results)):
                # application-error retry (reference
                # TaskManager::RetryTaskIfPossible with retry_exceptions,
                # task_manager.cc:869): only RayTaskError (user code
                # raised) retries — cancellation/system errors don't.
                try:
                    err0 = pickle.loads(results[0][1])
                except Exception:  # noqa: BLE001
                    err0 = None
                if isinstance(err0, exc.RayTaskError):
                    entry.retries_left -= 1
                    retrying = True
            if not duplicate and not retrying:
                entry.done = True
                # submit-side backpressure accounting (max_pending_calls)
                self._decr_actor_pending_locked(entry)
                # dynamic-return children become owned objects of ours,
                # registered before the generator handle resolves so a
                # get() of a child ref never races its registration.
                # FREED children stay freed: a consumer that already
                # dropped its ref must not have the batch re-report
                # resurrect the location (the RefState machine rejects
                # the FREED->ready edge).
                for oid, loc in (dynamic_children or []):
                    if self.objects.get(oid.hex(),
                                        (PENDING,))[0] != FREED:
                        self._own.set_location(oid.hex(), tuple(loc),
                                               event="dynamic_child")
                    ev = self.object_events.pop(oid.hex(), None)
                    if ev is not None:  # recovery getters waiting
                        ev.set()
        if retrying:
            if lease_id is not None:
                self._settle_lease_slot(entry, lease_id, worker_exiting)
            logger.warning(
                "retrying task %s after application error, %d retries "
                "left", entry.spec.function_name, entry.retries_left)
            threading.Thread(target=self._enqueue_for_lease,
                             args=(entry.spec.task_id.hex(), entry),
                             daemon=True).start()
            return
        if duplicate:
            # Late/duplicate completion (e.g. after cancel or retry): the
            # first writer won; settle the lease slot that rode in.
            if lease_id is not None:
                self._settle_lease_slot(entry, lease_id, worker_exiting)
            return
        if nested_refs and entry.return_ids:
            # ObjectRefs embedded in the result: register borrows with
            # their owners NOW (the producing worker's pins are about to
            # lapse); released when the ENCLOSING return object frees
            # (reference ReferenceCounter contained-ref accounting).
            for oid, per in zip(entry.return_ids, nested_refs):
                if per:
                    self._register_nested_borrows(oid.hex(), per)
        for oid, loc in zip(entry.return_ids, results):
            with self._lock:
                # keep location unless already freed
                if self.objects.get(oid.hex(), (PENDING,))[0] != FREED:
                    self._own.set_location(oid.hex(), tuple(loc),
                                           event="resolve")
                # pop, don't get: events are waiter-created and resolve
                # retires them — keeps the dict sized by objects being
                # actively waited on, not by every ref ever created
                ev = self.object_events.pop(oid.hex(), None)
                if ev is not None:
                    ev.set()
        self._free_refless_returns(entry)
        self._unpin_args(entry.spec.arg_object_refs)
        self.task_events.record(h, state="FINISHED", ts_finished=_ev_now())
        _count_task_outcome("finished")
        entry.wake_dynamic()  # wake streaming iterators: task over
        self._fire_done_callbacks([oid.hex() for oid in entry.return_ids])
        if lease_id is not None:
            self._settle_lease_slot(entry, lease_id, worker_exiting)

    def _on_task_done_batch(self, reports: List[Dict[str, Any]]) -> None:
        """Batched completion reports off a worker's report drainer:
        many finished tasks, one RPC. Each element is exactly a
        cw_task_done kwargs dict and runs the full singleton handler —
        entry.done dedup plus the lease machine's settle no-op make a
        replayed batch (idempotent resend after a send failure)
        harmless."""
        for r in reports:
            self._on_task_done(**r)

    def _free_refless_returns(self, entry: _TaskEntry) -> None:
        """Free-on-resolve: a result whose every ref died while the
        task was PENDING has no reachable holder left — the free check
        at last-ref drop saw PENDING and deferred "until completion",
        and completion (success OR failure) must re-run it. Without
        this the result — and, for successes, the eager nested borrows
        pinning objects at OTHER owners — leaks forever (found by the
        ownership fuzzer's drop schedules). Generator results free only
        when the handle is refless too: unreferenced children are
        otherwise still reachable through a live generator's handle."""
        with self._lock:
            handle_hex = entry.return_ids[0].hex() \
                if entry.return_ids else None
            generator = bool(entry.spec.dynamic_returns
                             or entry.dynamic_arrived)
            handle_refless = handle_hex is not None and \
                self.local_refs.get(handle_hex, 0) == 0 and \
                self.arg_pins.get(handle_hex, 0) == 0
            if not generator or handle_refless:
                victims = [oid.hex() for oid in entry.return_ids]
                victims += [c.hex()
                            for c in entry.dynamic_arrived.values()]
                for h2 in victims:
                    if self.local_refs.get(h2, 0) == 0 and \
                            self.arg_pins.get(h2, 0) == 0:
                        self._maybe_free_locked(h2)

    def _settle_lease_slot(self, entry: Optional[_TaskEntry],
                           lease_id: str, worker_exiting: bool) -> None:
        """One pushed task finished (or was superseded): free its
        pipeline slot, then either retire the lease (worker_exiting:
        max_calls recycling — the NM must not re-lease a process that's
        about to exit) or keep the leased worker primed / return it
        (reference direct_task_transport.cc:125 lease reuse)."""
        key = entry.sched_key if entry is not None else None
        task_hex = entry.spec.task_id.hex() if entry is not None else None
        with self._lock:
            self._ltab.settle_inflight(self._ltab.get(key), lease_id,
                                       task_hex)
        if worker_exiting:
            self._drop_lease(key, lease_id)
            self._return_lease(lease_id, entry, reuse=False)
            return
        if key is None:
            self._return_lease(lease_id, entry)
            return
        self._push_on_lease(key, lease_id, fallback_entry=entry)

    def _drop_lease(self, key: Optional[bytes], lease_id: str) -> None:
        """Forget a held lease (it is being returned/retired)."""
        with self._lock:
            ks = self._ltab.get(key)
            if ks is not None:
                self._ltab.drop_lease(ks, lease_id)

    def _on_dynamic_child(self, task_id: TaskID, child: ObjectID,
                          loc: Tuple) -> None:
        """Streaming generator child: register the object the moment the
        executor stores it so iterators see it before the task ends."""
        with self._lock:
            entry = self.tasks.get(task_id.hex())
            if entry is None:
                return
            if self.objects.get(child.hex(), (PENDING,))[0] != FREED:
                self._own.set_location(child.hex(), tuple(loc),
                                       event="dynamic_child")
            entry.dynamic_arrived[child.return_index()] = child
            entry.wake_dynamic()
            ev = self.object_events.pop(child.hex(), None)
        if ev is not None:
            ev.set()
        self._fire_done_callbacks([child.hex()])

    def _on_task_failed(self, task_id: TaskID, error_type: str,
                        message: str,
                        lease_id: Optional[str] = None) -> None:
        fail_hexes = [task_id.hex()]
        if lease_id is not None:
            # With lease reuse + pipelining, the tasks in flight on the
            # lease at failure time (running + queued in the dead
            # worker) may differ from the task the lease was granted
            # for — the lease→running map has the truth.
            with self._lock:
                running = self._ltab.pop_running(lease_id)
            if running:
                # SUBMISSION order, not hex order: the retries re-enter
                # the key queue in this order, and submission order is
                # topological for data dependencies — a dependent
                # re-queued ahead of its dependency can end up pipelined
                # behind it on one single-threaded worker and deadlock
                fail_hexes = sorted(
                    running,
                    key=lambda th: (self.tasks[th].submit_seq
                                    if th in self.tasks else 0))
            entry = self.tasks.get(fail_hexes[0])
            if entry is not None and entry.sched_key is not None:
                self._drop_lease(entry.sched_key, lease_id)
        for tid_hex in fail_hexes:
            self._fail_task(tid_hex, error_type, message, retry=True)

    def _fail_task(self, task_hex: str, error_type: str, message: str,
                   retry: bool) -> None:
        with self._lock:
            entry = self.tasks.get(task_hex)
            if entry is None or entry.done:
                return
            will_retry = retry and entry.retries_left > 0
            if will_retry:
                entry.retries_left -= 1
            else:
                entry.done = True
                self._decr_actor_pending_locked(entry)
        if will_retry:
            logger.warning("retrying task %s (%s: %s), %d retries left",
                           entry.spec.function_name, error_type, message,
                           entry.retries_left)
            threading.Thread(target=self._enqueue_for_lease,
                             args=(entry.spec.task_id.hex(), entry),
                             daemon=True).start()
            return
        if error_type == "WORKER_DIED":
            err: Exception = exc.WorkerCrashedError(message)
        elif error_type == "CANCELLED":
            err = exc.TaskCancelledError(message)
        else:
            err = exc.RaySystemError(f"{error_type}: {message}")
        blob = pickle.dumps(err)
        for oid in entry.return_ids:
            with self._lock:
                if self.objects.get(oid.hex(), (PENDING,))[0] != FREED:
                    self._own.set_location(oid.hex(), (ERROR, blob),
                                           event="fail")
                ev = self.object_events.pop(oid.hex(), None)
                if ev is not None:
                    ev.set()
        # same refless-free sweep as the success path: a failed
        # fire-and-forget task must not leak its (ERROR, blob) entry
        self._free_refless_returns(entry)
        self._unpin_args(entry.spec.arg_object_refs)
        self.task_events.record(task_hex, state="FAILED",
                                ts_finished=_ev_now(),
                                error=f"{error_type}: {message}"[:500])
        _count_task_outcome("failed")
        entry.wake_dynamic()
        self._fire_done_callbacks([oid.hex() for oid in entry.return_ids])

    # ------------------------------------------------------------------
    # Actor submission (reference direct_actor_task_submitter.h)
    # ------------------------------------------------------------------

    def create_actor(self, spec: TaskSpec, name: str = "",
                     namespace: str = "") -> None:
        spec.owner_node_id = self.node_id_hex
        self._pin_args(spec.arg_object_refs)
        with self._lock:
            self.actors[spec.actor_id.hex()] = _ActorState(
                actor_id=spec.actor_id)
        self._attach_trace(spec)
        spec.locality_hints, spec.arg_locations = \
            self._locality_info(spec.arg_object_refs)
        self._gcs.call("register_actor", spec=spec, name=name,
                       namespace=namespace)
        self.task_events.record(
            spec.task_id.hex(), state="SUBMITTED", ts_submitted=_ev_now(),
            name=f"{spec.function_name}.__init__", type="ACTOR_CREATION_TASK",
            job_id=spec.job_id.hex(), trace_id=spec.trace_id,
            parent_task_id=spec.parent_task_id)

    def attach_actor(self, actor_id: ActorID) -> None:
        """Track an actor we only hold a handle to (named/deserialized)."""
        with self._lock:
            if actor_id.hex() not in self.actors:
                self.actors[actor_id.hex()] = _ActorState(actor_id=actor_id)

    def actor_is_dead(self, actor_id: ActorID) -> bool:
        """Owner-side liveness peek (death pubsub keeps it fresh): a
        dict lookup, no RPC. Used by compiled DAGs to notice a cached
        actor died and fall back to the interpreted path."""
        with self._lock:
            st = self.actors.get(actor_id.hex())
            return bool(st is not None and st.dead)

    def actor_pending_calls(self, actor_id: ActorID) -> int:
        """Caller-side count of this actor's submitted-but-unfinished
        calls (reference max_pending_calls backpressure)."""
        with self._lock:
            return self._actor_pending.get(actor_id.hex(), 0)

    def _decr_actor_pending_locked(self, entry: "_TaskEntry") -> None:
        """Call under self._lock when an actor task reaches a terminal
        state — every terminal path must hit this or the caller's
        max_pending_calls budget leaks shut."""
        aid = entry.spec.actor_id
        if aid is not None and \
                entry.spec.task_type == TaskType.ACTOR_TASK:
            cnt = self._actor_pending.get(aid.hex(), 0)
            if cnt > 0:
                self._actor_pending[aid.hex()] = cnt - 1

    def submit_actor_task(self, actor_id: ActorID, method_name: str,
                          function_key: str, args_blob: bytes,
                          arg_refs: List[ObjectID],
                          num_returns: int,
                          concurrency_group: str = "",
                          max_pending_calls: int = -1,
                          dynamic_returns: bool = False
                          ) -> List[ObjectRef]:
        spec = TaskSpec(
            task_id=TaskID.of(self.job_id), job_id=self.job_id,
            task_type=TaskType.ACTOR_TASK, function_key=function_key,
            function_name=method_name, args=self._intern_blob(args_blob),
            arg_object_refs=arg_refs, num_returns=num_returns,
            resources={}, owner_address=self.address,
            owner_worker_id=self.worker_id, actor_id=actor_id,
            actor_method_name=method_name,
            concurrency_group=concurrency_group)
        spec.owner_node_id = self.node_id_hex
        spec.dynamic_returns = dynamic_returns
        # before the spec becomes reachable by other threads: a queued
        # spec can be popped+pickled by an in-flight _resolve_actor the
        # moment the lock below releases
        self._attach_trace(spec)
        return_ids = [ObjectID.for_task_return(spec.task_id, i + 1)
                      for i in range(num_returns)]
        if Config.memory_callsite_capture and return_ids:
            self._note_callsite([oid.hex() for oid in return_ids])
        with self._lock:
            state = self.actors.get(actor_id.hex())
            if state is None:
                state = _ActorState(actor_id=actor_id)
                self.actors[actor_id.hex()] = state
            if state.dead:
                blob = pickle.dumps(
                    exc.ActorDiedError(actor_id.hex(), state.death_cause))
                for oid in return_ids:
                    self._own.set_location(oid.hex(), (ERROR, blob),
                                           event="actor_dead")
                return [ObjectRef(oid, self.address) for oid in return_ids]
            # backpressure bound checked ATOMICALLY with the increment:
            # an unlocked pre-check would let concurrent submitters
            # overshoot the budget together
            pending = self._actor_pending.get(actor_id.hex(), 0)
            if 0 <= max_pending_calls <= pending:
                raise exc.PendingCallsLimitExceeded(
                    f"actor {actor_id.hex()[:12]} already has {pending} "
                    f"pending calls from this caller "
                    f"(max_pending_calls={max_pending_calls})")
            spec.sequence_number = state.seq
            state.seq += 1
            for oid in return_ids:
                self._own.set_location(oid.hex(), (PENDING,),
                                       event="submit")
            self.tasks[spec.task_id.hex()] = _TaskEntry(
                spec=spec, retries_left=0, return_ids=return_ids)
            self._actor_pending[actor_id.hex()] = pending + 1
            addr = state.address
            if addr is None:
                state.queue.append(spec)
                need_resolve = not state.resolving
                state.resolving = True
            else:
                need_resolve = False
        # register the caller's refs BEFORE the push: a fast completion
        # must never observe local_refs == 0 and free a live result
        # (see submit_task)
        refs_out = [ObjectRef(oid, self.address) for oid in return_ids]
        self.task_events.record(
            spec.task_id.hex(), state="SUBMITTED", ts_submitted=_ev_now(),
            name=f"{method_name} [actor {actor_id.hex()[:8]}]",
            type="ACTOR_TASK", job_id=spec.job_id.hex(),
            trace_id=spec.trace_id, parent_task_id=spec.parent_task_id)
        self._pin_args(arg_refs)
        if addr is not None:
            self._push_actor_task(addr, spec)
        elif need_resolve:
            threading.Thread(target=self._resolve_actor,
                             args=(actor_id,), daemon=True).start()
        return refs_out

    def _push_actor_task(self, addr: Optional[Tuple[str, int]],
                         spec: TaskSpec) -> None:
        try:
            if addr is None:
                raise rpc_lib.ConnectionLost("actor address unknown")
            # one-way push (reference PushTask is async with an error
            # callback): send failures raise and re-resolve below; a
            # push lost in a dying actor's buffer is failed by the
            # death/incarnation bookkeeping (state.pushed) instead.
            # Same-node actors take the shm ring.
            with self._lock:
                st = self.actors.get(spec.actor_id.hex())
                peer_node = st.node_id_hex if st is not None else None
            if not self._shm_send(tuple(addr), peer_node, "w_push_task",
                                  dict(spec=spec)):
                self._pool.get(addr).send_oneway("w_push_task", spec=spec)
            with self._lock:
                state = self.actors[spec.actor_id.hex()]
                state.pushed[spec.task_id.hex()] = state.incarnation
        except Exception:  # noqa: BLE001
            # actor possibly restarting: invalidate and re-resolve
            if addr is not None:
                self._pool.invalidate(addr)
            with self._lock:
                state = self.actors[spec.actor_id.hex()]
                if state.address == addr:
                    state.address = None
                state.queue.append(spec)
                need = not state.resolving
                state.resolving = True
            if need:
                threading.Thread(target=self._resolve_actor,
                                 args=(spec.actor_id,), daemon=True).start()

    def _resolve_actor(self, actor_id: ActorID) -> None:
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline and not self._shutdown:
            try:
                info = self._gcs.call("get_actor_info",
                                      actor_id_hex=actor_id.hex())
            except Exception:  # noqa: BLE001
                time.sleep(0.2)
                continue
            if info is None:
                time.sleep(0.1)
                continue
            if info.state == "ALIVE" and info.address is not None:
                lost: List[TaskSpec] = []
                with self._lock:
                    state = self.actors[actor_id.hex()]
                    new_addr = tuple(info.address)
                    restarted = (state.last_address is not None
                                 and state.last_address != new_addr)
                    state.address = new_addr
                    state.last_address = new_addr
                    state.node_id_hex = info.node_id.hex() \
                        if info.node_id is not None else None
                    state.resolving = False
                    q, state.queue = state.queue, []
                    q.sort(key=lambda s: s.sequence_number)
                    if restarted:
                        state.incarnation += 1
                        # Tasks pushed to the dead incarnation are lost:
                        # fail them (at-most-once actor task semantics).
                        for thex, inc in list(state.pushed.items()):
                            if inc < state.incarnation:
                                entry = self.tasks.get(thex)
                                state.pushed.pop(thex, None)
                                if entry is not None and not entry.done:
                                    lost.append(entry.spec)
                        # Renumber the never-pushed queue from seq 0 for the
                        # fresh incarnation's reordering buffer.
                        for i, spec in enumerate(q):
                            spec.sequence_number = i
                        state.seq = len(q)
                blob = pickle.dumps(exc.ActorUnavailableError(
                    actor_id.hex(), "actor restarted; in-flight task lost"))
                for spec in lost:
                    self._on_task_done(spec.task_id,
                                       [(ERROR, blob)] * spec.num_returns)
                for spec in q:
                    # push to the freshly-resolved address, not the mutable
                    # state.address (a concurrent push failure may null it)
                    self._push_actor_task(new_addr, spec)
                return
            if info.state == "DEAD":
                self._mark_actor_dead(actor_id, info.death_cause)
                return
            time.sleep(0.1)
        self._mark_actor_dead(actor_id, "timed out resolving actor address")

    def _mark_actor_dead(self, actor_id: ActorID, cause: str) -> None:
        with self._lock:
            state = self.actors.get(actor_id.hex())
            if state is None:
                return
            state.dead = True
            state.death_cause = cause
            state.resolving = False
            q, state.queue = state.queue, []
        err = exc.ActorDiedError(actor_id.hex(), cause)
        blob = pickle.dumps(err)
        for spec in q:
            self._on_task_done(spec.task_id,
                               [(ERROR, blob)] * spec.num_returns)
        # fail any in-flight (pushed but unacked) tasks for this actor
        with self._lock:
            inflight = [e for e in self.tasks.values()
                        if e.spec.actor_id == actor_id and not e.done]
        for e in inflight:
            self._on_task_done(e.spec.task_id,
                               [(ERROR, blob)] * e.spec.num_returns)

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True) -> None:
        self._gcs.call("kill_actor", actor_id_hex=actor_id.hex(),
                       no_restart=no_restart)

    def cancel_task(self, ref: ObjectRef) -> None:
        with self._lock:
            entry = self.tasks.get(ref.task_id().hex())
        if entry is None or entry.done:
            return
        self._fail_task(ref.task_id().hex(), "CANCELLED", "ray.cancel",
                        retry=False)

    # ------------------------------------------------------------------
    # Owner-side handlers
    # ------------------------------------------------------------------

    def _on_get_object(self, oid_hex: str) -> Tuple:
        with self._lock:
            loc = self.objects.get(oid_hex)
        if loc is None:
            return ("unknown",)
        if loc[0] == PENDING:
            return (PENDING,)
        return loc

    def _on_wait_object(self, oid_hex: str, timeout: float = 30.0) -> Tuple:
        """Long-poll variant of cw_get_object (reference: the pubsub
        long-poll object-location channel, core_worker.proto:441): parks
        until the object resolves instead of making borrowers busy-poll."""
        deadline = time.monotonic() + min(timeout, 60.0)
        while True:
            with self._lock:
                loc = self.objects.get(oid_hex)
                if loc is not None and loc[0] == PENDING:
                    ev = self.object_events.setdefault(
                        oid_hex, threading.Event())
                else:
                    return loc if loc is not None else ("unknown",)
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return (PENDING,)
            ev.wait(timeout=min(remaining, 1.0))

    def _on_add_ref(self, oid_hex: str,
                    borrower: Optional[Tuple[str, int]] = None) -> None:
        with self._lock:
            if borrower is not None:
                # borrower registration and its backing arg pin move
                # together inside the table (borrower_pins <= arg_pins
                # holds by construction)
                self._own.add_borrower(oid_hex, tuple(borrower))
            else:
                self._own.pin_arg(oid_hex, event="pin_arg")

    def _on_remove_ref(self, oid_hex: str,
                       borrower: Optional[Tuple[str, int]] = None) -> None:
        with self._lock:
            if borrower is not None:
                n = self._own.release_borrower(oid_hex, tuple(borrower))
                if n is None:
                    # unmatched (this borrower holds no pin here — e.g.
                    # the dead-borrower sweep already released it, or a
                    # duplicate release): decrementing arg_pins anyway
                    # would free a pin some OTHER claimant holds. The
                    # table recorded the anomaly; drop the release.
                    return
            else:
                n = self._own.unpin_arg(oid_hex, strict=False,
                                        event="unpin_arg")
            if n <= 0 and self.local_refs.get(oid_hex, 0) == 0:
                self._maybe_free_locked(oid_hex)

    def _on_claims(self, oid_hexes: List[str]) -> Dict[str, bool]:
        with self._lock:
            return self._own.claims(list(oid_hexes))

    def _sweep_dead_borrowers(self) -> None:
        """Reconcile borrower pins against reality: pins of DEAD
        borrowers are dropped outright; LIVE borrowers are asked which
        pinned objects they still claim (cw_claims) and disclaimed pins
        are released — the safety net for a release whose sends were
        all lost (without it a transient outage leaks the pin at a live
        owner forever). Safe against in-flight releases: a late
        cw_remove_ref for a reconciled pin is dropped as unmatched."""
        with self._lock:
            by_addr: Dict[Tuple[str, int], List[str]] = {}
            for h, by in self.borrower_pins.items():
                for a in by:
                    by_addr.setdefault(a, []).append(h)
        for addr, oids in by_addr.items():
            claims: Optional[Dict[str, bool]] = None
            dead = False
            try:
                claims = self._pool.get(addr).call("cw_claims",
                                                   oid_hexes=oids)
            except Exception:  # noqa: BLE001
                self._pool.invalidate(addr)
                try:
                    self._pool.get(addr).call("cw_ping")
                except Exception:  # noqa: BLE001
                    dead = True
            if dead:
                logger.info("borrower %s died; releasing its pins", addr)
                with self._lock:
                    for oid_hex, n in self._own.sweep_borrower(addr):
                        if n <= 0 and \
                                self.local_refs.get(oid_hex, 0) == 0:
                            self._maybe_free_locked(oid_hex)
                continue
            if not isinstance(claims, dict):
                continue  # borrower alive but claims unavailable
            disclaimed = [h for h in oids if claims.get(h) is False]
            if not disclaimed:
                continue
            logger.info("borrower %s disclaims %d pinned object(s); "
                        "reconciling lost release(s)", addr,
                        len(disclaimed))
            with self._lock:
                for oid_hex, n in self._own.sweep_borrower(
                        addr, only=disclaimed,
                        event="borrower_disclaimed"):
                    if n <= 0 and self.local_refs.get(oid_hex, 0) == 0:
                        self._maybe_free_locked(oid_hex)

    def _on_node_event(self, message: Any) -> None:
        """GCS "node" channel: fail (and retry) in-flight normal tasks
        whose lease lives on a node that just died — both tasks granted to
        workers there (node_id match) and tasks still queued at its node
        manager (lease_node match). Actor tasks resolve through the GCS
        actor-restart path instead."""
        try:
            event, info = message
        except Exception:  # noqa: BLE001
            return
        if event != "DEAD":
            return
        dead_hex = info.node_id.hex()
        dead_nm = tuple(info.address) if info.address else None
        kick_keys = set()
        with self._lock:
            lost = [e for e in self.tasks.values()
                    if not e.done and e.spec.actor_id is None
                    and (e.node_id_hex == dead_hex
                         or (e.lease_node is not None
                             and e.lease_node == dead_nm))]
            # Lease requests "queued" at the dead NM never get their
            # grants: reset the slot count so the key's queue can
            # re-request at a live NM instead of stalling forever
            # (over-counting self-heals — surplus grants with an empty
            # queue hand their lease straight back).
            for e in lost:
                ks = self._ltab.get(e.sched_key)
                if ks is not None and e.lease_node == dead_nm:
                    self._ltab.reset_slots(ks, event="node_death_reset")
                    # surgical: only the dead NM's parked entry dies —
                    # counts parked at live NMs (and their pending
                    # grants) keep balancing each other
                    self._ltab.drop_parked(ks, dead_nm)
                    if ks.queue:
                        kick_keys.add(e.sched_key)
            # Sweep EVERY key's parked_at for the dead NM, not only the
            # lost entries' keys: a request can sit parked there with no
            # task entry pointing at it (the task completed via another
            # NM's grant, or a later attempt overwrote lease_node).
            # Those requests never grant — without releasing their
            # slots the key stalls holding in_flight == parked, which
            # the watchdog's lease_slot_balance probe reads as balanced.
            # A negative bucket (grant outraced its "queued" reply) is
            # dropped without a release: that slot was already returned
            # by the grant, and the reply that would rebalance it died
            # with the NM.
            if dead_nm is not None:
                for key, ks in self._sched_keys.items():
                    n = self._ltab.drop_parked(ks, dead_nm)
                    if n > 0:
                        self._ltab.release_slots(
                            ks, n, event="dead_nm_slot_release")
                        if ks.queue:
                            kick_keys.add(key)
        for e in lost:
            self._fail_task(e.spec.task_id.hex(), "WORKER_DIED",
                            f"node {dead_hex[:12]} died", retry=True)
        for key in kick_keys:
            threading.Thread(target=self._kick_key, args=(key,),
                             daemon=True, name="node-death-kick").start()

    def _on_actor_event(self, message: Any) -> None:
        try:
            event, info = message
        except Exception:  # noqa: BLE001
            return
        with self._lock:
            state = self.actors.get(info.actor_id.hex())
        if state is None or state.dead:
            return  # not an actor we hold a handle to
        if event == "DEAD":
            self._mark_actor_dead(info.actor_id, info.death_cause)
        elif event == "RESTARTING":
            with self._lock:
                state.address = None
                need = not state.resolving
                state.resolving = True
            if need:
                threading.Thread(target=self._resolve_actor,
                                 args=(info.actor_id,), daemon=True,
                                 name="actor-rebind").start()

    def _on_pubsub_push(self, channel: str, token: str, message: Any) -> None:
        cb = self._subscriptions.get((channel, token))
        if cb is not None:
            try:
                cb(message)
            except Exception:  # noqa: BLE001
                logger.exception("pubsub callback failed")

    def subscribe(self, channel: str, callback: Any) -> str:
        import uuid
        token = uuid.uuid4().hex
        self._subscriptions[(channel, token)] = callback
        self._gcs.call("subscribe", channel=channel, address=self.address,
                       token=token)
        return token

    def unsubscribe(self, channel: str, token: str) -> None:
        """Drop a subscription end to end: local callback AND the GCS's
        (address, token) entry — a short-lived subscriber (follow-mode
        log streaming) must not keep the publish fan-out paying for it
        forever."""
        self._subscriptions.pop((channel, token), None)
        try:
            self._gcs.call("unsubscribe", channel=channel,
                           address=self.address, token=token)
        except Exception:  # noqa: BLE001 - GCS gone; entry dies with it
            pass

    def _on_can_exit(self) -> bool:
        """May this worker exit without stranding objects? False while
        anyone holds a pin on objects we own (a driver's ref to a value
        this worker put() makes us the owner — killing us would lose it;
        reference: the raylet's cooperative idle Exit RPC that the core
        worker declines while it owns in-scope objects)."""
        with self._lock:
            return not self.arg_pins and not self.borrower_pins

    def _on_kill_self(self) -> str:
        threading.Timer(0.05, lambda: os._exit(0)).start()
        return "dying"

    # ------------------------------------------------------------------

    def shutdown(self) -> None:
        self._shutdown = True
        self._lease_req_q.put(None)
        _metrics_plane.unregister_sampler("core_worker")
        _metrics_plane.unregister_snapshot_extra(
            _memory_plane.PROC_DIGEST_KEY)
        _profiler.sampler().stop()
        # Drain queued borrow releases before tearing the process down so a
        # clean exit doesn't strand pins at owners.
        try:
            self._drain_local_frees()
        except Exception:  # noqa: BLE001 - store may already be gone
            pass
        while True:
            try:
                item = self._borrow_release_queue.get_nowait()
            except queue.Empty:
                break
            if item is None or len(item) == 1:
                continue
            try:
                if item[0] == "store_delete":
                    self._pool.get(item[1]).send_oneway(
                        "store_delete", object_ids=[item[2]])
                else:
                    owner_addr, oid_hex = item[:2]
                    self._pool.get(owner_addr).call(
                        "cw_remove_ref", oid_hex=oid_hex,
                        borrower=self.address)
            # best-effort release during shutdown: the owner may already
            # be gone, and there is nothing left to free on our side
            except Exception:  # noqa: BLE001  graftlint: disable=RT008
                pass
        self._borrow_release_queue.put(None)
        # release reader leases on pulled replicas so the local store can
        # evict them (a SIGKILLed process leaks its leases until the
        # store itself is torn down — graceful exits should not)
        with self._lock:
            leases = self._own.drain_replica_leases()
        for h, n in leases.items():
            try:
                self.store.unpin(h, count=n)
            # best-effort during teardown: the store may already be gone
            except Exception:  # noqa: BLE001  graftlint: disable=RT008
                pass
        try:
            self.task_events.stop()
        except Exception:  # noqa: BLE001 - teardown; event sink may be gone
            pass
        if self._shm_rx is not None:
            self._shm_rx.stop()
        with self._shm_lock:
            senders, self._shm_senders = dict(self._shm_senders), {}
        for s in senders.values():
            try:
                s.close()
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass
        self.server.stop()
        self.store.close()
        self._pool.close_all()
        self._gcs.close()
        self._nm.close()


class _Executor:
    """Task execution engine inside worker processes.

    reference parity: CoreWorker::ExecuteTask (core_worker.cc:2598) +
    scheduling queues (normal_scheduling_queue.h:32, actor_scheduling_queue
    .h:40 for per-caller seq ordering) + ConcurrencyGroupManager thread pools
    (thread_pool.h:36).
    """

    def __init__(self, cw: CoreWorker):
        self.cw = cw
        self.actor_instance: Any = None
        self.actor_id: Optional[ActorID] = None
        self._queue: "queue.Queue[Optional[TaskSpec]]" = queue.Queue()
        self._lock = TracedLock("executor")
        # per-owner seq reordering
        self._next_seq: Dict[str, int] = {}
        self._buffer: Dict[str, Dict[int, TaskSpec]] = {}
        self._cancelled: set = set()
        # task_ids already queued via push_task: makes a retried
        # w_push_task (rpc reply lost after a successful send) a no-op
        # instead of a double execution. Bounded — a retry lands within
        # seconds, not after thousands of intervening pushes.
        self._pushed_ids: "collections.OrderedDict[str, None]" = \
            collections.OrderedDict()
        self._threads: List[threading.Thread] = []
        # named concurrency groups: group -> dedicated task queue
        self._group_queues: Dict[str, "queue.Queue"] = {}
        # per-group running-execution counts; queued + running is the
        # server-side "ongoing" depth (reference: replica queue length
        # probed by serve's PowerOfTwoChoicesReplicaScheduler,
        # router.py:893)
        self._running: Dict[str, int] = {}
        # per-function execution counts for max_calls worker recycling
        self._calls_by_fn: Dict[str, int] = {}
        # which concurrency group the current thread serves (threads are
        # group-pinned for life) + per-group thread-pool widths, for
        # spare-capacity accounting
        self._group_tls = threading.local()
        self._default_threads = 0
        self._group_widths: Dict[str, int] = {}
        # done-report drainer: the common-path cw_task_done one-ways
        # queue here and ship in owner-grouped batches (one frame — or
        # one shm ring slot — for N completions) instead of one socket
        # write per task. idle flags the drainer as between batches so
        # the pre-exit flush can tell "queue empty" from "report still
        # in the drainer's hands".
        self._report_q: "queue.Queue" = queue.Queue()
        self._report_idle = threading.Event()
        self._report_idle.set()
        threading.Thread(target=self._report_drain_loop, daemon=True,
                         name="done-report-drain").start()
        self._spawn_exec_threads(1)

    def has_spare_capacity(self) -> bool:
        """True while at least one executor thread of the CALLING
        thread's concurrency group is idle — then this actor can still
        field the calls a cycle peer would send here, so a blocking get
        does not make it a hard node in the waits-for graph. Counted per
        group: an idle thread of a different group can't serve this
        group's queue."""
        group = getattr(self._group_tls, "group", "")
        with self._lock:
            running = self._running.get(group, 0)
            width = self._group_widths.get(group, 1) if group \
                else self._default_threads
        return running < width

    def queue_depth(self, group: str = "") -> int:
        """Queued + currently-executing tasks for one concurrency group
        (default group when unnamed). Readable from a DIFFERENT group's
        thread even while this group is saturated."""
        q = self._group_queues.get(group, self._queue) if group \
            else self._queue
        with self._lock:
            running = self._running.get(group, 0)
        return q.qsize() + running

    def total_queue_depth(self) -> int:
        """Queued + executing across the default AND every named
        concurrency group — the saturation signal the metrics plane
        exports (a replica saturated on one named group must not read
        as idle)."""
        with self._lock:
            groups = list(self._group_queues)
        return self.queue_depth("") + sum(
            self.queue_depth(g) for g in groups)

    def _spawn_exec_threads(self, n: int) -> None:
        while len(self._threads) < n:
            t = threading.Thread(target=self._exec_loop, daemon=True,
                                 name=f"exec-{len(self._threads)}")
            t.start()
            self._threads.append(t)
            self._default_threads += 1

    def _ensure_aio_loop(self):
        """Lazily start the actor's asyncio loop thread."""
        import asyncio
        loop = getattr(self, "_aio_loop", None)
        if loop is not None:
            return loop
        with self._lock:
            loop = getattr(self, "_aio_loop", None)
            if loop is None:
                loop = asyncio.new_event_loop()
                t = threading.Thread(target=loop.run_forever,
                                     daemon=True, name="actor-aio-loop")
                t.start()
                self._aio_loop = loop
        return self._aio_loop

    def push_task(self, spec: TaskSpec, lease_id: Optional[str] = None) -> str:
        if spec.task_type == TaskType.ACTOR_TASK:
            owner = spec.owner_worker_id.hex()
            with self._lock:
                buf = self._buffer.setdefault(owner, {})
                buf[spec.sequence_number] = spec
                nxt = self._next_seq.setdefault(owner, 0)
                while nxt in buf:
                    s = buf.pop(nxt)
                    s._lease_id = None  # type: ignore[attr-defined]
                    # route by concurrency group: releases stay in
                    # per-owner order, but a saturated group never
                    # blocks calls destined for other groups
                    # (reference concurrency_group_manager.h)
                    self._group_queues.get(
                        getattr(s, "concurrency_group", "") or "",
                        self._queue).put(s)
                    nxt += 1
                self._next_seq[owner] = nxt
        else:
            if spec.task_type == TaskType.ACTOR_CREATION_TASK:
                # duplicate-safe so the NM's creation push can sit in the
                # rpc retry set: a reply lost AFTER a successful send is
                # re-sent, and the second copy must queue nothing. Safe
                # for creation ONLY — an actor restart lands on a fresh
                # worker process, so the same creation task_id never
                # legitimately arrives here twice. (NORMAL_TASK retries
                # DO reuse the task_id on a possibly-reused worker, and
                # ACTOR_TASK pushes are already guarded by the per-owner
                # sequence cursor above.)
                tid = spec.task_id.hex()
                with self._lock:
                    if tid in self._pushed_ids:
                        return "ok"
                    self._pushed_ids[tid] = None
                    while len(self._pushed_ids) > 64:
                        self._pushed_ids.popitem(last=False)
            spec._lease_id = lease_id  # type: ignore[attr-defined]
            if spec.task_type == TaskType.ACTOR_CREATION_TASK:
                self._spawn_exec_threads(max(1, spec.max_concurrency))
                for group, width in (spec.concurrency_groups
                                     or {}).items():
                    self._ensure_group(group, width)
            self._queue.put(spec)
        return "ok"

    def _ensure_group(self, group: str, width: int) -> None:
        """Dedicated queue + thread pool per named concurrency group."""
        with self._lock:
            if group in self._group_queues:
                return
            q: "queue.Queue" = queue.Queue()
            self._group_queues[group] = q
            self._group_widths[group] = max(1, width)
        for i in range(max(1, width)):
            t = threading.Thread(target=self._exec_loop, args=(q, group),
                                 daemon=True,
                                 name=f"exec-{group}-{i}")
            t.start()
            self._threads.append(t)

    def cancel_task(self, task_id_hex: str) -> None:
        self._cancelled.add(task_id_hex)

    def _exec_loop(self, q: Optional["queue.Queue"] = None,
                   group: str = "") -> None:
        q = q if q is not None else self._queue
        self._group_tls.group = group
        while True:
            spec = q.get()
            if spec is None:
                return
            with self._lock:
                self._running[group] = self._running.get(group, 0) + 1
            try:
                self._execute(spec)
            except Exception:  # noqa: BLE001
                logger.exception("executor crashed on %s", spec.function_name)
            finally:
                with self._lock:
                    self._running[group] = self._running.get(group, 1) - 1

    def _resolve_args(self, spec: TaskSpec) -> Tuple[tuple, dict]:
        args, kwargs = ser.unpack(memoryview(spec.args))
        # Top-level ObjectRef args are resolved to values (reference
        # semantics: only top-level args are awaited+inlined).
        def resolve(x: Any) -> Any:
            if isinstance(x, ObjectRef):
                return self.cw.get([x], timeout=None)[0]
            return x
        return tuple(resolve(a) for a in args), \
            {k: resolve(v) for k, v in kwargs.items()}

    def _execute(self, spec: TaskSpec) -> None:
        cw = self.cw
        will_exit = False  # max_calls recycling decision (see below)
        if spec.task_id.hex() in self._cancelled:
            self._report_error(spec, exc.TaskCancelledError(spec.function_name))
            return
        # max_calls counts EVERY execution — failing and generator tasks
        # included (the recycle exists for leaky native libs, which leak
        # on errors too). The exit decision itself happens at report time.
        recycle_candidate = False
        if spec.task_type == TaskType.NORMAL_TASK and spec.max_calls > 0:
            with self._lock:
                n = self._calls_by_fn.get(spec.function_key, 0) + 1
                self._calls_by_fn[spec.function_key] = n
            recycle_candidate = n >= spec.max_calls

        def decide_exit() -> bool:
            # _on_can_exit covers pins registered so far; a ref returned
            # BY THIS task isn't borrowed yet when we exit — losing such
            # an owner matches the reference's owner-failure semantics
            # for worker-owned objects.
            return recycle_candidate and cw._on_can_exit()
        cw.set_current_task(spec.task_id)
        cw.set_current_trace(spec.trace_id)
        # manual begin/end (the finally below clears the trace context,
        # so a `with` wrapping it would record a trace-less span)
        _task_span = _spans.start_span("task.run",
                                       name=spec.function_name,
                                       task_id=spec.task_id.hex())
        cw.task_events.record(spec.task_id.hex(), state="RUNNING",
                              ts_running=_ev_now(),
                              worker_id=cw.worker_id.hex(),
                              node_id=cw.node_id_hex)
        # expose the task's placement group for get_current_placement_group
        # (reference: worker.placement_group_id via TaskSpec capture); an
        # actor keeps its creation PG for all subsequent method calls
        if spec.placement_group_id is not None:
            cw.current_placement_group_id = spec.placement_group_id
        try:
            results: List[Tuple] = []
            try:
                if spec.task_type == TaskType.ACTOR_CREATION_TASK:
                    cls = cw.import_function(spec.function_key)
                    args, kwargs = self._resolve_args(spec)
                    # kill_worker chaos rules select by actor class;
                    # tagged before __init__ runs so pushes dispatched
                    # during a slow constructor already match
                    from ray_tpu._private import chaos as chaos_lib
                    chaos_lib.client().set_actor_class(spec.function_name)
                    # profiler samples carry the actor identity
                    # (process-wide: one actor instance per worker)
                    _profiler.set_process_actor(spec.actor_id.hex())
                    self.actor_instance = cls(*args, **kwargs)
                    self.actor_id = spec.actor_id
                    cw._gcs.call("report_actor_alive",
                                 actor_id_hex=spec.actor_id.hex(),
                                 address=cw.address,
                                 node_id_hex=cw.node_id_hex)
                    values: List[Any] = [None] * spec.num_returns
                elif spec.task_type == TaskType.ACTOR_TASK:
                    if self.actor_instance is None:
                        raise exc.RaySystemError("actor not initialized")
                    method = getattr(self.actor_instance,
                                     spec.actor_method_name)
                    args, kwargs = self._resolve_args(spec)
                    out = method(*args, **kwargs)
                    if inspect.iscoroutine(out):
                        # async actor (reference fiber.h / asyncio
                        # actors): coroutines run on one shared event
                        # loop so awaits interleave; up to
                        # max_concurrency calls (exec threads) can be
                        # in flight at once
                        import asyncio
                        out = asyncio.run_coroutine_threadsafe(
                            out, self._ensure_aio_loop()).result()
                    if spec.dynamic_returns:
                        # generator ACTOR method (streaming responses):
                        # same child-object protocol as generator tasks
                        self._emit_dynamic_children(spec, out,
                                                    decide_exit)
                        return
                    values = self._split_returns(out, spec.num_returns)
                elif spec.dynamic_returns:
                    # generator task (reference dynamic returns): store
                    # each yielded value as its own object; the declared
                    # return resolves to the list of child refs. Each
                    # child is ALSO reported as it lands so streaming
                    # consumers iterate before the task finishes.
                    fn = cw.import_function(spec.function_key)
                    args, kwargs = self._resolve_args(spec)
                    self._emit_dynamic_children(
                        spec, fn(*args, **kwargs), decide_exit)
                    return
                else:
                    fn = cw.import_function(spec.function_key)
                    args, kwargs = self._resolve_args(spec)
                    out = fn(*args, **kwargs)
                    values = self._split_returns(out, spec.num_returns)
            except Exception as e:  # noqa: BLE001 - app error
                if spec.task_type == TaskType.ACTOR_CREATION_TASK:
                    try:
                        cw._gcs.call(
                            "report_actor_death",
                            actor_id_hex=spec.actor_id.hex(),
                            reason=f"creation failed: {e}", restart=False)
                    except Exception:  # noqa: BLE001 - NM death report covers it
                        pass
                will_exit = decide_exit()
                self._report_error(
                    spec, exc.RayTaskError(
                        spec.function_name, traceback.format_exc(), e),
                    worker_exiting=will_exit)
                return
            from ray_tpu._private.object_ref import collect_serialized_refs
            all_collected: List[Any] = []
            per_return: List[Optional[List[Tuple]]] = []
            for i, v in enumerate(values):
                oid = ObjectID.for_task_return(spec.task_id, i + 1)
                collected: List[Any] = []
                with collect_serialized_refs(collected):
                    # scatter-write: serialize + store in one copy
                    results.append(cw.store_value(oid.hex(), v))
                # PER RETURN: borrows must key to the return value that
                # actually embeds the ref (freeing return 0 must not
                # release refs held only by return 1)
                per_return.append(
                    [(r.id, tuple(r.owner_address)
                      if r.owner_address else cw.address)
                     for r in collected] or None)
                all_collected.extend(collected)
            nested = None
            pin_handle = None
            if all_collected:
                # ObjectRefs embedded in RESULTS: their descriptors ride
                # the done report so the task's owner registers borrows
                # EAGERLY (released when it frees the enclosing result)
                # — reference ReferenceCounter "contained refs". Transit
                # pins bridge the report: held until the owner ACKS (the
                # report goes blocking when nested refs ride it — see
                # _report_done), since our python refs die right after
                # this frame. Releasing on a wall-clock TTL instead let
                # a chaos-delayed report outlive the pins and observe
                # freed nested objects (ADVICE r5); the TTL survives
                # only as the no-ack fallback below.
                nested = per_return
                pin_handle = cw.pin_refs(all_collected)
            # recycling decision rides the report so the owner retires
            # this worker's lease (reuse=False) atomically — a
            # post-report exit would race new leases onto a dying process
            will_exit = decide_exit()
            ok = self._report_done(spec, results, worker_exiting=will_exit,
                                   nested_refs=nested)
            if pin_handle is not None:
                if ok:
                    cw.release_pins_now(pin_handle)
                else:
                    cw.release_pins_after(pin_handle,
                                          Config.transit_pin_ttl_s)
        finally:
            _spans.finish_span(_task_span)
            cw.task_events.record(spec.task_id.hex(), ts_exec_end=_ev_now())
            cw.set_current_task(None)
            cw.set_current_trace(None)
            if spec.task_type == TaskType.NORMAL_TASK:
                cw.current_placement_group_id = None
            if will_exit:
                logger.info("max_calls=%d reached for %s; worker exiting",
                            spec.max_calls, spec.function_name)
                try:
                    cw.task_events.flush()
                except Exception:  # noqa: BLE001 - exiting either way
                    pass
                os._exit(0)

    def _emit_dynamic_children(self, spec: TaskSpec, iterator: Any,
                               decide_exit) -> None:
        """Drain a generator's items into child objects, reporting each
        incrementally (streaming consumers iterate before the task
        finishes); the declared return resolves to the child-ref list.
        Incremental reports ride a background drainer so a slow owner
        never blocks the producer; the task-end batch is the safety
        net."""
        cw = self.cw
        report_q: "queue.Queue" = queue.Queue()

        def _report_children() -> None:
            owner = cw._pool.get(spec.owner_address)
            while True:
                item = report_q.get()
                if item is None:
                    return
                child, loc = item
                try:
                    owner.send_oneway("cw_dynamic_child",
                                      task_id=spec.task_id,
                                      child=child, loc=loc)
                except Exception:  # noqa: BLE001
                    return  # batch report covers the rest

        reporter = threading.Thread(
            target=_report_children, daemon=True,
            name="dynamic-child-report")
        reporter.start()
        children = []
        for i, item in enumerate(iterator):
            child = ObjectID.for_task_return(spec.task_id, i + 2)
            loc = cw.store_value(child.hex(), item)
            children.append((child, loc))
            report_q.put((child, loc))
        report_q.put(None)
        reporter.join(timeout=30)
        will_exit = decide_exit()
        self._report_done(
            spec,
            [(INLINE,
              ser.pack([ObjectRef(oid, spec.owner_address,
                                  _register=False)
                        for oid, _ in children]))],
            dynamic_children=children,
            worker_exiting=will_exit)

    @staticmethod
    def _split_returns(out: Any, num_returns: int) -> List[Any]:
        if num_returns == 1:
            return [out]
        if num_returns == 0:
            return []
        out_list = list(out)
        if len(out_list) != num_returns:
            raise ValueError(
                f"task declared num_returns={num_returns} but returned "
                f"{len(out_list)} values")
        return out_list

    def _report_done(self, spec: TaskSpec, results: List[Tuple],
                     dynamic_children: Optional[List[Tuple]] = None,
                     worker_exiting: bool = False,
                     nested_refs: Optional[List[Tuple]] = None) -> bool:
        """Report completion to the owner; returns True when the owner
        ACKED the report (blocking path) — the caller may then release
        transit pins immediately instead of waiting out a TTL."""
        lease_id = getattr(spec, "_lease_id", None)
        try:
            return self._report_done_once(spec, results, lease_id,
                                          dynamic_children,
                                          worker_exiting, nested_refs)
        except Exception:  # noqa: BLE001 - transient send failure
            pass
        # A LOST completion report strands the task at its owner forever
        # (the owner keeps waiting, its arg pins never release — the
        # permanent-leak class the ownership fuzzer's drop schedules
        # exercise). cw_task_done is duplicate-safe, so retry the report
        # BLOCKING with backoff; only an owner that stays unreachable
        # loses its results (and they are moot with it).
        for delay_s in (0.1, 0.4, 1.0):
            time.sleep(delay_s)
            try:
                self.cw._pool.get(spec.owner_address).call(
                    "cw_task_done", task_id=spec.task_id,
                    results=results, lease_id=lease_id,
                    dynamic_children=dynamic_children,
                    worker_exiting=worker_exiting,
                    nested_refs=nested_refs)
                return True
            except Exception:  # noqa: BLE001 - retried below
                continue
        logger.warning("owner %s unreachable for task result",
                       spec.owner_address)
        return False

    def _report_done_once(self, spec: TaskSpec, results: List[Tuple],
                          lease_id, dynamic_children,
                          worker_exiting: bool, nested_refs) -> bool:
        if worker_exiting or nested_refs:
            # BLOCKING when this process is about to exit (max_calls
            # recycling: the owner must record the result before the
            # NM's worker-death report can race in, else a task that
            # succeeded gets retried — side effects twice) AND when
            # ObjectRefs ride the result: the owner registers its
            # eager nested borrows inside this call, so on return
            # the transit pins may drop — a one-way report delayed
            # in flight (chaos `delay` on this path) could otherwise
            # arrive after the pins' TTL and find the nested objects
            # freed (ADVICE r5).
            if worker_exiting:
                # earlier one-way reports may still sit on the drainer;
                # ship them before the exit-ack — a report lost with
                # the exiting process would retry an already-succeeded
                # task (side effects twice)
                self._flush_reports()
            self.cw._pool.get(spec.owner_address).call(
                "cw_task_done", task_id=spec.task_id,
                results=results, lease_id=lease_id,
                dynamic_children=dynamic_children,
                worker_exiting=worker_exiting,
                nested_refs=nested_refs)
            return True
        report = dict(task_id=spec.task_id, results=results,
                      lease_id=lease_id,
                      dynamic_children=dynamic_children,
                      worker_exiting=worker_exiting,
                      nested_refs=nested_refs)
        if Config.task_done_batching:
            # hand off to the drainer: delivery failures are retried
            # there (blocking, per report) with the same backoff this
            # method's caller would apply
            self._report_q.put((tuple(spec.owner_address),
                                spec.owner_node_id, report))
            return False
        # one-way: the worker moves on to its next task without
        # waiting out the owner's bookkeeping round trip (send
        # failures still raise; a dead owner is the only loss case
        # and its results are moot)
        if not self.cw._shm_send(tuple(spec.owner_address),
                                 spec.owner_node_id, "cw_task_done",
                                 report):
            self.cw._pool.get(spec.owner_address).send_oneway(
                "cw_task_done", **report)
        return False

    def _report_drain_loop(self) -> None:
        while True:
            first = self._report_q.get()
            self._report_idle.clear()
            batch = [first]
            try:
                while True:
                    batch.append(self._report_q.get_nowait())
            except queue.Empty:
                pass
            self._ship_batch(batch)
            if self._report_q.empty():
                self._report_idle.set()

    def _ship_batch(self, batch: List[Tuple]) -> None:
        by_owner: Dict[Tuple, List[Dict]] = {}
        for owner, owner_node, report in batch:
            by_owner.setdefault((owner, owner_node), []).append(report)
        for (owner, owner_node), reports in by_owner.items():
            self._ship_reports(owner, owner_node, reports)

    def _ship_reports(self, owner, owner_node,
                      reports: List[Dict]) -> None:
        cw = self.cw
        try:
            with _spans.span("cw.task_done_batch", n=len(reports)):
                if len(reports) == 1:
                    if not cw._shm_send(owner, owner_node,
                                        "cw_task_done", reports[0]):
                        cw._pool.get(owner).send_oneway(
                            "cw_task_done", **reports[0])
                elif not cw._shm_send(owner, owner_node,
                                      "cw_task_done_batch",
                                      dict(reports=reports)):
                    cw._pool.get(owner).send_oneway(
                        "cw_task_done_batch", reports=reports)
            return
        except Exception:  # noqa: BLE001 - fall through to per-report
            pass           # blocking retries
        # A LOST completion report strands the task at its owner (see
        # _report_done); each report retries individually so one bad
        # element can't take its batch siblings down with it.
        for r in reports:
            delivered = False
            for delay_s in (0.1, 0.4, 1.0):
                time.sleep(delay_s)
                try:
                    cw._pool.get(owner).call("cw_task_done", **r)
                    delivered = True
                    break
                except Exception:  # noqa: BLE001 - retried with backoff;
                    continue       # the owner may be mid-restart
            if not delivered:
                logger.warning("owner %s unreachable for task result",
                               owner)

    def _flush_reports(self) -> None:
        """Ship everything queued on the done-report drainer from the
        CALLING thread, then wait (bounded) for the drainer to go idle
        so no report is left in its hands when the process exits."""
        while True:
            batch = []
            try:
                while True:
                    batch.append(self._report_q.get_nowait())
            except queue.Empty:
                pass
            if not batch:
                break
            self._ship_batch(batch)
        self._report_idle.wait(timeout=2.0)

    def _report_error(self, spec: TaskSpec, err: Exception,
                      worker_exiting: bool = False) -> None:
        try:
            self._emit_error_postmortem(spec, err)
        except Exception:  # noqa: BLE001 - diagnostics never block reports
            pass
        blob = pickle.dumps(err)
        self._report_done(spec, [(ERROR, blob)] * max(spec.num_returns, 1)
                          if spec.num_returns else [],
                          worker_exiting=worker_exiting)

    def _emit_error_postmortem(self, spec: TaskSpec,
                               err: Exception) -> None:
        """Task-failure bundle (the worker survives, so it captures its
        own context): traceback + recent log records + span-ring tail,
        one-way into the GCS's bounded postmortem ring — queryable via
        util.state.postmortems() / `ray_tpu logs --postmortem`."""
        cw = self.cw
        k = int(Config.postmortem_span_tail)
        bundle = {
            "kind": "task_error",
            "task_id": spec.task_id.hex(),
            "task": spec.function_name,
            "worker_id": cw.worker_id.hex(),
            "node_id": cw.node_id_hex,
            "actor_id": self.actor_id.hex() if self.actor_id else None,
            "trace_id": spec.trace_id,
            "reason": repr(err),
            "traceback": getattr(err, "traceback_str", "") or "",
            "ts": time.time(),
            "log_tail": _log_plane.tail(int(Config.postmortem_log_lines)),
            "span_tail": [list(r) for r in
                          _spans.ring().snapshot_records()[-k:]],
            "gauges": {"rss_bytes": _log_plane.read_rss_bytes()},
        }
        cw._pool.get(cw.gcs_address).send_oneway(
            "postmortem_report", bundle=bundle)
