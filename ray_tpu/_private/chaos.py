"""Chaos plane: deterministic, targeted fault injection.

reference parity: asio_chaos.cc (randomized handler delays behind
RAY_testing_asio_delay_us) generalized into a cluster-wide policy the
way the reference's NodeKillerActor / test_utils kill helpers are used —
but as a first-class control-plane object instead of ad-hoc test code.

A ChaosPolicy is an ordered list of ChaosRule records hosted by the GCS
and distributed to every process over the existing pubsub ("chaos"
channel). Each rule is fault x selector x trigger:

    fault     delay | drop_connection | partition | kill_worker |
              error | evict_object | stall_worker
    selector  RPC-method glob, node id (hex prefix), node pair
              (partition), actor class glob, object id glob
    trigger   seeded probability, after-N-matching-calls counter,
              max-fires cap (max_fires=1 == one-shot)

Every process consults its local copy at cheap hook points:

    rpc client call      drop_connection, partition
    rpc server dispatch  delay, kill_worker, stall_worker
    store create/get/pull  error, evict_object

`stall_worker` is the hung-collective fault (ISSUE 17): SIGSTOP a
matching worker for delay_ms, then SIGCONT it — every thread freezes
(heartbeat sidecars included), which is exactly what a wedged XLA
collective looks like from the outside. It is NODE-MANAGER-ACTUATED
ONLY: a stopped process cannot resume itself, so the worker self-fault
path that kill_worker has does not exist here; rules fire on NM
dispatch (method="nm_*" — harvest RPCs arrive every couple of
seconds) via the stall actuator, with the same record-after-confirm +
refund-on-miss accounting as daemon kills.

Counters and seeded RNG streams are PER PROCESS (each process draws the
same seeded stream, like the reference asio randomization), so a
counter-triggered rule is deterministic for the process it targets.
`evict_object` honors the store's reader leases: a leased object (a
zero-copy view is outstanding — see object_store.py pin/unpin) has its
eviction DEFERRED to the last unpin instead of rewriting memory under a
live array; the fire is still recorded when the rule triggers.
Every fire increments a prometheus counter, is reported to the GCS
(which aggregates fired counts, emits a CHAOS_FAULT_INJECTED cluster
event, and disables the rule cluster-wide once max_fires is reached).
"""

from __future__ import annotations

import fnmatch
import logging
import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple
from ray_tpu.util.locks import TracedLock

logger = logging.getLogger(__name__)

FAULT_TYPES = ("delay", "drop_connection", "partition", "kill_worker",
               "error", "evict_object", "stall_worker")

# Chaos control-plane traffic is never itself a chaos target (a drop rule
# matching "*" must not sever the channel that could clear it).
_EXEMPT_PREFIXES = ("chaos_", "cw_pubsub_push", "add_events", "subscribe")


@dataclass
class ChaosRule:
    """One injection rule. See module docstring for semantics."""

    fault: str
    rule_id: str = ""
    # ---- target selectors (empty = match anything) -------------------
    method: str = "*"            # RPC method / store op glob
    node_id: str = ""            # node id hex prefix (peer/local node)
    nodes: Tuple[str, str] = ("", "")  # partition pair (hex prefixes)
    actor_class: str = ""        # actor class glob (kill_worker)
    object_glob: str = ""        # object id glob (store faults)
    # ---- trigger -----------------------------------------------------
    probability: float = 1.0     # seeded probability per matching call
    seed: int = 0                # RNG seed (same stream in every process)
    after_n: int = 0             # skip the first N matching calls
    max_fires: int = -1          # per-process cap; 1 == one-shot; -1 inf
    # ---- fault parameters --------------------------------------------
    delay_ms: float = 0.0        # delay: sleep this long on fire
    jitter: bool = False         # delay: uniform(0, delay_ms) instead
    error_message: str = ""      # error: message of the injected error
    # ---- filled in by the GCS at install time ------------------------
    # node id hex -> [(host, port), ...] of that node's RPC endpoints
    # (node manager + object store), for partition/peer matching.
    node_addrs: Dict[str, List[Tuple[str, int]]] = field(
        default_factory=dict)
    disabled: bool = False

    def to_dict(self) -> Dict[str, Any]:
        return {
            "fault": self.fault, "rule_id": self.rule_id,
            "method": self.method, "node_id": self.node_id,
            "nodes": tuple(self.nodes), "actor_class": self.actor_class,
            "object_glob": self.object_glob,
            "probability": self.probability, "seed": self.seed,
            "after_n": self.after_n, "max_fires": self.max_fires,
            "delay_ms": self.delay_ms, "jitter": self.jitter,
            "error_message": self.error_message,
            "node_addrs": {k: [tuple(a) for a in v]
                           for k, v in self.node_addrs.items()},
            "disabled": self.disabled,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ChaosRule":
        d = dict(d)
        d["nodes"] = tuple(d.get("nodes") or ("", ""))
        d["node_addrs"] = {k: [tuple(a) for a in v]
                           for k, v in (d.get("node_addrs") or {}).items()}
        known = {f for f in cls.__dataclass_fields__}  # tolerate newer
        return cls(**{k: v for k, v in d.items() if k in known})


class ChaosError(Exception):
    """Injected by an `error` rule (store ops). Distinct type so tests
    and logs can tell injected faults from organic ones."""


@dataclass
class _RuleState:
    """Per-process trigger state for one rule."""

    rule: ChaosRule
    matches: int = 0
    fires: int = 0
    rng: random.Random = None  # type: ignore[assignment]
    # precomputed partition sides: addresses of each node-pair side
    side_a: frozenset = frozenset()
    side_b: frozenset = frozenset()
    peer_addrs: frozenset = frozenset()  # node_id selector -> its addrs

    def __post_init__(self) -> None:
        self.rng = random.Random(self.rule.seed)
        a, b = self.rule.nodes
        self.side_a = frozenset(
            addr for hexid, addrs in self.rule.node_addrs.items()
            if a and hexid.startswith(a) for addr in addrs)
        self.side_b = frozenset(
            addr for hexid, addrs in self.rule.node_addrs.items()
            if b and hexid.startswith(b) for addr in addrs)
        self.peer_addrs = frozenset(
            addr for hexid, addrs in self.rule.node_addrs.items()
            if self.rule.node_id and hexid.startswith(self.rule.node_id)
            for addr in addrs)


class ChaosClient:
    """Per-process view of the cluster ChaosPolicy + local trigger state.

    Hook entry points are cheap no-ops until a policy with live rules is
    installed (module-level `active` flag, no lock on the fast path).
    """

    def __init__(self) -> None:
        self._lock = TracedLock("chaos")
        self._rules: List[_RuleState] = []
        self._version = -1
        self.active = False
        # process context
        self.node_id: str = ""
        self.actor_class: str = ""
        self.is_worker = False
        self.gcs_address: Optional[Tuple[str, int]] = None
        # NM-registered actuator: fn(actor_class_glob) -> None
        self._kill_actuator: Optional[Callable[[str], None]] = None
        # NM-registered actuator: fn(actor_class_glob, duration_ms) ->
        # bool (SIGSTOP a matching local worker, SIGCONT after duration)
        self._stall_actuator: Optional[Callable[[str, float], bool]] = None
        # worker-registered black-box hook: fn(reason) runs just before
        # a chaos self-kill so the dying process can persist its flight
        # dump (log_plane.write_flight_dump)
        self._predeath_hook: Optional[Callable[[str], Any]] = None
        self._tls = threading.local()
        self._counter = None  # lazy prometheus counter
        self._report_client = None
        self._env_rule_installed = False
        self._install_env_compat_rule()

    # ---- context / wiring -------------------------------------------

    def set_context(self, *, node_id: str = "", is_worker: bool = False,
                    gcs_address: Optional[Tuple[str, int]] = None) -> None:
        """Record this process's identity. node_id only fills in if not
        already set (first daemon wins: in-process head node and test
        clusters share one process across roles)."""
        with self._lock:
            if node_id and not self.node_id:
                self.node_id = node_id
            if is_worker:
                self.is_worker = True
            if gcs_address is not None and self.gcs_address is None:
                self.gcs_address = tuple(gcs_address)

    def set_actor_class(self, class_name: str) -> None:
        with self._lock:
            self.actor_class = class_name

    def reset(self) -> None:
        """Forget cluster-scoped state (context + distributed rules) so
        a later init against a DIFFERENT cluster starts clean — without
        this, a driver that shut one cluster down would keep matching
        the old cluster's node ids and policy version. The env-var
        compat rule is process-local and survives."""
        with self._lock:
            self.node_id = ""
            self.actor_class = ""
            self.is_worker = False
            self.gcs_address = None
            self._kill_actuator = None
            self._stall_actuator = None
            self._predeath_hook = None
            self._version = -1
            self._rules = [st for st in self._rules
                           if st.rule.rule_id == "env-rpc-delay"]
            self.active = bool(self._rules)
            report_client, self._report_client = self._report_client, None
        if report_client is not None:
            try:
                report_client.close()
            except Exception:  # noqa: BLE001 - old report client; already severed
                pass

    def set_kill_actuator(self, fn: Callable[[str], None]) -> None:
        """Node manager registers how kill_worker rules targeting its
        node take effect (kill a matching local worker process)."""
        with self._lock:
            self._kill_actuator = fn

    def set_stall_actuator(self, fn: Callable[[str, float], bool]) -> None:
        """Node manager registers how stall_worker rules take effect
        (SIGSTOP a matching local worker, SIGCONT after the duration).
        Daemon-side only: a stopped process cannot resume itself."""
        with self._lock:
            self._stall_actuator = fn

    def set_predeath_hook(self, fn: Callable[[str], Any]) -> None:
        """Worker registers its black-box flight-dump writer, run just
        before a self-kill fault exits the process."""
        with self._lock:
            self._predeath_hook = fn

    # ---- policy install ----------------------------------------------

    def _install_env_compat_rule(self) -> None:
        """Compat shim: RAY_TPU_testing_rpc_delay_us(_seed) becomes a
        process-local startup-installed delay rule (deprecated; see
        _private/config.py)."""
        try:
            from ray_tpu._private.config import Config
            max_us = Config.testing_rpc_delay_us
        except Exception:  # noqa: BLE001 - config import must never break rpc
            max_us = 0
        if max_us <= 0:
            return
        seed = os.environ.get("RAY_TPU_testing_rpc_delay_seed")
        rule = ChaosRule(
            fault="delay", rule_id="env-rpc-delay", method="*",
            delay_ms=max_us / 1000.0, jitter=True,
            seed=int(seed) if seed is not None else
            random.randrange(1 << 30))
        self._rules.append(_RuleState(rule))
        self._env_rule_installed = True
        self.active = True

    def install(self, policy: Dict[str, Any]) -> None:
        """Replace the cluster-distributed rules with a new policy
        version; per-rule local counters survive (keyed by rule id) so a
        version bump that merely disables one rule doesn't reset the
        others' deterministic counters."""
        version = int(policy.get("version", 0))
        with self._lock:
            if version <= self._version:
                return
            self._version = version
            prior = {st.rule.rule_id: st for st in self._rules}
            rules: List[_RuleState] = []
            # the env compat rule is local-only: keep it at the front
            env = prior.get("env-rpc-delay")
            if env is not None and self._env_rule_installed:
                rules.append(env)
            for rec in policy.get("rules", []):
                rule = ChaosRule.from_dict(rec)
                if rule.disabled:
                    continue
                st = prior.get(rule.rule_id)
                if st is not None and st.rule.to_dict() == rule.to_dict():
                    # unchanged rule riding a version bump (e.g. a
                    # sibling was disabled): carry counters + rng
                    # position over so its schedule stays deterministic
                    rules.append(st)
                else:
                    # new or RE-INJECTED rule: fresh state, so the
                    # precomputed selector sets match the new content
                    # and its counter/rng schedule starts from zero
                    rules.append(_RuleState(rule))
            self._rules = rules
            self.active = bool(rules)

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [{**st.rule.to_dict(), "matches": st.matches,
                     "fires": st.fires} for st in self._rules]

    # ---- trigger evaluation ------------------------------------------

    def _should_fire(self, st: _RuleState) -> bool:
        """Evaluate a rule's trigger for one matching call. Caller holds
        self._lock."""
        st.matches += 1
        if st.matches <= st.rule.after_n:
            return False
        if 0 <= st.rule.max_fires <= st.fires:
            return False
        if st.rule.probability < 1.0 and \
                st.rng.random() >= st.rule.probability:
            return False
        st.fires += 1
        return True

    def _record_fire(self, st: _RuleState, where: str) -> None:
        """Metrics + audit trail for one fired rule: bump the local
        prometheus counter and (one-way, best-effort) tell the GCS so it
        can aggregate counts, emit the CHAOS_FAULT_INJECTED event, and
        enforce cluster-wide max_fires."""
        rule = st.rule
        logger.warning("chaos: rule %s fired %s at %s",
                       rule.rule_id, rule.fault, where)
        try:
            counter = self._counter
            if counter is None:
                from ray_tpu.util.metrics import Counter
                counter = Counter(
                    "ray_tpu_chaos_faults_injected_total",
                    "chaos faults fired in this process",
                    tag_keys=("fault", "rule_id"))
                self._counter = counter
            counter.inc(tags={"fault": rule.fault,
                              "rule_id": rule.rule_id})
        except Exception:  # noqa: BLE001 - telemetry must never block a fault
            pass
        if self.gcs_address is None:
            return
        try:
            client = self._report_client
            if client is None:
                from ray_tpu._private import rpc as rpc_lib
                client = rpc_lib.RpcClient(self.gcs_address, timeout=5)
                self._report_client = client
            client.send_oneway("chaos_report_fired", rule_id=rule.rule_id,
                               fault=rule.fault, where=where,
                               node_id=self.node_id)
        except Exception:  # noqa: BLE001 - GCS gone; local effect stands
            pass

    def _entered(self) -> bool:
        """Reentrancy guard: hooks triggered while handling a hook (the
        fire-report RPC, actuator kills) must pass through untouched."""
        return getattr(self._tls, "in_hook", False)

    # ---- hook points -------------------------------------------------

    def on_client_call(self, method: str,
                       address: Tuple[str, int]) -> None:
        """RPC client hook: drop_connection + partition faults. Raises
        rpc.ConnectionLost on fire (before anything is sent, so the
        failure is deterministic and not absorbed by send retries)."""
        if not self.active or self._entered() or \
                method.startswith(_EXEMPT_PREFIXES):
            return
        address = tuple(address)
        fired: Optional[_RuleState] = None
        with self._lock:
            for st in self._rules:
                rule = st.rule
                if rule.fault == "drop_connection":
                    if not fnmatch.fnmatchcase(method, rule.method):
                        continue
                    if st.peer_addrs and address not in st.peer_addrs:
                        continue
                elif rule.fault == "partition":
                    if not fnmatch.fnmatchcase(method, rule.method):
                        continue
                    mine, (a, b) = self.node_id, rule.nodes
                    if not mine or not a or not b:
                        continue
                    if mine.startswith(a) and address in st.side_b:
                        pass
                    elif mine.startswith(b) and address in st.side_a:
                        pass
                    else:
                        continue
                else:
                    continue
                if self._should_fire(st):
                    fired = st
                    break
        if fired is None:
            return
        self._tls.in_hook = True
        try:
            self._record_fire(fired, f"client:{method}->{address}")
        finally:
            self._tls.in_hook = False
        from ray_tpu._private import rpc as rpc_lib
        raise rpc_lib.ConnectionLost(
            f"chaos {fired.rule.fault} rule {fired.rule.rule_id} "
            f"dropped {method} to {address}")

    def on_server_dispatch(self, method: str) -> None:
        """RPC server hook: delay + kill_worker + stall_worker faults."""
        if not self.active or self._entered() or \
                method.startswith(_EXEMPT_PREFIXES):
            return
        sleep_s = 0.0
        kill: Optional[_RuleState] = None
        stall: Optional[_RuleState] = None
        fired: List[Tuple[_RuleState, str]] = []
        with self._lock:
            for st in self._rules:
                rule = st.rule
                if rule.fault == "stall_worker" and stall is None:
                    # NM-actuated only (a SIGSTOP'd process cannot
                    # SIGCONT itself): workers never self-stall, and a
                    # daemon without the actuator skips the rule. Like
                    # daemon kills, the fire is recorded only after the
                    # actuator confirms a victim (refunded on a miss).
                    if self.is_worker or self._stall_actuator is None:
                        continue
                    if not fnmatch.fnmatchcase(method, rule.method):
                        continue
                    if rule.node_id and not \
                            self.node_id.startswith(rule.node_id):
                        continue
                    if self._should_fire(st):
                        stall = st
                    continue
                if rule.fault == "delay":
                    if not fnmatch.fnmatchcase(method, rule.method):
                        continue
                    if rule.node_id and not \
                            self.node_id.startswith(rule.node_id):
                        continue
                    if self._should_fire(st):
                        sleep_s += (st.rng.uniform(0, rule.delay_ms)
                                    if rule.jitter else rule.delay_ms) \
                            / 1000.0
                        if rule.rule_id != "env-rpc-delay":
                            fired.append((st, f"server:{method}"))
                elif rule.fault == "kill_worker" and kill is None:
                    if not fnmatch.fnmatchcase(method, rule.method):
                        continue
                    if rule.node_id and not \
                            self.node_id.startswith(rule.node_id):
                        continue
                    if self.is_worker:
                        if rule.actor_class and not (
                                self.actor_class and fnmatch.fnmatchcase(
                                    self.actor_class, rule.actor_class)):
                            continue
                    elif not (self._kill_actuator is not None
                              and rule.node_id):
                        # daemon-side kills need an actuator AND an
                        # explicit node target; anything else is the
                        # worker's own self-kill path
                        continue
                    if self._should_fire(st):
                        kill = st
                        if self.is_worker:
                            fired.append((st, f"server:{method}"))
                        # daemon-side kills record only AFTER the
                        # actuator confirms a victim (below): a no-op
                        # "fire" must not spend a one-shot budget
        self._tls.in_hook = True
        try:
            for st, where in fired:
                self._record_fire(st, where)
        finally:
            self._tls.in_hook = False
        if sleep_s > 0:
            time.sleep(sleep_s)
        if stall is not None:
            self._tls.in_hook = True
            try:
                stalled = bool(self._stall_actuator(
                    stall.rule.actor_class, stall.rule.delay_ms))
            except Exception:  # noqa: BLE001 - actuator crashed
                stalled = False
            finally:
                self._tls.in_hook = False
            if stalled:
                self._tls.in_hook = True
                try:
                    self._record_fire(stall, f"server:{method}")
                finally:
                    self._tls.in_hook = False
            else:
                # refund: no worker matched the selector right now
                with self._lock:
                    if stall.fires > 0:
                        stall.fires -= 1
        if kill is None:
            return
        if self.is_worker:
            # simulate preemption: die hard, mid-dispatch, like a real
            # SIGKILL'd TPU worker — the node manager's death report and
            # the recovery machinery take it from here
            logger.warning("chaos: rule %s killing this worker (%s)",
                           kill.rule.rule_id, self.actor_class or "task")
            try:
                if self._predeath_hook is not None:
                    # persist the span-ring tail + log tail so the node
                    # manager's postmortem bundle can explain this death
                    try:
                        self._predeath_hook(
                            f"chaos rule {kill.rule.rule_id} kill_worker")
                    except Exception:  # noqa: BLE001 - dying anyway
                        pass
                self._flush_report()
            finally:
                os._exit(1)
        else:
            self._tls.in_hook = True
            try:
                killed = bool(self._kill_actuator(kill.rule.actor_class))
            except Exception:  # noqa: BLE001 - actuator crashed
                killed = False
            finally:
                self._tls.in_hook = False
            if killed:
                self._tls.in_hook = True
                try:
                    self._record_fire(kill, f"server:{method}")
                finally:
                    self._tls.in_hook = False
            else:
                # refund the consumed fire: nothing matched the victim
                # selector right now, and the rule must stay armed
                with self._lock:
                    if kill.fires > 0:
                        kill.fires -= 1

    def on_store_op(self, op: str, object_ids: List[str],
                    store: Any) -> None:
        """Object store hook (create/get/pull): error + evict_object."""
        if not self.active or self._entered():
            return
        evict: List[Tuple[_RuleState, str]] = []
        err: Optional[_RuleState] = None
        with self._lock:
            for st in self._rules:
                rule = st.rule
                if rule.fault not in ("error", "evict_object"):
                    continue
                if not fnmatch.fnmatchcase(op, rule.method):
                    continue
                if rule.object_glob and not any(
                        fnmatch.fnmatchcase(oid, rule.object_glob)
                        for oid in object_ids):
                    continue
                if not self._should_fire(st):
                    continue
                if rule.fault == "error" and err is None:
                    err = st
                elif rule.fault == "evict_object":
                    evict.append((st, rule.object_glob))
        self._tls.in_hook = True
        try:
            for st, glob in evict:
                self._record_fire(st, f"store:{op}")
                try:
                    store.chaos_evict(glob or None, object_ids)
                except Exception:  # noqa: BLE001 - object already gone
                    pass
            if err is not None:
                self._record_fire(err, f"store:{op}")
        finally:
            self._tls.in_hook = False
        if err is not None:
            raise ChaosError(
                err.rule.error_message
                or f"chaos rule {err.rule.rule_id} failed store op {op}")

    def _flush_report(self) -> None:
        """Best-effort: let the in-flight oneway fire report reach the
        socket before os._exit truncates the process."""
        time.sleep(0.02)


_CLIENT = ChaosClient()


def client() -> ChaosClient:
    return _CLIENT


# Module-level hook wrappers (what rpc.py / object_store.py call).

def on_client_call(method: str, address: Tuple[str, int]) -> None:
    if _CLIENT.active:
        _CLIENT.on_client_call(method, address)


def on_server_dispatch(method: str) -> None:
    if _CLIENT.active:
        _CLIENT.on_server_dispatch(method)


def on_store_op(op: str, object_ids: List[str], store: Any) -> None:
    if _CLIENT.active:
        _CLIENT.on_store_op(op, object_ids, store)


def on_policy_message(message: Any) -> None:
    """Pubsub callback for the "chaos" channel."""
    try:
        _CLIENT.install(dict(message))
    except Exception:  # noqa: BLE001 - malformed policy must not kill pubsub
        logger.exception("bad chaos policy message")


def fetch_policy(gcs_call: Callable[..., Any]) -> None:
    """Pull the current policy at process startup (pubsub only covers
    processes alive at publish time)."""
    try:
        policy = gcs_call("chaos_get_policy")
        if policy:
            _CLIENT.install(policy)
    except Exception:  # noqa: BLE001 - old GCS / unreachable: no chaos
        pass
