"""LogMonitor: tail worker log files, index + publish attributed lines.

reference parity: python/ray/_private/log_monitor.py:103 — a per-node
process tails the session log dir and publishes new lines through GCS
pubsub; drivers print them with a (worker, node) prefix
(worker.py:1823 print_to_stdstream). Here it's a daemon thread inside
each node manager that additionally:

  - parses each line's attribution stamp (log_plane.parse_line: proc
    kind/pid, task id, actor id, trace id, level),
  - keeps a bounded per-worker in-memory tail index with rotation-safe
    offsets (inode change or truncation resets the offset) that the
    node manager serves to the GCS `logs_query` fan-out, and
  - flood-controls the driver stream: a per-source token bucket caps
    published lines/sec; dropped lines are counted (they stay in the
    tail index — only the live stream sheds).
"""

from __future__ import annotations

import collections
import itertools
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import log_plane
from ray_tpu._private.config import Config
from ray_tpu.util.locks import TracedLock

logger = logging.getLogger(__name__)


class LogMonitor:
    def __init__(self, log_dir: str, gcs_address: Optional[Tuple[str, int]],
                 node_id_hex: str, poll_interval: float = 0.25,
                 tail_lines: Optional[int] = None,
                 rate_lps: Optional[float] = None,
                 burst: Optional[int] = None,
                 _client: Any = None):
        self.log_dir = log_dir
        self.node_id_hex = node_id_hex
        self.poll_interval = poll_interval
        self.tail_lines = tail_lines or Config.log_tail_lines
        self.rate_lps = Config.log_stream_rate_lps \
            if rate_lps is None else rate_lps
        self.burst = burst or Config.log_stream_burst
        # file name -> (inode, offset): rotation/truncation safe — an
        # inode change (logrotate-style replace) or a size below the
        # recorded offset (copytruncate) restarts the tail at 0
        self._offsets: Dict[str, Tuple[int, int]] = {}
        self._tails: Dict[str, "collections.deque"] = {}
        self._seq = itertools.count()
        # flood control state per source: (tokens, last_refill_mono);
        # touched only by the single publisher (monitor thread, or
        # stop()'s final drain after the join)
        self._bucket: Dict[str, Tuple[float, float]] = {}
        self.dropped_by_source: Dict[str, int] = {}
        self._scan_lock = TracedLock("log_monitor_scan")
        # (source, records) awaiting publication, guarded by _scan_lock.
        # Scans queue here and the monitor thread publishes OUTSIDE the
        # lock: the publish RPC can block up to its 30s client timeout
        # (slow/partitioned GCS), and holding the lock through it would
        # stall logs_snapshot queries and postmortem capture.
        self._publish_q: List[Tuple[str, List[Dict[str, Any]]]] = []
        self._stop = threading.Event()
        if _client is not None:
            self._gcs = _client
        else:
            from ray_tpu._private.rpc import RpcClient
            self._gcs = RpcClient(tuple(gcs_address), timeout=30)
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="log-monitor")
        self._thread.start()

    # ---- tail index + queries -------------------------------------------

    def _source_records(self, source: str, lines: List[str]
                        ) -> List[Dict[str, Any]]:
        worker_id = source[len("worker-"):] if source.startswith("worker-") \
            else source
        out = []
        for raw in lines:
            rec = log_plane.parse_line(raw)
            rec["node_id"] = self.node_id_hex[:12]
            rec["worker_id"] = worker_id
            rec["source"] = source
            rec["seq"] = next(self._seq)
            if rec["ts"] is None:
                rec["ts"] = time.time()
            out.append(rec)
        return out

    def query(self, filters: Optional[Dict[str, Any]] = None,
              tail: int = 500) -> List[Dict[str, Any]]:
        """Filtered view over the node's tail index, oldest-first,
        trimmed to the last `tail` records."""
        with self._scan_lock:
            records: List[Dict[str, Any]] = []
            for dq in self._tails.values():
                records.extend(dq)
        records = log_plane.filter_records(records, filters)
        records.sort(key=lambda r: (r.get("ts") or 0.0, r.get("seq", 0)))
        return records[-int(tail):] if tail else records

    def tail_records(self, source: str, n: int) -> List[Dict[str, Any]]:
        with self._scan_lock:
            dq = self._tails.get(source)
            recs = list(dq) if dq is not None else []
        return recs[-n:]

    def scan_now(self) -> None:
        """Synchronous scan (postmortem capture wants the dead worker's
        final lines in the index before bundling)."""
        self._scan_once()

    # ---- tailing loop ---------------------------------------------------

    def _take_tokens(self, source: str, want: int) -> int:
        """Token-bucket flood control per source; returns how many of
        `want` lines may be published now."""
        if self.rate_lps <= 0:
            return want
        now = time.monotonic()
        tokens, last = self._bucket.get(source, (float(self.burst), now))
        tokens = min(float(self.burst), tokens + (now - last) * self.rate_lps)
        grant = min(want, int(tokens))
        self._bucket[source] = (tokens - grant, now)
        return grant

    def _scan_once(self) -> None:
        if not os.path.isdir(self.log_dir):
            return
        with self._scan_lock:
            for name in sorted(os.listdir(self.log_dir)):
                if not name.endswith(".log"):
                    continue
                path = os.path.join(self.log_dir, name)
                try:
                    st = os.stat(path)
                except OSError:
                    continue
                ino, offset = self._offsets.get(name, (st.st_ino, 0))
                if ino != st.st_ino or st.st_size < offset:
                    # rotated (new inode) or truncated: restart the tail
                    offset = 0
                    ino = st.st_ino
                if st.st_size <= offset:
                    self._offsets[name] = (ino, offset)
                    continue
                try:
                    with open(path, "rb") as f:
                        f.seek(offset)
                        chunk = f.read(st.st_size - offset)
                except OSError:
                    continue
                # only index complete lines; keep the partial tail for
                # the next scan
                last_nl = chunk.rfind(b"\n")
                if last_nl < 0:
                    self._offsets[name] = (ino, offset)
                    continue
                self._offsets[name] = (ino, offset + last_nl + 1)
                lines = chunk[:last_nl].decode(
                    "utf-8", errors="replace").splitlines()
                if not lines:
                    continue
                source = name[:-len(".log")]
                records = self._source_records(source, lines)
                dq = self._tails.get(source)
                if dq is None:
                    dq = self._tails[source] = collections.deque(
                        maxlen=self.tail_lines)
                dq.extend(records)
                self._publish_q.append((source, records))

    def _publish(self, source: str, records: List[Dict[str, Any]]) -> None:
        grant = self._take_tokens(source, len(records))
        dropped = len(records) - grant
        if dropped:
            self.dropped_by_source[source] = \
                self.dropped_by_source.get(source, 0) + dropped
            try:
                from ray_tpu.util.metrics import Counter, get_or_create
                get_or_create(
                    Counter, "ray_tpu_log_lines_dropped_total",
                    description="log lines shed from the driver stream "
                                "by per-source flood control (the tail "
                                "index keeps them)").inc(dropped)
            except Exception:  # noqa: BLE001 - metrics are best-effort
                pass
        published = records[:grant]
        if not published and not dropped:
            return
        try:
            self._gcs.call("publish", channel="worker_logs", message={
                "node_id": self.node_id_hex,
                "worker": source,
                "lines": [r.get("msg", "") for r in published],
                "records": published,
                "dropped": dropped,
                "dropped_total": self.dropped_by_source.get(source, 0),
            })
        except Exception:  # noqa: BLE001
            logger.debug("log publish failed", exc_info=True)

    def _drain_publish(self) -> None:
        """Publish queued batches (single caller at a time: the monitor
        thread, or stop()'s drain after the join — so the token-bucket
        state and per-source ordering stay race-free)."""
        while True:
            with self._scan_lock:
                if not self._publish_q:
                    return
                source, records = self._publish_q.pop(0)
            self._publish(source, records)

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_interval):
            try:
                self._scan_once()
                self._drain_publish()
            except Exception:  # noqa: BLE001
                logger.debug("log monitor scan failed", exc_info=True)

    def stop(self) -> None:
        self._stop.set()
        # the poll thread shares the index and the GCS client: join it
        # before the final drain so nothing races or double-publishes
        self._thread.join(timeout=5)
        # final drain so lines written just before shutdown still flow
        try:
            self._scan_once()
            self._drain_publish()
        except Exception:  # noqa: BLE001 - final drain is best-effort
            pass
        try:
            self._gcs.close()
        except Exception:  # noqa: BLE001 - closing an already-dead client
            pass
