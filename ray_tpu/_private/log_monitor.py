"""LogMonitor: tail worker log files, publish lines to the driver.

reference parity: python/ray/_private/log_monitor.py:103 — a per-node
process tails the session log dir and publishes new lines through GCS
pubsub; drivers print them with a (worker, node) prefix
(worker.py:1823 print_to_stdstream). Here it's a daemon thread inside
each node manager publishing to the "worker_logs" channel.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Dict, Tuple

logger = logging.getLogger(__name__)


class LogMonitor:
    def __init__(self, log_dir: str, gcs_address: Tuple[str, int],
                 node_id_hex: str, poll_interval: float = 0.25):
        self.log_dir = log_dir
        self.node_id_hex = node_id_hex
        self.poll_interval = poll_interval
        self._offsets: Dict[str, int] = {}
        self._stop = threading.Event()
        from ray_tpu._private.rpc import RpcClient
        self._gcs = RpcClient(gcs_address, timeout=30)
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="log-monitor")
        self._thread.start()

    def _scan_once(self) -> None:
        if not os.path.isdir(self.log_dir):
            return
        for name in sorted(os.listdir(self.log_dir)):
            if not name.endswith(".log"):
                continue
            path = os.path.join(self.log_dir, name)
            try:
                size = os.path.getsize(path)
            except OSError:
                continue
            offset = self._offsets.get(name, 0)
            if size <= offset:
                continue
            try:
                with open(path, "rb") as f:
                    f.seek(offset)
                    chunk = f.read(size - offset)
            except OSError:
                continue
            # only publish complete lines; keep the partial tail for
            # the next scan
            last_nl = chunk.rfind(b"\n")
            if last_nl < 0:
                continue
            self._offsets[name] = offset + last_nl + 1
            lines = chunk[:last_nl].decode(
                "utf-8", errors="replace").splitlines()
            if not lines:
                continue
            worker = name[:-len(".log")]
            try:
                self._gcs.call("publish", channel="worker_logs",
                               message={"node_id": self.node_id_hex,
                                        "worker": worker,
                                        "lines": lines})
            except Exception:  # noqa: BLE001
                logger.debug("log publish failed", exc_info=True)

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_interval):
            try:
                self._scan_once()
            except Exception:  # noqa: BLE001
                logger.debug("log monitor scan failed", exc_info=True)

    def stop(self) -> None:
        self._stop.set()
        # the poll thread shares _offsets and the GCS client: join it
        # before the final drain so nothing races or double-publishes
        self._thread.join(timeout=5)
        # final drain so lines written just before shutdown still flow
        try:
            self._scan_once()
        except Exception:  # noqa: BLE001
            pass
        self._gcs.close()
