"""Ownership protocol: explicit ref/lease/pin state machines.

reference parity: the design argument of Wang et al., "Ownership: A
Distributed Futures System for Fine-Grained Tasks" (NSDI '21) +
reference_count.h / lease protocol state — here made EXPLICIT instead of
implicit across ~15 interacting dicts in core_worker.py. Every count and
state the protocol maintains lives in this module and is mutated ONLY
through methods that funnel into one `transition()` choke point, which

  - validates legal edges (double-release, negative counts and
    free-while-pinned raise `OwnershipError` at the mutation site, not
    as downstream corruption),
  - tolerates the network-raced edges the protocol genuinely has
    (a duplicate remote release, a grant outracing its "queued" reply)
    by recording them as `unmatched:*` anomalies instead of raising,
  - appends every change to a bounded per-process transition ring, so a
    stuck object can explain itself (`ray_tpu ownership`,
    `/api/ownership`, `util.state.ownership`).

The machines:

  RefState (owner side, per object id)     LeaseState (per scheduling key)

      (unknown)                                slots: claim -> park(nm)
         | submit/put                                 -> grant/release
      PENDING ----------- recover <--.         leases: grant -> push(+1 in
         | resolve                   |                 flight) -> settle(-1)
      INLINE|STORE|ERROR ------------'                 -> drop/return
         | free (force for ray.free)           running: lease -> {task hexes}
      FREED   (terminal)

  counts per object: local_refs (ObjectRefs in this process), arg_pins
  (in-flight task args / transit pins / borrower-backed pins), borrower
  registrations per remote address (always a subset of arg_pins by
  construction), replica reader leases on the LOCAL store's pulled copy.

graftlint RT018 enforces the funnel: direct mutation of these count
dicts outside this module is a lint error.

Locking contract: tables do NOT lock. Every mutator must be called with
the owning component's lock held (CoreWorker._lock / StoreServer._lock /
NodeManager._lock); the ring itself is thread-safe.
"""

from __future__ import annotations

import collections
import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set, Tuple

# Object location tags (duplicated from core_worker to avoid an import
# cycle; core_worker asserts they match).
INLINE, STORE, ERROR, PENDING, FREED = \
    "inline", "store", "error", "pending", "freed"

_READY = (INLINE, STORE, ERROR)

# Legal location-tag edges for RefState (None = not yet known).
# READY->READY covers duplicate/late completion reports and dynamic-child
# re-registration (idempotent by design); READY->PENDING is lineage
# recovery resetting a lost object for re-execution.
_LOC_EDGES = {
    (None, PENDING), (None, INLINE), (None, STORE), (None, ERROR),
    (PENDING, PENDING), (PENDING, INLINE), (PENDING, STORE),
    (PENDING, ERROR),
    (INLINE, INLINE), (INLINE, STORE), (INLINE, ERROR), (INLINE, PENDING),
    (STORE, STORE), (STORE, INLINE), (STORE, ERROR), (STORE, PENDING),
    (ERROR, ERROR), (ERROR, INLINE), (ERROR, STORE), (ERROR, PENDING),
    (INLINE, FREED), (STORE, FREED), (ERROR, FREED),
    (FREED, FREED),  # idempotent re-free is a no-op, not a bug
}


class OwnershipError(RuntimeError):
    """An illegal ownership-protocol transition (double release,
    negative count, free of a pinned object) caught at its source."""


# ---------------------------------------------------------------------
# Transition ring: the protocol's flight recorder
# ---------------------------------------------------------------------


class TransitionRing:
    """Bounded ring of protocol transitions for this process. Appends
    are cheap (tuple into a deque under a short lock); `snapshot()`
    serves the ownership query plane. Anomalies (unmatched releases,
    clamped counts, rejected edges) are additionally counted by event so
    invariant checkers can assert on totals without scanning."""

    def __init__(self, maxlen: int = 2048):
        self._ring: "collections.deque" = collections.deque(maxlen=maxlen)
        self._seq = itertools.count(1)
        self._lock = threading.Lock()
        self.anomalies: Dict[str, int] = {}

    def record(self, kind: str, key: str, event: str, old: Any,
               new: Any, detail: Optional[str] = None) -> None:
        rec = (next(self._seq), time.time(), kind, key, event, old, new,
               detail)
        with self._lock:
            self._ring.append(rec)
            if event.startswith(("unmatched:", "illegal:")):
                self.anomalies[event] = self.anomalies.get(event, 0) + 1

    def snapshot(self, key_prefix: Optional[str] = None,
                 kind: Optional[str] = None,
                 limit: Optional[int] = None) -> Dict[str, Any]:
        with self._lock:
            recs = list(self._ring)
            anomalies = dict(self.anomalies)
        if kind:
            recs = [r for r in recs if r[2] == kind]
        if key_prefix:
            recs = [r for r in recs if str(r[3]).startswith(key_prefix)]
        if limit:
            recs = recs[-int(limit):]
        return {
            "transitions": [
                {"seq": r[0], "ts": r[1], "kind": r[2], "key": r[3],
                 "event": r[4], "old": r[5], "new": r[6],
                 "detail": r[7]} for r in recs],
            "anomalies": anomalies,
        }


_RING = TransitionRing()


def ring() -> TransitionRing:
    return _RING


def anomaly_counts() -> Dict[str, int]:
    """Per-process `unmatched:*` / `illegal:*` totals (fuzzer oracle)."""
    with _RING._lock:
        return dict(_RING.anomalies)


def transition(kind: str, key: str, event: str, old: Any, new: Any, *,
               strict: bool = True, signed: bool = False,
               detail: Optional[str] = None) -> Any:
    """THE choke point: every protocol state change funnels through
    here. Validates the edge — negative counts and illegal location
    edges either raise (`strict`, the in-process default: the caller
    holds both sides of the books, so a mismatch is a local bug) or are
    recorded as anomalies and clamped (network-raced edges: a duplicate
    remote release is the peer's history, not this process's
    corruption). `signed` counters may legally dip below zero (the
    parked-request buckets, where a grant can outrace its own "queued"
    reply). Returns the value actually committed (clamped when
    non-strict)."""
    illegal = None
    committed = new
    if isinstance(new, int) and new < 0 and not signed:
        illegal = f"count below zero ({old} -> {new})"
        committed = 0
    elif kind == "ref.loc" and (old, new) not in _LOC_EDGES:
        illegal = f"location edge {old} -> {new}"
        committed = old
    if illegal is None:
        _RING.record(kind, key, event, old, new, detail)
        return committed
    _RING.record(kind, key, f"illegal:{event}" if strict
                 else f"unmatched:{event}", old, new,
                 detail or illegal)
    if strict:
        raise OwnershipError(
            f"illegal ownership transition [{kind}] {event} on "
            f"{str(key)[:16]}: {illegal}"
            f"{' (' + detail + ')' if detail else ''}")
    return committed


# ---------------------------------------------------------------------
# RefState: owner-side per-object machine
# ---------------------------------------------------------------------


class RefTable:
    """Owner-side reference table: object location states plus every
    count that holds an object alive from this process. Mutate ONLY via
    methods; callers hold CoreWorker._lock (see module docstring)."""

    def __init__(self):
        # oid hex -> location tuple (tag, ...); the object directory
        self.objects: Dict[str, Tuple] = {}
        self.local_refs: Dict[str, int] = {}
        self.arg_pins: Dict[str, int] = {}
        self.borrowed: Dict[str, Tuple[str, int]] = {}
        self.borrower_pins: Dict[str, Dict[Tuple[str, int], int]] = {}
        self.replica_leases: Dict[str, int] = {}
        # enclosing-result oid hex -> [(owner_addr, nested oid hex)]
        self.nested_borrows: Dict[str, List[Tuple]] = {}
        # (deadline, local hexes, remote (addr, hex) keys) transit pins
        self.ttl_pins: List[Tuple] = []
        # outgoing REMOTE transit pins (pin_refs sent a cw_add_ref we
        # have not yet queued the release for): counts by oid hex. The
        # claim evidence behind cw_claims — without it, an owner's
        # reconciliation sweep could release a transit pin while the
        # done-report it protects is still in flight (the ADVICE-r5
        # freed-nested-object race, reintroduced via anti-entropy)
        self.transit_out: Dict[str, int] = {}

    # ---- location state ----------------------------------------------

    def loc_tag(self, h: str) -> Optional[str]:
        loc = self.objects.get(h)
        return loc[0] if loc is not None else None

    def set_location(self, h: str, loc: Tuple, *, event: str,
                     force: bool = False) -> Optional[Tuple]:
        """Commit a location transition. Freeing while this process
        still counts live claimants raises unless `force` (ray.free's
        explicit contract is "free even though referenced")."""
        old = self.objects.get(h)
        old_tag = old[0] if old is not None else None
        new_tag = loc[0]
        if new_tag == FREED and not force and (
                self.local_refs.get(h, 0) > 0
                or self.arg_pins.get(h, 0) > 0):
            transition("ref.loc", h, f"illegal:{event}", old_tag, new_tag,
                       strict=False,
                       detail=f"free while pinned (local_refs="
                              f"{self.local_refs.get(h, 0)}, arg_pins="
                              f"{self.arg_pins.get(h, 0)})")
            raise OwnershipError(
                f"free of {h[:16]} while pinned: local_refs="
                f"{self.local_refs.get(h, 0)} arg_pins="
                f"{self.arg_pins.get(h, 0)}")
        if old_tag == FREED and new_tag == FREED:
            return old  # idempotent re-free: no-op, not recorded
        if old_tag == new_tag and old == loc:
            return old  # no-change rewrite (duplicate report)
        transition("ref.loc", h, event, old_tag, new_tag)
        self.objects[h] = loc
        return old

    # ---- local refs --------------------------------------------------
    #
    # Local-ref counts are the protocol's highest-rate events (every
    # ObjectRef construction/destruction). Only the BOUNDARY edges are
    # protocol-relevant — first ref (0 -> 1: borrow registration) and
    # last ref (1 -> 0: release/free) — so only those hit the ring;
    # interior increments are always-legal dict ops and skip the choke
    # point entirely, keeping the put/get hot path free of the ring
    # lock. Illegal decrements still always validate (and raise).

    def incr_local(self, h: str) -> int:
        n = self.local_refs.get(h, 0) + 1
        if n == 1:
            transition("ref.local", h, "add_local_ref", 0, 1)
        self.local_refs[h] = n
        return n

    def decr_local(self, h: str, *, strict: bool = True) -> int:
        old = self.local_refs.get(h, 0)
        if old > 1:
            self.local_refs[h] = old - 1
            return old - 1
        n = transition("ref.local", h, "remove_local_ref",
                       old, old - 1, strict=strict)
        if n <= 0:
            self.local_refs.pop(h, None)
        else:
            self.local_refs[h] = n
        return n

    # ---- borrows we hold at remote owners ----------------------------

    def note_borrow(self, h: str, owner_addr: Tuple[str, int]) -> None:
        transition("ref.borrow", h, "borrow", None, 1,
                   detail=f"owner={owner_addr[0]}:{owner_addr[1]}")
        self.borrowed[h] = tuple(owner_addr)

    def drop_borrow(self, h: str, *,
                    event: str = "borrow_release"
                    ) -> Optional[Tuple[str, int]]:
        addr = self.borrowed.pop(h, None)
        if addr is not None:
            transition("ref.borrow", h, event, 1, 0)
        return addr

    # ---- arg pins (and the borrower registrations behind some) -------

    def pin_arg(self, h: str, n: int = 1, *,
                event: str = "pin_arg") -> int:
        new = self.arg_pins.get(h, 0) + n
        transition("ref.pin", h, event, new - n, new)
        self.arg_pins[h] = new
        return new

    def unpin_arg(self, h: str, n: int = 1, *, strict: bool = True,
                  event: str = "unpin_arg") -> int:
        new = transition("ref.pin", h, event, self.arg_pins.get(h, 0),
                         self.arg_pins.get(h, 0) - n, strict=strict)
        if new <= 0:
            self.arg_pins.pop(h, None)
        else:
            self.arg_pins[h] = new
        return new

    def add_borrower(self, h: str, addr: Tuple[str, int]) -> int:
        """Register one borrower pin: the borrower count AND its backing
        arg pin move together, so borrower_pins <= arg_pins holds by
        construction."""
        by = self.borrower_pins.setdefault(h, {})
        addr = tuple(addr)
        by[addr] = by.get(addr, 0) + 1
        return self.pin_arg(h, event="borrow_pin")

    def release_borrower(self, h: str,
                         addr: Tuple[str, int]) -> Optional[int]:
        """Release one borrower pin. Returns the new arg-pin count when
        the borrower actually held one here, None when unmatched — a
        duplicate/late remote release must NOT decrement a pin some
        other claimant legitimately holds (that was the double-free
        class ADVICE r5 found)."""
        by = self.borrower_pins.get(h)
        addr = tuple(addr)
        if by is None or addr not in by:
            transition("ref.pin", h, "unmatched:borrow_unpin",
                       self.arg_pins.get(h, 0),
                       self.arg_pins.get(h, 0), strict=False,
                       detail=f"borrower={addr[0]}:{addr[1]}")
            return None
        left = by[addr] - 1
        if left <= 0:
            by.pop(addr, None)
            if not by:
                self.borrower_pins.pop(h, None)
        else:
            by[addr] = left
        return self.unpin_arg(h, strict=False, event="borrow_unpin")

    def sweep_borrower(self, addr: Tuple[str, int],
                       only: Optional[List[str]] = None, *,
                       event: str = "borrower_swept"
                       ) -> List[Tuple[str, int]]:
        """Drop every pin a borrower holds — all of them (death sweep)
        or just `only` (reconciliation of oids a LIVE borrower
        disclaims); returns [(oid hex, new arg-pin count)] for the
        caller's free decisions."""
        addr = tuple(addr)
        out: List[Tuple[str, int]] = []
        for h in (list(self.borrower_pins) if only is None
                  else [h for h in only if h in self.borrower_pins]):
            by = self.borrower_pins.get(h)
            if by is None:
                continue
            count = by.pop(addr, 0)
            if not by:
                self.borrower_pins.pop(h, None)
            if count <= 0:
                continue
            out.append((h, self.unpin_arg(
                h, count, strict=False, event=event)))
        return out

    # ---- replica reader leases (local store pulls) -------------------

    def add_replica_lease(self, h: str, n: int = 1) -> int:
        new = self.replica_leases.get(h, 0) + n
        transition("ref.lease", h, "replica_lease", new - n, new)
        self.replica_leases[h] = new
        return new

    def pop_replica_leases(self, h: str) -> int:
        n = self.replica_leases.pop(h, 0)
        if n:
            transition("ref.lease", h, "replica_unlease", n, 0)
        return n

    def drain_replica_leases(self) -> Dict[str, int]:
        out = dict(self.replica_leases)
        for h, n in out.items():
            transition("ref.lease", h, "replica_unlease", n, 0,
                       detail="shutdown drain")
        self.replica_leases.clear()
        return out

    # ---- outgoing transit-pin claims ---------------------------------

    def add_transit_out(self, h: str) -> int:
        new = self.transit_out.get(h, 0) + 1
        transition("ref.transit", h, "transit_out", new - 1, new)
        self.transit_out[h] = new
        return new

    def drop_transit_out(self, h: str) -> int:
        new = transition("ref.transit", h, "transit_out_release",
                         self.transit_out.get(h, 0),
                         self.transit_out.get(h, 0) - 1, strict=False)
        if new <= 0:
            self.transit_out.pop(h, None)
        else:
            self.transit_out[h] = new
        return new

    def claims(self, oid_hexes: List[str]) -> Dict[str, bool]:
        """Does this process still claim each object at its owner? The
        union of every structure that backs a borrower pin we hold
        remotely: borrow records, eager nested-borrow registrations,
        and in-flight outgoing transit pins. The owner's reconciliation
        sweep releases pins we disclaim (its lost-release safety net);
        claims must therefore cover every pin whose release WE will
        eventually send, or the sweep frees live objects."""
        nested: Set[str] = set()
        for entries in self.nested_borrows.values():
            for _addr, h in entries:
                nested.add(h)
        return {h: (h in self.borrowed or h in nested
                    or self.transit_out.get(h, 0) > 0)
                for h in oid_hexes}

    # ---- nested borrows + TTL transit pins ---------------------------

    def note_nested(self, outer_hex: str, entries: List[Tuple]) -> None:
        self.nested_borrows.setdefault(outer_hex, []).extend(entries)
        transition("ref.nested", outer_hex, "nested_borrow",
                   None, len(entries))

    def pop_nested(self, outer_hex: str) -> Optional[List[Tuple]]:
        out = self.nested_borrows.pop(outer_hex, None)
        if out:
            transition("ref.nested", outer_hex, "nested_release",
                       len(out), 0)
        return out

    def add_ttl_pins(self, deadline: float, local: List[str],
                     remote_keys: List[Tuple]) -> None:
        self.ttl_pins.append((deadline, local, remote_keys))
        transition("ref.ttl", f"{len(local)}+{len(remote_keys)}",
                   "ttl_pin", None, len(self.ttl_pins))

    def pop_due_ttl(self, now: float) -> List[Tuple]:
        due = [p for p in self.ttl_pins if p[0] <= now]
        if due:
            # in place: CoreWorker aliases this list, rebinding would
            # silently fork the two views
            self.ttl_pins[:] = [p for p in self.ttl_pins if p[0] > now]
            transition("ref.ttl", f"{len(due)} handles", "ttl_expire",
                       len(self.ttl_pins) + len(due), len(self.ttl_pins))
        return due

    # ---- query -------------------------------------------------------

    def describe(self, h: str) -> Dict[str, Any]:
        return {
            "object_id": h,
            "loc": self.loc_tag(h),
            "local_refs": self.local_refs.get(h, 0),
            "arg_pins": self.arg_pins.get(h, 0),
            "borrower_pins": {
                f"{a[0]}:{a[1]}": n
                for a, n in self.borrower_pins.get(h, {}).items()},
            "borrowed_from": (list(self.borrowed[h])
                              if h in self.borrowed else None),
            "replica_leases": self.replica_leases.get(h, 0),
            "nested_borrows": len(self.nested_borrows.get(h, ())),
        }

    def live_objects(self, cap: int = 512) -> List[Dict[str, Any]]:
        """Objects with any live claim (counts > 0) or a non-terminal
        location — the set an operator asks about."""
        keys: Set[str] = (set(self.local_refs) | set(self.arg_pins)
                          | set(self.borrower_pins)
                          | set(self.replica_leases)
                          | set(self.borrowed))
        keys |= {h for h, loc in self.objects.items()
                 if loc[0] == PENDING}
        out = [self.describe(h) for h in itertools.islice(keys, cap)]
        out.sort(key=lambda r: r["object_id"])
        return out


# ---------------------------------------------------------------------
# LeaseState: owner-side per-scheduling-key machine
# ---------------------------------------------------------------------


@dataclass
class LeaseState:
    """Owner-side per-scheduling-key submission state (reference
    direct_task_transport.cc SchedulingKey): tasks of one shape share a
    queue, lease request slots cover the backlog, and leased workers
    are reused back-to-back while the queue has work. Mutated ONLY via
    LeaseTable methods (RT018); the queue itself is plain FIFO plumbing
    and stays directly accessible."""

    key_hex: str
    queue: "collections.deque" = field(default_factory=collections.deque)
    # outstanding lease requests; every slot is either parked at an NM
    # awaiting an async grant or actively driving the request loop
    requests_in_flight: int = 0
    # per-NM parked counts; signed (a grant can outrace its request's
    # "queued" reply, dipping one bucket to -1 until the reply lands)
    # and clamped at read
    parked_at: Dict[Tuple[str, int], int] = field(default_factory=dict)
    # lease_id -> (worker_address, nm_address, node_id_hex)
    leases: Dict[str, Tuple] = field(default_factory=dict)
    # lease_id -> tasks pushed but not yet completed (pipeline depth)
    lease_inflight: Dict[str, int] = field(default_factory=dict)


class LeaseTable:
    """All LeaseState machines of one process + the lease -> running
    task-hex map (worker-death reports fail exactly these under lease
    reuse + pipelining). Callers hold CoreWorker._lock."""

    def __init__(self):
        self.keys: Dict[Any, LeaseState] = {}
        # lease_id -> set of task hexes pushed-but-incomplete
        self.running: Dict[str, Set[str]] = {}
        # recently processed grant ids: grant delivery is at-least-once
        # (the NM re-queues a lease whose reply failed transiently), and
        # a duplicate grant must not release a second request slot or
        # unpark a second bucket — bounded ring + set for O(1) dedup
        self._grant_ring: "collections.deque" = collections.deque(
            maxlen=512)
        self._grant_seen: Set[str] = set()

    def note_grant(self, lease_id: str) -> bool:
        """Record a grant delivery; False when this lease id was already
        processed (the caller hands the duplicate lease straight back)."""
        if lease_id in self._grant_seen:
            transition("lease.held", lease_id, "grant_duplicate",
                       "held", "held")
            return False
        if len(self._grant_ring) == self._grant_ring.maxlen:
            self._grant_seen.discard(self._grant_ring[0])
        self._grant_ring.append(lease_id)
        self._grant_seen.add(lease_id)
        return True

    def state(self, key: Any) -> LeaseState:
        ks = self.keys.get(key)
        if ks is None:
            # scheduling keys are arbitrary hashables (tuples of
            # resource shape / runtime env / strategy); ring records
            # need a short stable label
            import hashlib
            label = hashlib.sha1(repr(key).encode()).hexdigest()[:12]
            ks = self.keys[key] = LeaseState(key_hex=label)
        return ks

    def get(self, key: Any) -> Optional[LeaseState]:
        return self.keys.get(key) if key is not None else None

    # ---- request slots -----------------------------------------------

    def claim_slot(self, ks: LeaseState) -> int:
        ks.requests_in_flight = transition(
            "lease.slot", ks.key_hex, "slot_claim",
            ks.requests_in_flight, ks.requests_in_flight + 1)
        return ks.requests_in_flight

    def release_slot(self, ks: LeaseState, *, event: str = "slot_release",
                     strict: bool = False) -> bool:
        """Release one request slot. Non-strict by default: several
        paths legitimately race to settle the same slot (grant vs.
        drained-queue vs. node death) and the loser must not blow up —
        but every unmatched release is recorded, so a systematic
        double-release shows up in the anomaly counts."""
        if ks.requests_in_flight <= 0:
            transition("lease.slot", ks.key_hex, f"unmatched:{event}",
                       0, 0, strict=False)
            if strict:
                raise OwnershipError(
                    f"lease slot double-release on key {ks.key_hex}")
            return False
        ks.requests_in_flight = transition(
            "lease.slot", ks.key_hex, event,
            ks.requests_in_flight, ks.requests_in_flight - 1)
        return True

    def reset_slots(self, ks: LeaseState, *, event: str) -> int:
        """Node-death recovery: zero the slot count outright (the
        requests died with the NM; over-counting self-heals — surplus
        grants with an empty queue hand their lease straight back)."""
        n, ks.requests_in_flight = ks.requests_in_flight, 0
        if n:
            transition("lease.slot", ks.key_hex, event, n, 0)
        return n

    def release_slots(self, ks: LeaseState, n: int, *,
                      event: str) -> int:
        """Release up to n slots (dead-NM parked sweep), floored at 0."""
        take = min(n, ks.requests_in_flight)
        if take > 0:
            ks.requests_in_flight = transition(
                "lease.slot", ks.key_hex, event,
                ks.requests_in_flight, ks.requests_in_flight - take)
        return take

    # ---- parked accounting -------------------------------------------

    def park(self, ks: LeaseState,
             addr: Optional[Tuple[str, int]]) -> int:
        addr = tuple(addr) if addr else None
        new = ks.parked_at.get(addr, 0) + 1
        # signed by design: may rebalance a grant that outraced the
        # "queued" reply (bucket at -1 -> 0)
        transition("lease.park", ks.key_hex, "park",
                   new - 1, new, signed=True, detail=f"nm={addr}")
        ks.parked_at[addr] = new
        return new

    def unpark(self, ks: LeaseState,
               addr: Optional[Tuple[str, int]]) -> int:
        addr = tuple(addr) if addr else None
        new = ks.parked_at.get(addr, 0) - 1
        transition("lease.park", ks.key_hex, "unpark",
                   new + 1, new, signed=True, detail=f"nm={addr}")
        ks.parked_at[addr] = new
        return new

    def drop_parked(self, ks: LeaseState,
                    addr: Optional[Tuple[str, int]]) -> int:
        """Discard one NM's parked bucket (node death); returns the
        bucket's (possibly negative, clamped) count."""
        addr = tuple(addr) if addr else None
        n = ks.parked_at.pop(addr, 0)
        if n:
            transition("lease.park", ks.key_hex, "drop_parked",
                       n, 0, strict=False, detail=f"nm={addr}")
        return n

    # ---- leases + pipeline depth -------------------------------------

    def add_lease(self, ks: LeaseState, lease_id: str,
                  info: Tuple) -> None:
        transition("lease.held", lease_id, "grant",
                   None, "held", detail=f"key={ks.key_hex}")
        ks.leases[lease_id] = info

    def drop_lease(self, ks: LeaseState, lease_id: str) -> bool:
        had = ks.leases.pop(lease_id, None) is not None
        ks.lease_inflight.pop(lease_id, None)
        if had:
            transition("lease.held", lease_id, "drop", "held", None)
        return had

    def incr_inflight(self, ks: LeaseState, lease_id: str,
                      task_hex: str) -> int:
        new = ks.lease_inflight.get(lease_id, 0) + 1
        transition("lease.inflight", lease_id, "push", new - 1, new,
                   detail=f"task={task_hex[:16]}")
        ks.lease_inflight[lease_id] = new
        self.running.setdefault(lease_id, set()).add(task_hex)
        return new

    def settle_inflight(self, ks: Optional[LeaseState], lease_id: str,
                        task_hex: Optional[str]) -> None:
        """One pushed task finished (or was superseded): drop it from
        the running set and free its pipeline slot. Tolerant of
        duplicate settles (late completion after a failure report) —
        recorded, never negative."""
        on_lease = self.running.get(lease_id)
        if on_lease is not None and task_hex is not None:
            on_lease.discard(task_hex)
            if not on_lease:
                self.running.pop(lease_id, None)
        if ks is None or lease_id not in ks.lease_inflight:
            return
        old = ks.lease_inflight[lease_id]
        if old <= 0:
            # already settled: duplicate completion report (the report
            # path is at-least-once by design) — visible in the ring,
            # not an anomaly
            transition("lease.inflight", lease_id, "settle_noop",
                       old, 0, detail=f"task={(task_hex or '?')[:16]}")
            return
        new = transition("lease.inflight", lease_id, "settle",
                         old, old - 1,
                         detail=f"task={(task_hex or '?')[:16]}")
        ks.lease_inflight[lease_id] = new

    def drop_running_task(self, lease_id: str, task_hex: str) -> None:
        on_lease = self.running.get(lease_id)
        if on_lease is not None:
            on_lease.discard(task_hex)
            if not on_lease:
                self.running.pop(lease_id, None)

    def pop_running(self, lease_id: str) -> Optional[Set[str]]:
        out = self.running.pop(lease_id, None)
        if out:
            transition("lease.held", lease_id, "fail_running",
                       len(out), 0,
                       detail=",".join(sorted(h[:12] for h in out)))
        return out

    # ---- query -------------------------------------------------------

    def summary(self) -> List[Dict[str, Any]]:
        out = []
        for key, ks in self.keys.items():
            out.append({
                "key": ks.key_hex,
                "queued": len(ks.queue),
                "requests_in_flight": ks.requests_in_flight,
                "parked": sum(max(0, n) for n in ks.parked_at.values()),
                "leases": len(ks.leases),
                "inflight": dict(ks.lease_inflight),
            })
        return out


def lease_drain_report(lease_table: LeaseTable) -> List[str]:
    """Post-quiesce leak report over one process's lease machines: with
    no work outstanding, every request slot, pipeline depth and running
    set must be zero — a nonzero survivor is the ADVICE-r5 stall-leak
    class. Caller holds the owning CoreWorker's lock. Used by the
    fuzz harness's drain phase and the test suites' teardown canary."""
    out: List[str] = []
    for ks in lease_table.keys.values():
        if ks.queue:
            out.append(f"key {ks.key_hex}: {len(ks.queue)} task(s) "
                       f"still queued")
        if ks.requests_in_flight:
            out.append(f"key {ks.key_hex}: {ks.requests_in_flight} "
                       f"lease request slot(s) leaked")
        inflight = {lid: n for lid, n in ks.lease_inflight.items() if n}
        if inflight:
            out.append(f"key {ks.key_hex}: pipeline depth not "
                       f"drained: {inflight}")
    if lease_table.running:
        out.append(f"{len(lease_table.running)} lease(s) still marked "
                   f"running: {sorted(lease_table.running)}")
    return out


# ---------------------------------------------------------------------
# Store-side ledger: reader leases on shared-memory entries
# ---------------------------------------------------------------------


def store_lease(entry: Any, oid: str, n: int = 1) -> int:
    """Take n reader leases on a store entry (zero-copy views stay
    valid while held). Caller holds StoreServer._lock."""
    old = entry.leases
    entry.leases = transition("store.lease", oid, "lease", old, old + n)
    return entry.leases


def store_unlease(entry: Any, oid: str, n: int = 1) -> int:
    """Release up to n reader leases; over-release clamps at zero and
    is recorded (a SIGKILLed reader's leases are reaped by store
    teardown, so its peer's late unpin can legitimately overshoot)."""
    old = entry.leases
    entry.leases = transition("store.lease", oid, "unlease",
                              old, old - n, strict=False)
    return entry.leases


# ---------------------------------------------------------------------
# Node-manager lease ledger
# ---------------------------------------------------------------------


class NMLeases:
    """lease id -> worker id hex, mutated only through grant/release so
    every NM-side lease transition hits the ring. Read access mirrors
    the dict surface node_manager uses."""

    def __init__(self):
        self._m: Dict[str, str] = {}

    def grant(self, lease_id: str, worker_hex: str) -> None:
        transition("nm.lease", lease_id, "grant", None, "leased",
                   detail=f"worker={worker_hex[:12]}")
        self._m[lease_id] = worker_hex

    def release(self, lease_id: str, *,
                event: str = "return") -> Optional[str]:
        wid = self._m.pop(lease_id, None)
        if wid is not None:
            transition("nm.lease", lease_id, event, "leased", None,
                       detail=f"worker={wid[:12]}")
        return wid

    def get(self, lease_id: str) -> Optional[str]:
        return self._m.get(lease_id)

    def __contains__(self, lease_id: str) -> bool:
        return lease_id in self._m

    def __len__(self) -> int:
        return len(self._m)

    def items(self):
        return self._m.items()
