"""Global Control Store: cluster control plane.

reference parity: src/ray/gcs/gcs_server/ — GcsServer (gcs_server.h:78) with
node membership (GcsNodeManager), actor directory + scheduling
(GcsActorManager/GcsActorScheduler), internal KV (GcsInternalKVManager),
function table (GcsFunctionManager), pub/sub (GcsPublisher), health checks
(GcsHealthCheckManager) and job accounting (GcsJobManager). Storage is an
in-process dict behind a small StoreClient-like interface so a persistent
backend can be swapped in (reference gcs_table_storage.h).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import rpc as rpc_lib
from ray_tpu._private.ids import ActorID, JobID, NodeID, WorkerID
from ray_tpu._private.state import (ActorInfo, NodeInfo, PlacementGroupInfo,
                                    ResourceSet, TaskSpec)
from ray_tpu.util.locks import TracedLock

logger = logging.getLogger(__name__)


class InMemoryStore:
    """Pluggable table storage (reference in_memory_store_client.h)."""

    def __init__(self) -> None:
        self._tables: Dict[str, Dict[str, Any]] = {}
        self._lock = TracedLock("gcs_store")

    def put(self, table: str, key: str, value: Any) -> None:
        with self._lock:
            self._tables.setdefault(table, {})[key] = value
            self._on_mutate_locked()

    def get(self, table: str, key: str) -> Any:
        with self._lock:
            return self._tables.get(table, {}).get(key)

    def delete(self, table: str, key: str) -> bool:
        with self._lock:
            hit = self._tables.get(table, {}).pop(key, None) is not None
            if hit:
                self._on_mutate_locked()
            return hit

    def keys(self, table: str, prefix: str = "") -> List[str]:
        with self._lock:
            return [k for k in self._tables.get(table, {}) if k.startswith(prefix)]

    def items(self, table: str) -> List[Tuple[str, Any]]:
        with self._lock:
            return list(self._tables.get(table, {}).items())

    def _on_mutate_locked(self) -> None:
        pass


class PersistentStore(InMemoryStore):
    """File-backed table storage (reference redis_store_client.h role:
    GCS state survives a control-plane restart — gcs_table_storage.h:242,
    reloaded like GcsInitData on boot). Snapshots the tables atomically
    on mutation, debounced to one write per DEBOUNCE_S."""

    DEBOUNCE_S = 0.2

    def __init__(self, path: str) -> None:
        super().__init__()
        self.path = path
        self._dirty = False
        self._flush_lock = TracedLock("gcs_flush")
        if os.path.exists(path):
            import pickle as _pickle
            try:
                with open(path, "rb") as f:
                    self._tables = _pickle.load(f)
            except Exception:  # noqa: BLE001 - corrupt snapshot must not
                # brick the control plane; set it aside and start fresh
                corrupt = f"{path}.corrupt"
                logger.error("GCS snapshot %s unreadable; moving to %s "
                             "and starting empty", path, corrupt)
                try:
                    os.replace(path, corrupt)
                except OSError:
                    pass
        self._flusher = threading.Thread(target=self._flush_loop,
                                         daemon=True, name="gcs-persist")
        self._stopped = False
        self._flusher.start()

    def _on_mutate_locked(self) -> None:
        self._dirty = True

    def flush(self) -> None:
        import pickle as _pickle
        # _flush_lock serializes writers (flusher thread vs stop()): both
        # use the same tmp path, and interleaved writes would install a
        # corrupt snapshot.
        with self._flush_lock:
            with self._lock:
                if not self._dirty:
                    return
                blob = _pickle.dumps(self._tables)
                self._dirty = False
            tmp = f"{self.path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, self.path)

    def _flush_loop(self) -> None:
        while not self._stopped:
            time.sleep(self.DEBOUNCE_S)
            try:
                self.flush()
            except Exception:  # noqa: BLE001
                logger.exception("GCS persistence flush failed")

    def stop(self) -> None:
        self._stopped = True
        self.flush()


class GcsServer:
    """The control-plane process (can be hosted in a thread or standalone)."""

    # Class-level defaults; __init__ reads the live values from Config so
    # operators can tune them per-cluster (reference
    # gcs_health_check_manager.h: health_check_period_ms +
    # health_check_failure_threshold). K consecutive probe failures are
    # required before a node is declared dead — a single chaos-delayed or
    # GC-paused probe must not kill a healthy node.
    HEALTH_CHECK_PERIOD_S = 2.0
    HEALTH_CHECK_FAILURES_TO_DEAD = 3

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 persist_path: Optional[str] = None):
        from ray_tpu._private.config import Config
        self.HEALTH_CHECK_PERIOD_S = Config.health_check_period_s
        self.HEALTH_CHECK_FAILURES_TO_DEAD = max(
            1, Config.health_check_failure_threshold)
        # Pluggable storage (reference StoreClient): file-backed when a
        # persist path is given (env RAY_TPU_GCS_PERSIST_PATH works too),
        # so KV state — function table, job metadata, checkpoint pointers
        # — survives a GCS restart.
        persist_path = persist_path or os.environ.get(
            "RAY_TPU_GCS_PERSIST_PATH")
        self._persist_path = persist_path
        self.store = PersistentStore(persist_path) if persist_path \
            else InMemoryStore()
        self._pool = rpc_lib.ClientPool(timeout=30)
        self._lock = TracedLock("gcs")
        # node_id hex -> NodeInfo
        self.nodes: Dict[str, NodeInfo] = {}
        # node_id hex -> {resource: available} (synced by node managers)
        self.node_available: Dict[str, Dict[str, float]] = {}
        # last accepted resource-report version per node (syncer-style
        # out-of-order protection)
        self.node_resource_version: Dict[str, int] = {}
        self.node_health_failures: Dict[str, int] = {}
        # actor_id hex -> ActorInfo ; actor specs kept for restart
        self.actors: Dict[str, ActorInfo] = {}
        self.actor_specs: Dict[str, TaskSpec] = {}
        self.named_actors: Dict[Tuple[str, str], str] = {}  # (ns, name)->id hex
        # channel -> [(subscriber rpc address, token)]
        self.subscribers: Dict[str, List[Tuple[Tuple[str, int], str]]] = {}
        self.job_counter = 0
        # pg_id hex -> PlacementGroupInfo
        self.placement_groups: Dict[str, "PlacementGroupInfo"] = {}
        # Task event sink (reference GcsTaskManager, gcs_task_manager.h:85):
        # merged task records keyed by task id, FIFO-capped.
        self.task_events: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        self.task_events_dropped = 0
        # Structured cluster events (reference util/event.h → the
        # dashboard event module): bounded ring of lifecycle records.
        self.cluster_events: List[Dict[str, Any]] = []
        self.CLUSTER_EVENTS_MAX = 4096
        # autoscaler v2 lifecycle plane: latest instance table + a
        # bounded ring of lifecycle transitions (autoscaler/v2.py
        # reports both each pass)
        self.autoscaler_instances: List[Dict[str, Any]] = []
        self.autoscaler_events: List[Dict[str, Any]] = []
        self.AUTOSCALER_EVENTS_MAX = 1024
        # Actor waits-for graph (blocking gets between actors) with
        # cycle-at-insert deadlock detection; see _private/wait_graph.py.
        from ray_tpu._private.wait_graph import WaitGraph
        self.wait_graph = WaitGraph()
        # Crash postmortems (debug plane): bounded ring of black-box
        # bundles keyed by postmortem id (node managers report worker
        # deaths, executors report task failures; see
        # node_manager._capture_postmortem / log_plane.py).
        self.postmortems: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        from ray_tpu._private.config import Config as _Config
        self.POSTMORTEMS_MAX = max(1, _Config.postmortems_max)
        # Chaos plane (see _private/chaos.py): ordered rule list + the
        # cluster-wide fired-count aggregate, distributed over pubsub.
        self.chaos_rules: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        self.chaos_fired: Dict[str, int] = {}
        self.chaos_version = 0
        self._chaos_rule_counter = 0
        # Gang heartbeat table (train/heartbeat.py): gang -> rank ->
        # last beat, stamped with GCS-side monotonic receipt time so
        # age needs no cross-host clock agreement. FIFO-capped on gangs;
        # supervisors clear their gang on teardown.
        self.gang_heartbeats_tbl: "OrderedDict[str, Dict[int, Dict[str, Any]]]" = \
            OrderedDict()
        self.GANG_HEARTBEAT_GANGS_MAX = 64
        self._dead = False

        # Reload the persisted actor directory (reference GcsInitData:
        # on restart the GCS rebuilds state from storage; nodes instead
        # RE-REGISTER via the resource-report loop — see
        # report_resources returning "unknown_node").
        for key in self.store.keys("actors", ""):
            rec = self.store.get("actors", key)
            if rec:
                info, spec = rec
                if info.state in ("PENDING", "RESTARTING"):
                    # its scheduling thread died with the old process
                    info.state = "DEAD"
                    info.death_cause = "GCS restarted mid-scheduling"
                self.actors[key] = info
                if spec is not None:
                    self.actor_specs[key] = spec
        # drop names whose actor record never made it to the snapshot
        # (crash between the two writes) — a name pointing at a missing
        # record would brick every lookup of that name
        self.named_actors.update(
            {k: v for k, v in
             (self.store.get("meta", "named_actors") or {}).items()
             if v in self.actors})

        self.server = rpc_lib.RpcServer({
            # KV (reference InternalKVGcsService)
            "kv_put": self.kv_put,
            "kv_get": self.kv_get,
            "kv_del": self.kv_del,
            "kv_keys": self.kv_keys,
            "kv_exists": self.kv_exists,
            # nodes (reference NodeInfoGcsService / NodeResourceInfoGcsService)
            "register_node": self.register_node,
            "unregister_node": self.unregister_node,
            "get_all_nodes": self.get_all_nodes,
            "report_resources": self.report_resources,
            "get_cluster_resources": self.get_cluster_resources,
            # jobs
            "next_job_id": self.next_job_id,
            # actors (reference ActorInfoGcsService)
            "register_actor": self.register_actor,
            "get_actor_info": self.get_actor_info,
            "get_named_actor": self.get_named_actor,
            "list_named_actors": self.list_named_actors,
            "report_actor_alive": self.report_actor_alive,
            "report_actor_death": self.report_actor_death,
            "kill_actor": self.kill_actor,
            "list_actors": self.list_actors,
            # placement groups (reference PlacementGroupInfoGcsService)
            "create_placement_group": self.create_placement_group,
            "remove_placement_group": self.remove_placement_group,
            "get_placement_group": self.get_placement_group,
            "list_placement_groups": self.list_placement_groups,
            # task events (reference TaskInfoGcsService / GcsTaskManager)
            "add_task_events": self.add_task_events,
            "list_tasks": self.list_tasks,
            # flight recorder: cluster-wide span-ring gather
            # (`ray_tpu timeline --spans`, dashboard /api/timeline?spans=1)
            "spans_collect": self.spans_collect,
            # profiling plane: cluster flamegraph collect (`ray_tpu
            # profile`, dashboard /api/profile; _private/profiler.py)
            "profile_collect": self.profile_collect,
            # memory attribution plane: cluster object table (`ray_tpu
            # memory`, dashboard /api/memory; _private/memory_plane.py)
            "memory_collect": self.memory_collect,
            # lockdep plane: traced-lock snapshots + order graphs
            # (`ray_tpu locks`, dashboard /api/locks; util/locks.py)
            "locks_collect": self.locks_collect,
            # ownership protocol plane: RefState/LeaseState + transition
            # rings (`ray_tpu ownership`, dashboard /api/ownership;
            # _private/ownership.py)
            "ownership_collect": self.ownership_collect,
            # debug plane: attributed-log fan-out + crash postmortems
            # (`ray_tpu logs`, dashboard /api/logs + /api/postmortems)
            "logs_query": self.logs_query,
            "postmortem_report": self.postmortem_report,
            "postmortem_list": self.postmortem_list,
            "postmortem_get": self.postmortem_get,
            # structured events (reference ReportEventService)
            "add_events": self.add_events,
            "list_events": self.list_events,
            # autoscaler v2 (autoscaler/v2.py): lifecycle-event +
            # instance-table report, served back to `ray_tpu
            # autoscaler` / util.state.autoscaler_instances() /
            # /api/autoscaler; each event also lands in the cluster
            # event log and on the "autoscaler_lifecycle" pubsub
            # channel (elastic trainers subscribe for membership
            # changes)
            "autoscaler_v2_report": self.autoscaler_v2_report,
            "autoscaler_v2_state": self.autoscaler_v2_state,
            # actor waits-for graph (deadlock detection)
            "wait_graph_add": self.wait_graph_add,
            "wait_graph_remove": self.wait_graph_remove,
            "wait_graph_snapshot": self.wait_graph_snapshot,
            # gang heartbeat plane (train/heartbeat.py): rank sidecars
            # beat in (oneway), gang supervisors poll ages + the
            # runtime step-deadline override, and clear on teardown
            "gang_heartbeat": self.gang_heartbeat,
            "gang_heartbeats": self.gang_heartbeats,
            "gang_heartbeat_clear": self.gang_heartbeat_clear,
            # chaos plane (_private/chaos.py)
            "chaos_inject": self.chaos_inject,
            "chaos_clear": self.chaos_clear,
            "chaos_list": self.chaos_list,
            "chaos_get_policy": self.chaos_get_policy,
            "chaos_report_fired": self.chaos_report_fired,
            # pubsub (reference InternalPubSubGcsService)
            "subscribe": self.subscribe,
            "unsubscribe": self.unsubscribe,
            "publish": self.publish,
            "ping": lambda: "pong",
        }, host=host, port=port)
        self.address = self.server.address
        # standalone GCS processes get a trace row; in-process head nodes
        # are relabeled by the driver's CoreWorker (one process, one row)
        from ray_tpu._private import spans as spans_lib
        spans_lib.set_process_label("gcs")
        # Cluster metrics plane (_private/metrics_plane.py): harvest
        # sampler + history ring + invariant watchdog, with its RPC
        # surface registered post-construction (the plane needs the live
        # node/subscriber tables this object owns).
        from ray_tpu._private import metrics_plane as metrics_plane_lib
        metrics_plane_lib.register_sampler("gcs",
                                           self._sample_metric_gauges)
        # Durable history segments live next to the KV snapshot when
        # the GCS persists (a restart replays both); explicit
        # Config.metrics_history_dir overrides inside the plane.
        hist_dir = None
        if self._persist_path:
            hist_dir = self._persist_path + ".metrics"
        self.metrics_plane = metrics_plane_lib.MetricsPlane(
            self, history_dir=hist_dir)
        self.server.register("metrics_collect", self.metrics_plane.collect)
        self.server.register("metrics_prometheus",
                             self.metrics_plane.prometheus)
        self.server.register("metrics_merged", self.metrics_plane.merged)
        self.server.register("metrics_history",
                             self.metrics_plane.query_history)
        self.server.register("metrics_history_range",
                             self.metrics_plane.query_history_range)
        self.server.register("metrics_configure",
                             self.metrics_plane.configure)
        self._health_thread = threading.Thread(
            target=self._health_check_loop, daemon=True, name="gcs-health")
        self._health_thread.start()

    def _sample_metric_gauges(self) -> None:
        """GCS-owned gauges for the metrics harvest. The wait-graph
        gauges used to be mirrored into the dashboard head's registry
        per scrape (_refresh_wait_graph_metrics); exporting them here
        keeps the Grafana exprs (`ray_tpu_wait_graph_edges`,
        `ray_tpu_deadlocks_detected`) alive on the merged endpoint
        natively."""
        from ray_tpu.util.metrics import Gauge, get_or_create
        snap = self.wait_graph.snapshot()
        get_or_create(
            Gauge, "ray_tpu_wait_graph_edges",
            description="live actor waits-for edges (blocking gets)"
        ).set(float(len(snap["edges"])))
        get_or_create(
            Gauge, "ray_tpu_deadlocks_detected",
            description="waits-for cycles detected since cluster start"
        ).set(float(snap["deadlocks_detected"]))
        get_or_create(
            Gauge, "ray_tpu_wait_graph_max_edge_age_seconds",
            description="age of the oldest live actor wait edge "
                        "(watchdog stuck-wait probe input)"
        ).set(float(snap["max_edge_age_s"]))
        with self._lock:
            alive = sum(1 for n in self.nodes.values() if n.alive)
        get_or_create(
            Gauge, "ray_tpu_alive_nodes",
            description="nodes the GCS currently considers alive"
        ).set(float(alive))
        self._sample_gang_heartbeat_gauge()

    # ---- KV --------------------------------------------------------------

    def kv_put(self, key: str, value: bytes, overwrite: bool = True) -> bool:
        if not overwrite and self.store.get("kv", key) is not None:
            return False
        self.store.put("kv", key, value)
        return True

    def kv_get(self, key: str) -> Optional[bytes]:
        return self.store.get("kv", key)

    def kv_del(self, key: str) -> bool:
        return self.store.delete("kv", key)

    def kv_keys(self, prefix: str = "") -> List[str]:
        return self.store.keys("kv", prefix)

    def kv_exists(self, key: str) -> bool:
        return self.store.get("kv", key) is not None

    # ---- nodes -----------------------------------------------------------

    def register_node(self, info: NodeInfo) -> None:
        with self._lock:
            hex_id = info.node_id.hex()
            prev = self.nodes.get(hex_id)
            self.nodes[hex_id] = info
            # a RE-register of a live node (idempotent retry / blip
            # recovery) must not clobber its real availability with the
            # full total — busy nodes would look free until the next
            # report tick
            if prev is None or not prev.alive or \
                    hex_id not in self.node_available:
                self.node_available[hex_id] = dict(info.resources_total)
        self.publish("node", ("ALIVE", info))

    def unregister_node(self, node_id_hex: str) -> None:
        self._mark_node_dead(node_id_hex, "unregistered")

    def _mark_node_dead(self, node_id_hex: str, reason: str) -> None:
        with self._lock:
            info = self.nodes.get(node_id_hex)
            if info is None or not info.alive:
                return
            info.alive = False
            self.node_available.pop(node_id_hex, None)
            dead_actors = [a for a in self.actors.values()
                           if a.node_id and a.node_id.hex() == node_id_hex
                           and a.state in ("ALIVE", "PENDING", "RESTARTING")]
        log = logger.info if reason == "unregistered" else logger.warning
        log("GCS: node %s dead (%s)", node_id_hex[:12], reason)
        self._emit("NODE_DEAD", reason,
                   severity="INFO" if reason == "unregistered"
                   else "WARNING", node_id=node_id_hex)
        self.publish("node", ("DEAD", info))
        for a in dead_actors:
            self.report_actor_death(a.actor_id.hex(),
                                    f"node {node_id_hex[:12]} died", restart=True)

    def get_all_nodes(self) -> List[NodeInfo]:
        with self._lock:
            return list(self.nodes.values())

    def report_resources(self, node_id_hex: str,
                         available: Dict[str, float],
                         version: int = 0) -> str:
        with self._lock:
            if node_id_hex in self.nodes and self.nodes[node_id_hex].alive:
                # versioned, change-triggered reports (reference
                # RaySyncer ray_syncer.h:88): drop stale out-of-order
                # updates; version resets (node-manager restart) accept
                last = self.node_resource_version.get(node_id_hex, 0)
                if version and version < last and version > 1:
                    return "ok"  # stale in-flight report
                self.node_resource_version[node_id_hex] = version
                self.node_available[node_id_hex] = dict(available)
                self.node_health_failures[node_id_hex] = 0
                return "ok"
        # a restarted GCS (or one that declared this node dead during a
        # network blip) doesn't know the reporter: tell it to
        # re-register (reference: raylets reconnect after GCS restart,
        # NotifyGCSRestart node_manager.proto:357)
        return "unknown_node"

    def get_cluster_resources(self) -> Dict[str, Dict[str, Dict[str, float]]]:
        with self._lock:
            return {
                nid: {"total": dict(self.nodes[nid].resources_total),
                      "available": dict(avail)}
                for nid, avail in self.node_available.items()
                if self.nodes[nid].alive}

    def _health_check_loop(self) -> None:
        # reference: gcs_health_check_manager.h — active raylet health probes
        while not self._dead:
            time.sleep(self.HEALTH_CHECK_PERIOD_S)
            with self._lock:
                targets = [(nid, n.address) for nid, n in self.nodes.items()
                           if n.alive]
            for nid, addr in targets:
                try:
                    self._pool.get(addr).call("nm_ping")
                    with self._lock:
                        self.node_health_failures[nid] = 0
                except Exception:  # noqa: BLE001
                    with self._lock:
                        self.node_health_failures[nid] = \
                            self.node_health_failures.get(nid, 0) + 1
                        failures = self.node_health_failures[nid]
                    self._pool.invalidate(addr)
                    if failures >= self.HEALTH_CHECK_FAILURES_TO_DEAD:
                        self._mark_node_dead(nid, "health check failed")

    # ---- jobs ------------------------------------------------------------

    def next_job_id(self) -> JobID:
        with self._lock:
            # persisted so job ids stay unique across GCS restarts
            counter = (self.store.get("meta", "job_counter") or 0) + 1
            self.store.put("meta", "job_counter", counter)
            self.job_counter = counter
            return JobID(self.job_counter.to_bytes(4, "big"))

    # ---- actors ----------------------------------------------------------

    def _persist_actor(self, actor_id_hex: str) -> None:
        """Write one actor's directory record + the named map to the
        store so lookups survive a GCS restart (reference
        GcsActorTable, gcs_table_storage.h:48). Stores a snapshot COPY:
        the live ActorInfo keeps mutating under the GCS lock while the
        persistence flusher pickles tables under the store lock."""
        import copy
        with self._lock:
            info = self.actors.get(actor_id_hex)
            spec = self.actor_specs.get(actor_id_hex)
            named = dict(self.named_actors)
            info = copy.copy(info) if info is not None else None
        if info is not None:
            self.store.put("actors", actor_id_hex, (info, spec))
            self.store.put("meta", "named_actors", named)

    def register_actor(self, spec: TaskSpec, name: str = "",
                       namespace: str = "") -> str:
        """Register + schedule an actor creation (reference
        GcsActorManager::HandleRegisterActor + GcsActorScheduler)."""
        actor_id = spec.actor_id
        assert actor_id is not None
        key = (namespace, name)
        with self._lock:
            if name:
                existing = self.named_actors.get(key)
                existing_info = (self.actors.get(existing)
                                 if existing is not None else None)
                if existing_info is not None and \
                        existing_info.state != "DEAD":
                    raise ValueError(
                        f"actor name '{name}' already taken in ns '{namespace}'")
                self.named_actors[key] = actor_id.hex()
            self.actors[actor_id.hex()] = ActorInfo(
                actor_id=actor_id, name=name, namespace=namespace,
                class_name=spec.function_name, state="PENDING", address=None,
                node_id=None, max_restarts=spec.max_restarts)
            self.actor_specs[actor_id.hex()] = spec
        self._persist_actor(actor_id.hex())
        # Schedule asynchronously so registration returns immediately
        # (reference: GcsActorManager registers then hands to the scheduler).
        threading.Thread(target=self._schedule_actor,
                         args=(actor_id.hex(),), daemon=True).start()
        return actor_id.hex()

    def _pick_node_for(self, required: ResourceSet,
                       spec: TaskSpec) -> Optional[str]:
        from ray_tpu._private.scheduler import pick_node
        with self._lock:
            view = {nid: dict(avail) for nid, avail in self.node_available.items()
                    if self.nodes[nid].alive}
            labels = {nid: dict(self.nodes[nid].labels) for nid in view}
        return pick_node(view, required, spec.scheduling_strategy,
                         local_node_id=None, labels=labels,
                         locality_hints=spec.locality_hints)

    def _schedule_actor(self, actor_id_hex: str) -> None:
        spec = self.actor_specs[actor_id_hex]
        # PG-scheduled actors are feasible ONLY on the node holding the
        # committed bundle: match on the bundle-scoped resource names
        # (the same rewrite the target node manager checks in
        # _effective_resources) — raw resources would make every node
        # "feasible" and pin the retry loop to a node that can never
        # accept the actor.
        from ray_tpu._private.node_manager import rewrite_resources_for_pg
        from ray_tpu._private.state import PlacementGroupSchedulingStrategy
        if isinstance(spec.scheduling_strategy,
                      PlacementGroupSchedulingStrategy) and \
                spec.placement_group_id is not None:
            required = ResourceSet(rewrite_resources_for_pg(
                spec.resources, spec.placement_group_id.hex(),
                spec.placement_group_bundle_index))
        else:
            required = spec.required_resources()
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            node_id_hex = self._pick_node_for(required, spec)
            if node_id_hex is None:
                time.sleep(0.1)
                continue
            with self._lock:
                node = self.nodes.get(node_id_hex)
            if node is None or not node.alive:
                continue
            try:
                ok = self._pool.get(node.address).call(
                    "nm_schedule_actor_creation", spec=spec)
            except Exception:  # noqa: BLE001
                ok = False
            if ok:
                with self._lock:
                    info = self.actors[actor_id_hex]
                    info.node_id = node.node_id
                return
            time.sleep(0.05)
        self.report_actor_death(actor_id_hex,
                                "scheduling timed out (infeasible?)",
                                restart=False)

    def report_actor_alive(self, actor_id_hex: str,
                           address: Tuple[str, int],
                           node_id_hex: str) -> None:
        with self._lock:
            info = self.actors.get(actor_id_hex)
            if info is None:
                return
            info.state = "ALIVE"
            info.address = tuple(address)
            info.node_id = NodeID.from_hex(node_id_hex)
        self._persist_actor(actor_id_hex)
        self.publish("actor", ("ALIVE", self.actors[actor_id_hex]))

    def report_actor_death(self, actor_id_hex: str, reason: str,
                           restart: bool = True) -> None:
        with self._lock:
            info = self.actors.get(actor_id_hex)
            if info is None or info.state == "DEAD":
                return
            can_restart = (restart and
                           (info.max_restarts == -1
                            or info.num_restarts < info.max_restarts))
            # Always record the latest death reason — even when restarting —
            # so a later terminal DEAD (e.g. restart-scheduling failure with
            # a vague reason) still surfaces what originally killed the actor.
            info.death_cause = reason or info.death_cause or "(unknown cause)"
            if can_restart:
                info.state = "RESTARTING"
                info.num_restarts += 1
                info.address = None
            else:
                info.state = "DEAD"
                info.address = None
        self._persist_actor(actor_id_hex)
        # a dead actor's blocking gets died with it; waiters on it get
        # ActorDiedError through the usual path, not a stale wait edge
        self.wait_graph.drop_actor(actor_id_hex)
        if can_restart:
            logger.warning("GCS: restarting actor %s (%d/%s): %s",
                           actor_id_hex[:12], info.num_restarts,
                           info.max_restarts, reason)
            self._emit("ACTOR_RESTARTING", reason, severity="WARNING",
                       actor_id=actor_id_hex,
                       restart=info.num_restarts)
            self.publish("actor", ("RESTARTING", info))
            threading.Thread(target=self._schedule_actor,
                             args=(actor_id_hex,), daemon=True).start()
        else:
            self._emit("ACTOR_DEAD", info.death_cause, severity="INFO",
                       actor_id=actor_id_hex)
            self.publish("actor", ("DEAD", info))

    def get_actor_info(self, actor_id_hex: str) -> Optional[ActorInfo]:
        with self._lock:
            return self.actors.get(actor_id_hex)

    def get_named_actor(self, name: str, namespace: str = ""
                        ) -> Optional[ActorInfo]:
        with self._lock:
            aid = self.named_actors.get((namespace, name))
            return self.actors.get(aid) if aid else None

    def list_named_actors(self, namespace: str = "", all_namespaces: bool = False
                          ) -> List[Tuple[str, str]]:
        with self._lock:
            return [k for k, aid in self.named_actors.items()
                    if (all_namespaces or k[0] == namespace)
                    and aid in self.actors
                    and self.actors[aid].state != "DEAD"]

    def list_actors(self) -> List[ActorInfo]:
        with self._lock:
            return list(self.actors.values())

    def kill_actor(self, actor_id_hex: str, no_restart: bool = True) -> None:
        with self._lock:
            info = self.actors.get(actor_id_hex)
            addr = info.address if info else None
        if addr is not None:
            try:
                self._pool.get(addr).call("cw_kill_self")
            except Exception:  # noqa: BLE001 - death report below still lands
                pass
        self.report_actor_death(actor_id_hex, "ray.kill", restart=not no_restart)

    # ---- task events (reference GcsTaskManager) -------------------------

    TASK_EVENTS_MAX = 16384

    def add_task_events(self, events: List[Dict[str, Any]]) -> None:
        with self._lock:
            for rec in events:
                tid = rec.get("task_id")
                if not tid:
                    continue
                existing = self.task_events.get(tid)
                if existing is None:
                    self.task_events[tid] = dict(rec)
                else:
                    # Terminal states must not be clobbered by a late-arriving
                    # RUNNING delta from the executing worker's buffer.
                    if existing.get("state") in ("FINISHED", "FAILED"):
                        rec = {k: v for k, v in rec.items() if k != "state"}
                    existing.update(rec)
            while len(self.task_events) > self.TASK_EVENTS_MAX:
                self.task_events.popitem(last=False)
                self.task_events_dropped += 1

    def list_tasks(self, filters: Optional[Dict[str, Any]] = None,
                   limit: int = 10000) -> List[Dict[str, Any]]:
        with self._lock:
            records = list(self.task_events.values())
        if filters:
            records = [r for r in records
                       if all(r.get(k) == v for k, v in filters.items())]
        return records[-limit:]

    # ---- flight recorder (see _private/spans.py) ------------------------

    SPANS_COLLECT_TIMEOUT_S = 5.0

    def spans_collect(self) -> List[Dict[str, Any]]:
        """Fan a snapshot request out to every process and gather the
        span rings: this process, every node manager (which gathers its
        own workers), and every pubsub subscriber (drivers live outside
        any node manager's worker table). Each snapshot is annotated
        with `clock_offset_s` — the RPC-midpoint estimate of
        peer_wall_clock - gcs_wall_clock — so the merger can align all
        processes onto one timebase. Best-effort: unreachable processes
        just drop out of the trace."""
        from ray_tpu._private import spans as spans_lib
        own = spans_lib.snapshot()
        own["clock_offset_s"] = 0.0
        # a process can be reached twice (subscribed workers also appear
        # in their node manager's table); dedupe is by proc uid with a
        # deterministic preference: own ring (offset exactly 0), then
        # direct core-worker estimates, then NM-chained ones (two
        # estimation hops)
        direct: List[Dict[str, Any]] = []
        via_nm: List[Dict[str, Any]] = []
        # Two-phase gather shared with the metrics plane: node managers
        # first (each gathers its own workers), so the subscriber phase
        # skips every worker an NM already shipped — workers also sit in
        # `subscribers`, and pulling them directly too would transfer
        # each ring twice just to dedupe by proc uid.
        nm_replies, cw_replies, _unreachable = \
            spans_lib.gather_cluster_snapshots(
                self, "nm_spans_snapshot", "cw_spans_snapshot",
                timeout=self.SPANS_COLLECT_TIMEOUT_S, grace_s=2.0)
        for _addr, reply, t0, _t1 in nm_replies:
            # offset of the NM's wall clock vs ours; the NM already
            # stamped each of its workers relative to ITS clock. The NM
            # stamps wall_time at handler ENTRY (its own worker gather
            # can take seconds, so the usual RPC-midpoint reference
            # would be skewed by half the gather) — the reference point
            # is t0 + one-way network latency.
            # cross-process clock-offset estimation is the one place a
            # wall-clock difference is the point (monotonic clocks are
            # not comparable across processes/hosts).
            offset = reply["wall_time"] - t0
            for snap in reply["snapshots"]:
                snap["clock_offset_s"] = \
                    snap.get("clock_offset_s", 0.0) + offset
                via_nm.append(snap)
        for _addr, snap, t0, t1 in cw_replies:
            snap["clock_offset_s"] = snap["wall_time"] - (t0 + t1) / 2.0
            direct.append(snap)
        return spans_lib.dedupe_by_uid([own] + direct + via_nm)

    # ---- profiling plane (see _private/profiler.py) ---------------------

    PROFILE_COLLECT_GRACE_S = 8.0

    def profile_collect(self, duration_s: float = 5.0, hz: float = 100.0,
                        device: bool = False) -> Dict[str, Any]:
        """Cluster profile: start→sleep→snapshot on every process —
        node managers (each covers its workers one hop below) and
        pubsub-subscribed drivers — CONCURRENTLY under one overall
        deadline, so every process samples the same window and an
        unreachable node bounds, not doubles, the collect. A process
        reached twice (NM gather + direct subscriber pull) runs ONE
        sampling session (profiler.collect_local singleflight) and is
        deduped by proc uid here. The merge downstream is clock-free:
        folded-stack counts, never timestamps."""
        from ray_tpu._private import profiler as profiler_lib
        from ray_tpu._private import spans as spans_lib
        duration_s = min(120.0, max(0.05, float(duration_s)))
        own_box: List[Optional[Dict[str, Any]]] = [None]

        def _own() -> None:
            try:
                own_box[0] = profiler_lib.collect_local(duration_s, hz)
            except Exception:  # noqa: BLE001 - the control plane's own
                pass           # profile is optional in the merge

        own_thread = None
        if not device:
            # sample this process too (in-process head: GCS + NM +
            # driver share it; the singleflight collapses the sessions)
            own_thread = threading.Thread(target=_own, daemon=True,
                                          name="gcs-profile-own")
            own_thread.start()
        nm_replies, cw_replies, unreachable = \
            spans_lib.gather_cluster_snapshots(
                self, "nm_profile_collect", "cw_profile_collect",
                timeout=duration_s + self.PROFILE_COLLECT_GRACE_S,
                grace_s=2.0, concurrent=True,
                call_kwargs={"duration_s": duration_s, "hz": hz,
                             "device": device})
        profiles: List[Dict[str, Any]] = []
        for _addr, reply, _t0, _t1 in nm_replies:
            profiles.extend(reply.get("profiles", ()))
        profiles.extend(snap for _a, snap, _t0, _t1 in cw_replies)
        if own_thread is not None:
            own_thread.join(timeout=duration_s + 5.0)
        if own_box[0] is not None:
            profiles.insert(0, own_box[0])
        profiles = spans_lib.dedupe_by_uid([p for p in profiles if p])
        return {"ts": time.time(), "duration_s": duration_s, "hz": hz,
                "device": device, "profiles": profiles,
                "unreachable": unreachable}

    # ---- memory attribution plane (see _private/memory_plane.py) --------

    MEMORY_COLLECT_TIMEOUT_S = 5.0

    def memory_collect(self, max_objects: Optional[int] = None,
                       timeout: Optional[float] = None) -> Dict[str, Any]:
        """Cluster object table: every core worker's reference-table
        snapshot joined with every node's store residency under one
        overall deadline (memory_plane.build_object_table). Reply names
        the nodes that did not answer — absence of a row is only
        meaningful when coverage was complete."""
        from ray_tpu._private import memory_plane as memory_plane_lib
        from ray_tpu._private import spans as spans_lib
        t = float(timeout) if timeout else self.MEMORY_COLLECT_TIMEOUT_S
        call_kwargs = {"max_objects": max_objects} \
            if max_objects is not None else None
        nm_replies, cw_replies, unreachable = \
            spans_lib.gather_cluster_snapshots(
                self, "nm_memory_snapshot", "cw_memory_snapshot",
                timeout=t, grace_s=1.0, call_kwargs=call_kwargs)
        proc_snaps: List[Dict[str, Any]] = []
        node_snaps: List[Dict[str, Any]] = []
        for _addr, reply, _t0, _t1 in nm_replies:
            node_snaps.append(reply)
            proc_snaps.extend(reply.get("worker_snaps", ()))
        proc_snaps.extend(snap for _a, snap, _t0, _t1 in cw_replies)
        proc_snaps = spans_lib.dedupe_by_uid(proc_snaps)
        rows = memory_plane_lib.build_object_table(proc_snaps,
                                                   node_snaps)
        return {"ts": time.time(), "objects": rows,
                "procs": len(proc_snaps),
                "objects_dropped": sum(
                    int(s.get("objects_dropped") or 0)
                    for s in proc_snaps),
                "unreachable": unreachable}

    # ---- ownership protocol plane (see _private/ownership.py) -----------

    OWNERSHIP_COLLECT_TIMEOUT_S = 5.0

    def ownership_collect(self, object_id: Optional[str] = None,
                          limit: int = 200,
                          timeout: Optional[float] = None
                          ) -> Dict[str, Any]:
        """Cluster ownership gather: every process's RefState/LeaseState
        view + transition-ring tail (node managers bundle their store's
        leased/pinned entries and held NM leases; workers and drivers
        answer directly) under one overall deadline. Reply names the
        nodes that did not answer — a missing claimant is only
        meaningful when coverage was complete."""
        from ray_tpu._private import spans as spans_lib
        t = float(timeout) if timeout else self.OWNERSHIP_COLLECT_TIMEOUT_S
        kwargs: Dict[str, Any] = {"limit": limit}
        if object_id is not None:
            kwargs["object_id"] = object_id
        nm_replies, cw_replies, unreachable = \
            spans_lib.gather_cluster_snapshots(
                self, "nm_ownership_snapshot", "cw_ownership_snapshot",
                timeout=t, grace_s=1.0, call_kwargs=kwargs)
        proc_snaps: List[Dict[str, Any]] = []
        node_snaps: List[Dict[str, Any]] = []
        for _addr, reply, _t0, _t1 in nm_replies:
            node_snaps.append({k: v for k, v in reply.items()
                               if k != "worker_snaps"})
            proc_snaps.extend(reply.get("worker_snaps", ()))
        proc_snaps.extend(snap for _a, snap, _t0, _t1 in cw_replies)
        proc_snaps = spans_lib.dedupe_by_uid(proc_snaps)
        # anomaly totals dedupe by PROCESS, not snapshot: with an
        # in-process head node the NM and the driver share one
        # transition ring, and summing both snapshots would double-count
        # every event (per-uid max: the two reads race the same
        # monotonically-growing counters)
        per_uid: Dict[Any, Dict[str, int]] = {}
        for snap in proc_snaps + node_snaps:
            uid = snap.get("proc_uid")
            tgt = per_uid.setdefault(uid, {})
            for ev, n in (snap.get("anomalies") or {}).items():
                tgt[ev] = max(tgt.get(ev, 0), int(n))
        anomalies: Dict[str, int] = {}
        for counts in per_uid.values():
            for ev, n in counts.items():
                anomalies[ev] = anomalies.get(ev, 0) + n
        return {"ts": time.time(), "procs": proc_snaps,
                "nodes": node_snaps, "anomalies": anomalies,
                "unreachable": unreachable}

    # ---- lockdep plane (see ray_tpu/util/locks.py) ----------------------

    LOCKS_COLLECT_TIMEOUT_S = 5.0

    def locks_collect(self, timeout: Optional[float] = None
                      ) -> Dict[str, Any]:
        """Cluster lock-plane gather: every process's traced-lock
        snapshot (per-name hold stats + waiters + holder attribution +
        acquisition-order edge graph with any cycle) over the shared
        two-phase fan-out, under one overall deadline. Reply names the
        nodes that did not answer."""
        from ray_tpu._private import spans as spans_lib
        from ray_tpu.util import locks as locks_lib
        t = float(timeout) if timeout else self.LOCKS_COLLECT_TIMEOUT_S
        own = locks_lib.snapshot()
        nm_replies, cw_replies, unreachable = \
            spans_lib.gather_cluster_snapshots(
                self, "nm_locks_snapshot", "cw_locks_snapshot",
                timeout=t, grace_s=1.0)
        gathered: List[Dict[str, Any]] = []
        for _addr, reply, _t0, _t1 in nm_replies:
            gathered.extend(reply.get("snapshots", ()))
        gathered.extend(snap for _a, snap, _t0, _t1 in cw_replies)
        procs = spans_lib.dedupe_by_uid([own] + gathered)
        return {"ts": time.time(), "procs": procs,
                "unreachable": unreachable}

    # ---- debug plane: log fan-out + postmortems (log_plane.py) ----------

    LOGS_COLLECT_TIMEOUT_S = 5.0

    def logs_query(self, filters: Optional[Dict[str, Any]] = None,
                   tail: int = 500,
                   timeout: Optional[float] = None) -> Dict[str, Any]:
        """Cluster log query: ONE fan-out round over the same two-phase
        gather the span/metrics planes use (node managers first — each
        serves its whole node's tail index, filtered server-side — then
        remaining pubsub subscribers, i.e. drivers), all under a single
        overall deadline so an unreachable node bounds, not doubles,
        the query's worst case. Returns ts-merged records trimmed to
        `tail` plus the node ids that did not answer."""
        from ray_tpu._private import spans as spans_lib
        t = float(timeout) if timeout else self.LOGS_COLLECT_TIMEOUT_S
        nm_replies, cw_replies, unreachable = \
            spans_lib.gather_cluster_snapshots(
                self, "nm_logs_snapshot", "cw_logs_snapshot",
                timeout=t, grace_s=1.0,
                call_kwargs={"filters": filters, "tail": tail})
        records: List[Dict[str, Any]] = []
        for _addr, reply, _t0, _t1 in nm_replies:
            records.extend(reply.get("records", ()))
        seen: set = set()
        for _addr, snap, _t0, _t1 in cw_replies:
            uid = snap.get("proc_uid")
            if uid in seen:
                continue
            seen.add(uid)
            records.extend(snap.get("records", ()))
        records.sort(key=lambda r: (r.get("ts") or 0.0, r.get("seq", 0)))
        if tail:
            records = records[-int(tail):]
        return {"records": records, "unreachable": unreachable}

    def postmortem_report(self, bundle: Dict[str, Any]) -> str:
        pm_id = bundle.get("postmortem_id") or f"pm-{os.urandom(6).hex()}"
        bundle["postmortem_id"] = pm_id
        with self._lock:
            self.postmortems[pm_id] = bundle
            while len(self.postmortems) > self.POSTMORTEMS_MAX:
                self.postmortems.popitem(last=False)
        self._emit("POSTMORTEM_CAPTURED",
                   f"{bundle.get('kind', 'crash')} postmortem {pm_id}: "
                   f"{str(bundle.get('reason', ''))[:200]}",
                   severity="WARNING", postmortem_id=pm_id,
                   node_id=bundle.get("node_id"),
                   worker_id=bundle.get("worker_id"))
        return pm_id

    def postmortem_list(self, limit: int = 50) -> List[Dict[str, Any]]:
        """Newest-last summaries (without the bulky tails — fetch one
        by id for the full bundle)."""
        if limit <= 0:
            return []
        with self._lock:
            bundles = list(self.postmortems.values())[-limit:]
        return [{k: v for k, v in b.items()
                 if k not in ("log_tail", "span_tail")}
                | {"log_lines": len(b.get("log_tail") or ()),
                   "span_records": len(b.get("span_tail") or ())}
                for b in bundles]

    def postmortem_get(self, postmortem_id: str
                       ) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self.postmortems.get(postmortem_id)

    # ---- structured events (reference util/event.h sink) ----------------

    def add_events(self, events: List[Dict[str, Any]]) -> None:
        with self._lock:
            self.cluster_events.extend(events)
            overflow = len(self.cluster_events) - self.CLUSTER_EVENTS_MAX
            if overflow > 0:
                del self.cluster_events[:overflow]

    def list_events(self, event_type: Optional[str] = None,
                    severity: Optional[str] = None,
                    limit: int = 1000) -> List[Dict[str, Any]]:
        if limit <= 0:  # out[-0:] would mean "everything"
            return []
        with self._lock:
            out = list(self.cluster_events)
        if event_type:
            out = [e for e in out if e.get("event_type") == event_type]
        if severity:
            out = [e for e in out if e.get("severity") == severity]
        return out[-limit:]

    # ---- autoscaler v2 lifecycle plane (autoscaler/v2.py) ---------------

    def autoscaler_v2_report(self, instances: List[Dict[str, Any]],
                             events: List[Dict[str, Any]]) -> None:
        """One report per autoscaler pass: replace the instance table,
        append lifecycle transitions to the bounded ring, mirror each
        into the cluster event log, and push it on the
        "autoscaler_lifecycle" pubsub channel so elastic trainers can
        react to membership changes without polling."""
        with self._lock:
            self.autoscaler_instances = list(instances)
            self.autoscaler_events.extend(events)
            overflow = (len(self.autoscaler_events)
                        - self.AUTOSCALER_EVENTS_MAX)
            if overflow > 0:
                del self.autoscaler_events[:overflow]
        for evt in events:
            self._emit(
                "AUTOSCALER_INSTANCE",
                f"instance {evt.get('instance_id', '?')} "
                f"({evt.get('node_type', '?')}): "
                f"{evt.get('from', '?')} -> {evt.get('to', '?')}"
                + (f" ({evt['reason']})" if evt.get("reason") else ""),
                **{k: v for k, v in evt.items() if k != "ts"})
            self.publish("autoscaler_lifecycle", evt)

    def autoscaler_v2_state(self, limit: int = 200) -> Dict[str, Any]:
        with self._lock:
            return {"instances": list(self.autoscaler_instances),
                    "events": list(self.autoscaler_events[-limit:])}

    # ---- actor waits-for graph (deadlock detection) ---------------------

    def wait_graph_add(self, waiter_hex: str, target_hex: str,
                       token: str) -> Optional[List[Dict[str, str]]]:
        """Register a blocking-get edge. Returns None (edge recorded) or
        the cycle the edge would close, annotated with class names, in
        which case the edge is NOT recorded and the caller must raise
        DeadlockError instead of blocking. Idempotent per token (safe
        under RPC retry)."""
        cycle = self.wait_graph.add(waiter_hex, target_hex, token)
        if cycle is None:
            return None
        from ray_tpu._private.wait_graph import format_cycle
        with self._lock:
            names = {h: self.actors[h].class_name
                     for h in cycle if h in self.actors}
        self._emit("DEADLOCK_DETECTED", format_cycle(cycle, names),
                   severity="ERROR", cycle=list(cycle))
        return [{"actor_id": h, "class_name": names.get(h, "")}
                for h in cycle]

    def wait_graph_remove(self, token: str) -> None:
        self.wait_graph.remove(token)

    def wait_graph_snapshot(self) -> Dict[str, Any]:
        return self.wait_graph.snapshot()

    # ---- gang heartbeat plane (train/heartbeat.py) ----------------------

    def gang_heartbeat(self, gang: str, rank: int, step: int = 0,
                       phase: str = "", node_id: str = "",
                       pid: int = 0) -> None:
        """One rank beat (oneway from the worker sidecar). Stamped with
        THIS process's monotonic clock: age is computed at query time
        against the same clock, so no cross-host time agreement is
        needed and a paused sender reads exactly as a growing age."""
        with self._lock:
            gang_tbl = self.gang_heartbeats_tbl.get(gang)
            if gang_tbl is None:
                while len(self.gang_heartbeats_tbl) >= \
                        self.GANG_HEARTBEAT_GANGS_MAX:
                    self.gang_heartbeats_tbl.popitem(last=False)
                gang_tbl = self.gang_heartbeats_tbl[gang] = {}
            gang_tbl[int(rank)] = {
                "step": int(step), "phase": phase, "node_id": node_id,
                "pid": int(pid), "recv_mono": time.monotonic()}

    def gang_heartbeats(self, gang: str) -> Dict[str, Any]:
        """Per-rank heartbeat ages for one gang, enriched with each
        rank's NM RPC address (NodeInfo.address) so the supervisor can
        hard-kill a wedged pid without an extra lookup, plus the
        runtime step-deadline override (metrics_configure) so the
        deadline stays tunable without touching the trainer."""
        now = time.monotonic()
        with self._lock:
            ranks: Dict[int, Dict[str, Any]] = {}
            for rank, rec in (self.gang_heartbeats_tbl.get(gang)
                              or {}).items():
                node = self.nodes.get(rec["node_id"])
                ranks[rank] = {
                    "step": rec["step"], "phase": rec["phase"],
                    "node_id": rec["node_id"], "pid": rec["pid"],
                    "nm_address": list(node.address)
                    if node is not None and node.alive else None,
                    "age_s": max(0.0, now - rec["recv_mono"]),
                }
        plane = getattr(self, "metrics_plane", None)
        override = getattr(plane, "step_deadline_override_s", None)
        return {"gang": gang, "ranks": ranks,
                "step_deadline_override_s": override}

    def gang_heartbeat_clear(self, gang: str) -> bool:
        with self._lock:
            return self.gang_heartbeats_tbl.pop(gang, None) is not None

    # A row this stale is an ABANDONED formation, not a wedge: any real
    # wedge is detected and torn down by its gang supervisor within the
    # step deadline (seconds), and a clean teardown clears the rows. A
    # supervisor that died without cleanup (crashed driver, failed test
    # run) leaves rows that would otherwise read as wedged-forever to
    # the watchdog. GC'd here rather than on a timer of their own so
    # the table stays bounded on the always-on GCS.
    GANG_HEARTBEAT_ABANDON_S = 120.0

    def _gang_heartbeat_rows(self) -> List[Tuple[str, int, float]]:
        """Live (gang, rank, age_s) rows from the heartbeat table —
        shared by the harvest gauge export and the metrics plane's
        liveness tick (which must NOT wait for a harvest: a wedged
        worker stalls the fan-out by design). Rows past the abandon
        horizon are dropped, not reported."""
        now = time.monotonic()
        dropped: List[Tuple[str, int]] = []
        with self._lock:
            out = []
            for gang, tbl in list(self.gang_heartbeats_tbl.items()):
                for rank, rec in list(tbl.items()):
                    age = max(0.0, now - rec["recv_mono"])
                    if age > self.GANG_HEARTBEAT_ABANDON_S:
                        del tbl[rank]
                        dropped.append((gang, rank))
                        continue
                    out.append((gang, rank, age))
                if not tbl:
                    self.gang_heartbeats_tbl.pop(gang, None)
        for gang, rank in dropped:
            logger.info(
                "dropping abandoned gang heartbeat row %s rank %d "
                "(stale > %.0fs; its formation was torn down without "
                "a clear, or its supervisor died)", gang, rank,
                self.GANG_HEARTBEAT_ABANDON_S)
        return out

    def gang_heartbeat_age_series(self) -> Dict[str, float]:
        """The heartbeat ages as flat watchdog series keys (same
        `name{gang=...,rank=...}` shape the aggregator produces), so
        the liveness tick feeds _probe_gang_wedge the exact input the
        harvested gauge would — one probe, two cadences."""
        return {f"ray_tpu_gang_heartbeat_age_seconds"
                f"{{gang={gang},rank={rank}}}": age
                for gang, rank, age in self._gang_heartbeat_rows()}

    def _sample_gang_heartbeat_gauge(self) -> None:
        """Export ray_tpu_gang_heartbeat_age_seconds{gang,rank} on each
        harvest. Rebuild-per-sample (reset then set the live rows): the
        tag population is dynamic, and a lingering series for a cleared
        gang would read as wedged-forever to the watchdog probe."""
        from ray_tpu.util.metrics import Gauge, get_or_create
        rows = self._gang_heartbeat_rows()
        g = get_or_create(
            Gauge, "ray_tpu_gang_heartbeat_age_seconds",
            description="seconds since each gang rank's last heartbeat "
                        "(sidecar beats every ~0.5s; a growing age is a "
                        "wedged/stopped rank)",
            tag_keys=("gang", "rank"))
        g.reset()
        for gang, rank, age in rows:
            g.set(age, tags={"gang": gang, "rank": str(rank)})

    # ---- chaos plane (_private/chaos.py) --------------------------------

    def _chaos_policy_locked(self) -> Dict[str, Any]:
        return {"version": self.chaos_version,
                "rules": [dict(r) for r in self.chaos_rules.values()]}

    def _chaos_publish(self) -> None:
        """Push the policy to every subscriber AND install it into this
        process's own chaos client (the GCS's RPC server is a hook point
        too; in-process head nodes share this client with the driver)."""
        with self._lock:
            policy = self._chaos_policy_locked()
        from ray_tpu._private import chaos as chaos_lib
        chaos_lib.client().install(policy)
        self.publish("chaos", policy)

    def chaos_inject(self, rules: List[Dict[str, Any]]) -> List[str]:
        """Append rules to the policy (ordered). Fills in each rule's
        node-address map from the live node table so partition /
        node-targeted rules can match peer addresses, then distributes
        the bumped policy over pubsub."""
        from ray_tpu._private.chaos import FAULT_TYPES, ChaosRule
        with self._lock:
            node_addrs = {
                nid: [tuple(n.address), tuple(n.store_address)]
                for nid, n in self.nodes.items() if n.alive}
            ids = []
            for rec in rules:
                rule = ChaosRule.from_dict(rec)
                if rule.fault not in FAULT_TYPES:
                    raise ValueError(
                        f"unknown chaos fault {rule.fault!r} "
                        f"(one of {FAULT_TYPES})")
                if not rule.rule_id:
                    self._chaos_rule_counter += 1
                    rule.rule_id = f"cr-{self._chaos_rule_counter:04d}"
                if not rule.node_addrs:
                    rule.node_addrs = node_addrs
                self.chaos_rules[rule.rule_id] = rule.to_dict()
                self.chaos_fired.setdefault(rule.rule_id, 0)
                ids.append(rule.rule_id)
            self.chaos_version += 1
        for rid in ids:
            self._emit("CHAOS_RULE_INSTALLED",
                       f"chaos rule {rid} installed", severity="WARNING",
                       rule_id=rid,
                       fault=self.chaos_rules[rid]["fault"])
        self._chaos_publish()
        return ids

    def chaos_clear(self, rule_ids: Optional[List[str]] = None) -> int:
        with self._lock:
            doomed = list(self.chaos_rules) if rule_ids is None \
                else [r for r in rule_ids if r in self.chaos_rules]
            for rid in doomed:
                del self.chaos_rules[rid]
            if doomed:
                self.chaos_version += 1
        if doomed:
            self._chaos_publish()
        return len(doomed)

    def chaos_list(self) -> Dict[str, Any]:
        with self._lock:
            return {"version": self.chaos_version,
                    "rules": [{**dict(r), "fired": self.chaos_fired.get(
                        rid, 0)} for rid, r in self.chaos_rules.items()]}

    def chaos_get_policy(self) -> Dict[str, Any]:
        with self._lock:
            return self._chaos_policy_locked()

    def chaos_report_fired(self, rule_id: str, fault: str = "",
                           where: str = "", node_id: str = "") -> None:
        """A process fired a rule: aggregate the count, audit it as a
        cluster event, and retire the rule cluster-wide once its
        max_fires budget is spent (per-process counters alone can't
        bound fires across worker restarts)."""
        disable = False
        with self._lock:
            self.chaos_fired[rule_id] = \
                self.chaos_fired.get(rule_id, 0) + 1
            rule = self.chaos_rules.get(rule_id)
            if rule is not None and rule.get("max_fires", -1) >= 0 and \
                    self.chaos_fired[rule_id] >= rule["max_fires"]:
                rule["disabled"] = True
                self.chaos_version += 1
                disable = True
        self._emit("CHAOS_FAULT_INJECTED",
                   f"chaos rule {rule_id} fired {fault} at {where}",
                   severity="WARNING", rule_id=rule_id, fault=fault,
                   node_id=node_id)
        if disable:
            logger.warning("chaos: rule %s reached max_fires; disabling "
                           "cluster-wide", rule_id)
            self._chaos_publish()

    def _emit(self, event_type: str, message: str,
              severity: str = "INFO", **fields: Any) -> None:
        from ray_tpu._private.events import build_event
        self.add_events([build_event("gcs", event_type, message,
                                     severity, **fields)])

    # ---- pubsub ----------------------------------------------------------

    # ---- placement groups (reference GcsPlacementGroupManager,
    #      gcs_placement_group_scheduler.h: 2-phase prepare/commit) -------

    def create_placement_group(self, pg_id_hex: str, bundles, strategy: str,
                               name: str = "", detached: bool = False,
                               creator_job_id: str = "") -> str:
        from ray_tpu._private.ids import PlacementGroupID
        info = PlacementGroupInfo(
            pg_id=PlacementGroupID.from_hex(pg_id_hex), name=name,
            bundles=list(bundles), strategy=strategy,
            creator_job_id=creator_job_id, detached=detached)
        with self._lock:
            self.placement_groups[pg_id_hex] = info
        threading.Thread(target=self._schedule_placement_group,
                         args=(pg_id_hex,), daemon=True,
                         name=f"gcs-pg-{pg_id_hex[:8]}").start()
        return pg_id_hex

    def _schedule_placement_group(self, pg_id_hex: str,
                                  deadline_s: float = 120.0) -> None:
        from ray_tpu._private.scheduler import pack_bundles
        info = self.placement_groups[pg_id_hex]
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline and not self._dead:
            if info.state == "REMOVED":
                return
            with self._lock:
                view = {nid: dict(avail)
                        for nid, avail in self.node_available.items()
                        if self.nodes[nid].alive}
            placement = pack_bundles(view, info.bundles, info.strategy)
            if placement is None:
                time.sleep(0.1)
                continue
            # Phase 1: prepare every bundle on its node; roll back all on
            # any failure (reference PrepareBundleResources,
            # node_manager.proto:378).
            prepared = []
            ok = True
            for idx, (nid, bundle) in enumerate(
                    zip(placement, info.bundles)):
                node = self.nodes.get(nid)
                try:
                    good = node is not None and node.alive and \
                        self._pool.get(node.address).call(
                            "nm_prepare_bundle", pg_id_hex=pg_id_hex,
                            bundle_index=idx, resources=bundle)
                except Exception:  # noqa: BLE001
                    good = False
                if not good:
                    ok = False
                    break
                prepared.append((node, idx))
            if not ok:
                for node, idx in prepared:
                    try:
                        self._pool.get(node.address).call(
                            "nm_return_bundle", pg_id_hex=pg_id_hex,
                            bundle_index=idx)
                    except Exception:  # noqa: BLE001 - node died; bundles died with it
                        pass
                time.sleep(0.1)
                continue
            # Phase 2: commit (reference CommitBundleResources,
            # node_manager.proto:382).
            for node, idx in prepared:
                try:
                    self._pool.get(node.address).call(
                        "nm_commit_bundle", pg_id_hex=pg_id_hex,
                        bundle_index=idx)
                except Exception:  # noqa: BLE001 - prepare already
                    # reserved the resources; a node dying between
                    # prepare and commit surfaces through its NODE_DEAD
                    # sweep, but the skipped commit must be on record
                    logger.warning(
                        "placement group %s: commit_bundle %d on node "
                        "%s failed", pg_id_hex[:12], idx,
                        node.node_id.hex()[:12], exc_info=True)
            with self._lock:
                # remove_placement_group may have raced us between the
                # top-of-loop check and the commit: it saw PENDING and
                # returned no bundles, so we must release them here rather
                # than resurrect a removed group.
                if info.state == "REMOVED":
                    removed_while_scheduling = True
                else:
                    removed_while_scheduling = False
                    info.bundle_nodes = list(placement)
                    info.state = "CREATED"
            if removed_while_scheduling:
                for node, idx in prepared:
                    try:
                        self._pool.get(node.address).call(
                            "nm_return_bundle", pg_id_hex=pg_id_hex,
                            bundle_index=idx)
                    except Exception:  # noqa: BLE001 - node gone; nothing to return
                        pass
                return
            self.publish("placement_group", ("CREATED", info))
            return
        with self._lock:
            if info.state == "PENDING":
                info.state = "INFEASIBLE"
        self.publish("placement_group", ("INFEASIBLE", info))

    def remove_placement_group(self, pg_id_hex: str) -> bool:
        with self._lock:
            info = self.placement_groups.get(pg_id_hex)
            if info is None or info.state == "REMOVED":
                return False
            prev_state = info.state
            info.state = "REMOVED"
            # kill actors scheduled into this group (reference
            # GcsPlacementGroupManager::RemovePlacementGroup cleans up
            # dependent actors)
            doomed = [aid for aid, spec in self.actor_specs.items()
                      if spec.placement_group_id is not None
                      and spec.placement_group_id.hex() == pg_id_hex]
        for aid in doomed:
            try:
                self.kill_actor(aid, no_restart=True)
            except Exception:  # noqa: BLE001 - actor already dead
                pass
        if prev_state == "CREATED":
            for idx, nid in enumerate(info.bundle_nodes):
                node = self.nodes.get(nid)
                if node is None:
                    continue
                try:
                    self._pool.get(node.address).call(
                        "nm_return_bundle", pg_id_hex=pg_id_hex,
                        bundle_index=idx)
                except Exception:  # noqa: BLE001 - node gone; nothing to return
                    pass
        self.publish("placement_group", ("REMOVED", info))
        return True

    def get_placement_group(self, pg_id_hex: str):
        with self._lock:
            return self.placement_groups.get(pg_id_hex)

    def list_placement_groups(self):
        with self._lock:
            return list(self.placement_groups.values())

    def subscribe(self, channel: str, address: Tuple[str, int],
                  token: str) -> None:
        with self._lock:
            subs = self.subscribers.setdefault(channel, [])
            if (tuple(address), token) not in subs:
                subs.append((tuple(address), token))

    def unsubscribe(self, channel: str, address: Tuple[str, int],
                    token: str) -> None:
        """Drop one (address, token) subscription (short-lived
        subscribers — `ray_tpu logs --follow` — must not keep receiving
        pushes forever; idempotent so RPC retries are safe)."""
        with self._lock:
            subs = self.subscribers.get(channel)
            if subs is not None:
                try:
                    subs.remove((tuple(address), token))
                except ValueError:
                    pass

    def publish(self, channel: str, message: Any) -> None:
        with self._lock:
            subs = list(self.subscribers.get(channel, []))
        for address, token in subs:
            try:
                self._pool.get(address).call("cw_pubsub_push", channel=channel,
                                             token=token, message=message)
            except Exception:  # noqa: BLE001
                with self._lock:
                    try:
                        self.subscribers[channel].remove((address, token))
                    except ValueError:
                        pass

    def shutdown(self) -> None:
        self._dead = True
        self.metrics_plane.stop()
        from ray_tpu._private import metrics_plane as metrics_plane_lib
        metrics_plane_lib.unregister_sampler("gcs")
        self.server.stop()
        self._pool.close_all()
        if isinstance(self.store, PersistentStore):
            self.store.stop()
