"""Durable tiered time-series store for the GCS metrics plane.

Grows metrics_plane.SeriesHistory's 300-sample in-memory ring into a
crash-safe store that survives GCS restarts and holds hours of history
in bounded space:

  - **raw tier**: every harvested (wall_ts, merged flat series) sample
    at the harvest cadence (~2s), including FORCED rounds (CLI dumps,
    tests) tagged `forced=True` — present in the ring so `ray_tpu top`
    sparklines have no gaps, excluded from rate computation by readers.
  - **downsample tiers** ("30s", "5min"): one sample per aligned window,
    counters as intra-window DELTAS (what actually happened in the
    window — directly chartable as a rate), gauges as [min, mean, max].
    Built online as raw samples arrive; each tier's windows close
    independently.
  - **durability**: per tier, an append-only segment directory
    (`<dir>/<tier>/seg-*.json`). Segments are written
    tmp+fsync+rename — a crash mid-write loses at most the open
    segment's buffered samples, never corrupts an existing one — and
    replayed on construction so the GCS comes back with its
    pre-restart history queryable.
  - **retention**: a byte budget split across tiers (raw half, each
    downsample tier a quarter); oldest segments evicted first. The
    coarse tiers cover long windows in few bytes, so the budget buys
    roughly: minutes raw, hours at 30s, a day at 5min.

Pure data structure — no threads; the caller (MetricsPlane's sampler
round) provides serialization. All disk I/O failures degrade to
in-memory-only operation rather than breaking the harvest.
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

# downsample tier name -> window seconds
DOWNSAMPLE_TIERS: Dict[str, float] = {"30s": 30.0, "5min": 300.0}

TIERS: Tuple[str, ...] = ("raw",) + tuple(DOWNSAMPLE_TIERS)


def _series_name(key: str) -> str:
    i = key.find("{")
    return key if i < 0 else key[:i]


class _Downsampler:
    """Online aggregator for one tier: folds raw samples into aligned
    windows, emitting one sample per closed window."""

    def __init__(self, interval_s: float) -> None:
        self.interval_s = interval_s
        self._wid: Optional[int] = None
        # key -> [min, sum, count, max] (gauges) / last value (counters)
        self._gauges: Dict[str, List[float]] = {}
        self._counters: Dict[str, float] = {}
        # key -> last value of the PREVIOUS window (counter delta base)
        self._base: Dict[str, float] = {}
        # key -> first value seen in the current window (fallback base
        # for keys whose previous window never saw them)
        self._first: Dict[str, float] = {}

    def _finalize(self) -> Optional[Tuple[float, Dict[str, Any]]]:
        if self._wid is None:
            return None
        series: Dict[str, Any] = {}
        for key, last in self._counters.items():
            base = self._base.get(key, self._first.get(key, last))
            series[key] = max(0.0, last - base)
        for key, (mn, total, n, mx) in self._gauges.items():
            series[key] = [mn, total / max(1, n), mx]
        ts = (self._wid + 1) * self.interval_s
        self._base = dict(self._counters)
        self._gauges = {}
        self._counters = {}
        self._first = {}
        self._wid = None
        return (ts, series) if series else None

    def add(self, ts: float, series: Dict[str, float],
            is_counter) -> Optional[Tuple[float, Dict[str, Any]]]:
        """Fold one raw sample; returns the closed window's sample when
        `ts` crosses into a new window, else None."""
        wid = int(ts // self.interval_s)
        emitted = None
        if self._wid is not None and wid != self._wid:
            emitted = self._finalize()
        if self._wid is None:
            self._wid = wid
        for key, v in series.items():
            if isinstance(v, (list, tuple)):
                continue  # already-downsampled value (replay artifact)
            if is_counter(key):
                self._first.setdefault(key, float(v))
                self._counters[key] = float(v)
            else:
                agg = self._gauges.get(key)
                if agg is None:
                    self._gauges[key] = [float(v), float(v), 1, float(v)]
                else:
                    agg[0] = min(agg[0], v)
                    agg[1] += v
                    agg[2] += 1
                    agg[3] = max(agg[3], v)
        return emitted


class TieredHistory:
    """Raw + downsampled series history with optional on-disk segments.

    API mirrors (and supersets) metrics_plane.SeriesHistory: `append` /
    `query` keep their shapes so every existing reader (`ray_tpu top`,
    dashboard sparklines, `util.state.metrics_history`) works
    unchanged; `range_query` adds lookback-window reads across tiers
    that reach back through the on-disk segments past the in-memory
    ring.
    """

    def __init__(self, max_samples: int,
                 dir: Optional[str] = None,  # noqa: A002
                 retention_bytes: int = 32 << 20,
                 segment_samples: int = 32) -> None:
        self._max = max(2, int(max_samples))
        self._dir = dir or None
        self._retention = max(1 << 16, int(retention_bytes))
        self._segment_samples = max(1, int(segment_samples))
        self._lock = threading.Lock()
        # tier -> list of samples; raw entries are (ts, series, forced),
        # downsample entries (ts, series)
        self._rings: Dict[str, List[Tuple]] = {t: [] for t in TIERS}
        self._pending: Dict[str, List[Tuple]] = {t: [] for t in TIERS}
        self._down = {name: _Downsampler(iv)
                      for name, iv in DOWNSAMPLE_TIERS.items()}
        self._kinds: Dict[str, str] = {}
        self._seq = 0
        self.write_errors = 0
        self.segments_written = 0
        self.segments_evicted = 0
        if self._dir is not None:
            try:
                for tier in TIERS:
                    os.makedirs(os.path.join(self._dir, tier),
                                exist_ok=True)
                self._replay()
            except Exception:  # noqa: BLE001 - a bad disk must not
                logger.exception(  # keep the metrics plane from starting
                    "metrics history replay failed; starting empty")

    # -- kind resolution ----------------------------------------------

    def _is_counter(self, key: str) -> bool:
        name = _series_name(key)
        kind = self._kinds.get(name)
        if kind is None and (name.endswith("_sum")
                             or name.endswith("_count")):
            base = name.rsplit("_", 1)[0]
            if self._kinds.get(base) == "histogram":
                return True
            kind = self._kinds.get(base)
        if kind is not None:
            return kind in ("counter", "histogram")
        # unknown metric: *_total/_sum/_count is the prometheus counter
        # naming convention this codebase follows throughout
        return name.endswith(("_total", "_sum", "_count"))

    # -- writes --------------------------------------------------------

    def append(self, ts: float, series: Dict[str, float],
               kinds: Optional[Dict[str, str]] = None,
               forced: bool = False) -> None:
        with self._lock:
            if kinds:
                self._kinds.update(kinds)
            self._rings["raw"].append((ts, series, bool(forced)))
            self._pending["raw"].append((ts, series, bool(forced)))
            self._trim_raw_locked()
            for tier, ds in self._down.items():
                emitted = ds.add(ts, series, self._is_counter)
                if emitted is not None:
                    self._rings[tier].append(emitted)
                    del self._rings[tier][:-self._max]
                    self._pending[tier].append(emitted)
            flush_tiers = [t for t, p in self._pending.items()
                           if len(p) >= self._segment_samples]
        for tier in flush_tiers:
            self._flush_tier(tier)

    def _trim_raw_locked(self) -> None:
        """Bound the raw ring: at most max_samples NON-forced samples
        (the retention contract `samples x interval_s` the readers
        assume), and a 2x hard cap on total entries so a forced-dump
        loop can't grow it without bound."""
        ring = self._rings["raw"]
        plain = sum(1 for s in ring if not s[2])
        while ring and (plain > self._max or len(ring) > 2 * self._max):
            if ring[0][2]:
                ring.pop(0)
            else:
                ring.pop(0)
                plain -= 1

    def flush(self) -> None:
        """Write every buffered sample out (shutdown path: the GCS
        flushes before exiting so a restart replays right up to the
        last harvest)."""
        for tier in TIERS:
            self._flush_tier(tier)

    def _flush_tier(self, tier: str) -> None:
        if self._dir is None:
            with self._lock:
                # memory-only mode: pending buffers must not grow
                self._pending[tier] = []
            return
        with self._lock:
            pending, self._pending[tier] = self._pending[tier], []
            if not pending:
                return
            self._seq += 1
            seq = self._seq
        first_ts = pending[0][0]
        payload = {"v": 1, "tier": tier,
                   "samples": [list(s) for s in pending]}
        tdir = os.path.join(self._dir, tier)
        path = os.path.join(
            tdir, f"seg-{int(first_ts * 1000):015d}-{seq:06d}.json")
        try:
            fd, tmp = tempfile.mkstemp(prefix=".seg-", dir=tdir)
            try:
                with os.fdopen(fd, "w") as f:
                    json.dump(payload, f, separators=(",", ":"))
                    f.flush()
                    os.fsync(f.fileno())
                os.rename(tmp, path)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
            self.segments_written += 1
            self._enforce_retention()
        except Exception:  # noqa: BLE001 - disk trouble degrades to
            # memory-only for this batch; the harvest must not fail
            self.write_errors += 1
            logger.warning("metrics history segment write failed "
                           "(%s)", path, exc_info=True)

    # -- retention -----------------------------------------------------

    def _tier_budget(self, tier: str) -> int:
        return self._retention // 2 if tier == "raw" \
            else self._retention // (2 * len(DOWNSAMPLE_TIERS))

    def _segment_files(self, tier: str) -> List[str]:
        tdir = os.path.join(self._dir, tier)
        try:
            names = [n for n in os.listdir(tdir)
                     if n.startswith("seg-") and n.endswith(".json")]
        except OSError:
            return []
        return [os.path.join(tdir, n) for n in sorted(names)]

    def _enforce_retention(self) -> None:
        for tier in TIERS:
            files = self._segment_files(tier)
            sizes = []
            for p in files:
                try:
                    sizes.append(os.path.getsize(p))
                except OSError:
                    sizes.append(0)
            total = sum(sizes)
            budget = self._tier_budget(tier)
            i = 0
            # never evict the newest segment, whatever its size
            while total > budget and i < len(files) - 1:
                try:
                    os.unlink(files[i])
                    self.segments_evicted += 1
                except OSError:
                    pass
                total -= sizes[i]
                i += 1

    def disk_usage(self) -> int:
        if self._dir is None:
            return 0
        total = 0
        for tier in TIERS:
            for p in self._segment_files(tier):
                try:
                    total += os.path.getsize(p)
                except OSError:
                    pass
        return total

    # -- replay --------------------------------------------------------

    def _replay(self) -> None:
        """Rebuild the in-memory rings from the segment directories.
        Unparsable segments (torn by a crash predating the tmp+rename
        discipline, or hand-edited) are skipped, not fatal."""
        for tier in TIERS:
            samples: List[Tuple] = []
            for path in self._segment_files(tier):
                try:
                    with open(path) as f:
                        payload = json.load(f)
                    for s in payload.get("samples", ()):
                        if tier == "raw":
                            samples.append((float(s[0]), s[1],
                                            bool(s[2]) if len(s) > 2
                                            else False))
                        else:
                            samples.append((float(s[0]), s[1]))
                except Exception:  # noqa: BLE001 - torn/garbled segment
                    logger.warning("skipping unreadable metrics "
                                   "history segment %s", path)
            samples.sort(key=lambda s: s[0])
            self._rings[tier] = samples[-2 * self._max:]
        if self._rings["raw"]:
            self._trim_raw_locked()

    # -- reads ---------------------------------------------------------

    def query(self, names: Optional[List[str]] = None,
              limit: Optional[int] = None) -> List[Tuple[float, Dict]]:
        """SeriesHistory-compatible read of the raw ring: [(ts,
        series)], oldest first, prefix-matched on names. Forced samples
        are INCLUDED (no sparkline gaps); rate-computing callers use
        query_ex to skip them."""
        return [(ts, series)
                for ts, series, _f in self.query_ex(names, limit)]

    def query_ex(self, names: Optional[List[str]] = None,
                 limit: Optional[int] = None
                 ) -> List[Tuple[float, Dict, bool]]:
        with self._lock:
            samples = list(self._rings["raw"])
        if limit is not None:
            samples = samples[-int(limit):]
        if names:
            samples = [
                (ts, {k: v for k, v in series.items()
                      if any(k.startswith(n) for n in names)}, forced)
                for ts, series, forced in samples]
        return samples

    def range_query(self, names: Optional[List[str]] = None,
                    since_s: float = 600.0,
                    tier: str = "raw") -> List[Tuple[float, Dict]]:
        """Samples with wall ts >= now - since_s from `tier`, oldest
        first, reaching through on-disk segments when the lookback
        exceeds the in-memory ring. Raw-tier forced samples are
        included (value samples, not rate samples)."""
        if tier not in TIERS:
            raise ValueError(
                f"unknown history tier {tier!r} (have {list(TIERS)})")
        # Wall clock on purpose: sample timestamps are wall time so the
        # series stays comparable across GCS restarts (monotonic resets).
        cutoff = time.time() - max(0.0, float(since_s))  # graftlint: disable=RT010
        with self._lock:
            ring = list(self._rings[tier])
        by_ts: Dict[float, Dict] = {}
        ring_oldest = ring[0][0] if ring else None
        if self._dir is not None and \
                (ring_oldest is None
                 or ring_oldest > cutoff):  # graftlint: disable=RT010
            for path in self._segment_files(tier):
                try:
                    with open(path) as f:
                        payload = json.load(f)
                except Exception:  # noqa: BLE001 - torn segment
                    continue
                for s in payload.get("samples", ()):
                    ts = float(s[0])
                    if ts >= cutoff:  # graftlint: disable=RT010
                        by_ts[ts] = s[1]
        for entry in ring:
            if entry[0] >= cutoff:  # graftlint: disable=RT010
                by_ts[entry[0]] = entry[1]
        out = sorted(by_ts.items())
        if names:
            out = [(ts, {k: v for k, v in series.items()
                         if any(k.startswith(n) for n in names)})
                   for ts, series in out]
        return out

    def stop(self) -> None:
        self.flush()
