"""Global per-process worker state + driver bootstrap.

reference parity: python/ray/_private/worker.py — the module-level Worker
singleton (`global_worker`, worker.py:411), `init` (worker.py:1165) and
`connect`/`shutdown` (worker.py:2122, :1742). Head bring-up hosts the GCS and
a node manager in-process (the reference spawns separate gcs_server/raylet
binaries via _private/services.py; a standalone-process mode exists via
`ray_tpu._private.node_main` for the multi-node test harness).
"""

from __future__ import annotations

import atexit
import logging
import os
import shutil
import tempfile
import time
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

from ray_tpu._private.ids import JobID

logger = logging.getLogger(__name__)


@dataclass
class Worker:
    core_worker: Any
    mode: str                      # "driver" | "worker"
    gcs_address: Tuple[str, int]
    node_manager_address: Tuple[str, int]
    node: Any = None               # head Node (driver-embedded services)
    namespace: str = ""

    @property
    def connected(self) -> bool:
        return self.core_worker is not None


_global_worker: Optional[Worker] = None
# Thin-client session when connected via ray_tpu.init("ray://host:port")
# (reference util/client worker.py global client context).
_client_context = None


def client_context():
    return _client_context


def set_client_context(ctx) -> None:
    global _client_context
    _client_context = ctx


def global_worker() -> Worker:
    if _global_worker is None:
        raise RuntimeError(
            "ray_tpu.init() has not been called in this process")
    return _global_worker


def global_worker_or_none() -> Optional[Worker]:
    return _global_worker


def set_global_worker(w: Optional[Worker]) -> None:
    global _global_worker
    _global_worker = w


class HeadNode:
    """Driver-embedded head services: GCS + node manager + session dir.

    reference parity: python/ray/_private/node.py Node(head=True) →
    start_head_processes (node.py:1300).
    """

    def __init__(self, resources: Optional[Dict[str, float]] = None,
                 num_cpus: Optional[float] = None,
                 object_store_memory: Optional[int] = None,
                 session_root: Optional[str] = None):
        from ray_tpu._private.gcs import GcsServer
        from ray_tpu._private.node_manager import NodeManager

        base = session_root or (
            "/dev/shm" if os.path.isdir("/dev/shm") else tempfile.gettempdir())
        self.session_dir = os.path.join(
            base, f"ray_tpu_session_{int(time.time() * 1000)}_{os.getpid()}")
        os.makedirs(self.session_dir, exist_ok=True)

        self.gcs = GcsServer()
        res = dict(resources or {})
        if num_cpus is not None:
            res["CPU"] = float(num_cpus)
        self.node_manager = NodeManager(
            gcs_address=self.gcs.address, session_dir=self.session_dir,
            resources=res, is_head=True,
            object_store_capacity=object_store_memory)

    def shutdown(self) -> None:
        # local-only usage report (reference usage_lib, zero egress);
        # written NEXT TO the session dir so it survives the rmtree
        from ray_tpu._private.usage import write_usage_report
        write_usage_report(
            os.path.dirname(self.session_dir),
            f"usage_stats_{os.path.basename(self.session_dir)}.json")
        self.node_manager.shutdown()
        self.gcs.shutdown()
        shutil.rmtree(self.session_dir, ignore_errors=True)


# actor id prefix -> display name (resolved once per actor via the GCS)
_actor_name_cache: Dict[str, str] = {}


def _actor_label(actor_prefix: str) -> str:
    label = _actor_name_cache.get(actor_prefix)
    if label is not None:
        return label
    label = f"actor-{actor_prefix[:8]}"
    try:
        w = global_worker_or_none()
        if w is not None:
            for info in w.core_worker._gcs.call("list_actors"):
                if info.actor_id.hex().startswith(actor_prefix):
                    label = info.name or \
                        f"{info.class_name}-{actor_prefix[:8]}"
                    break
    except Exception:  # noqa: BLE001 - GCS away; keep the id label
        pass
    _actor_name_cache[actor_prefix] = label
    return label


def _print_worker_logs(msg) -> None:
    """reference worker.py:1823 print_to_stdstream — driver-side sink
    for the worker_logs pubsub channel. stderr, so drivers that emit
    machine-readable stdout (bench JSON) stay parseable. Attributed
    records print with an (actor_name, node) prefix; the log monitor's
    per-source flood control reports shed lines via `dropped` and the
    notice keeps the count honest (`ray_tpu logs` still has them —
    only the live stream sheds)."""
    import sys
    try:
        node = msg["node_id"][:8]
        records = msg.get("records")
        if records:
            for rec in records:
                src = (_actor_label(rec["actor_id"]) if rec.get("actor_id")
                       else msg["worker"])
                # the driver's terminal IS the debug plane's sink here
                print(f"({src}, node={node}) "  # graftlint: disable=RT012
                      f"{rec.get('msg', '')}", file=sys.stderr)
        else:
            prefix = f"({msg['worker']}, node={node})"
            for line in msg["lines"]:
                print(f"{prefix} {line}",  # graftlint: disable=RT012
                      file=sys.stderr)
        if msg.get("dropped"):
            # the shed-line notice is itself terminal output
            print(f"({msg['worker']}, node={node}) "  # graftlint: disable=RT012
                  f"... flood control dropped {msg['dropped']} lines "
                  f"from this stream ({msg.get('dropped_total', 0)} "
                  f"total; `ray_tpu logs` has them)", file=sys.stderr)
    except Exception:  # noqa: BLE001 - printing logs must never kill the driver
        pass


def init(address: Optional[str] = None, *,
         resources: Optional[Dict[str, float]] = None,
         num_cpus: Optional[float] = None,
         object_store_memory: Optional[int] = None,
         namespace: str = "",
         ignore_reinit_error: bool = False,
         log_to_driver: bool = True,
         _session_root: Optional[str] = None) -> Worker:
    """Connect this process as a driver; bootstrap a head if no address."""
    global _global_worker
    if address is not None and address.startswith("ray://"):
        # client mode (reference ray.init("ray://...")): no local core
        # worker; everything proxies through the cluster-side server
        from ray_tpu.client.worker import connect
        if _client_context is not None:
            if ignore_reinit_error:
                return _client_context
            raise RuntimeError("already connected in client mode")
        ctx = connect(address[len("ray://"):])
        ctx.namespace = namespace  # default for get_actor lookups
        set_client_context(ctx)
        return ctx
    if _global_worker is not None:
        if ignore_reinit_error:
            return _global_worker
        raise RuntimeError("ray_tpu.init() called twice "
                           "(use ignore_reinit_error=True)")

    from ray_tpu._private.core_worker import CoreWorker
    from ray_tpu._private.rpc import RpcClient

    node = None
    if address is None:
        node = HeadNode(resources=resources, num_cpus=num_cpus,
                        object_store_memory=object_store_memory,
                        session_root=_session_root)
    if node is not None:
        gcs_address = node.gcs.address
        nm_address = node.node_manager.address
        store_address = node.node_manager.store.address
        node_id_hex = node.node_manager.node_id.hex()
    else:
        host, port = address.rsplit(":", 1)
        gcs_address = (host, int(port))
        gcs = RpcClient(gcs_address, timeout=30)
        nodes = [n for n in gcs.call("get_all_nodes") if n.alive]
        if not nodes:
            raise RuntimeError(f"no alive nodes at {address}")
        head = next((n for n in nodes if n.is_head), nodes[0])
        nm_address = head.address
        store_address = head.store_address
        node_id_hex = head.node_id.hex()
        gcs.close()

    gcs = RpcClient(gcs_address, timeout=30)
    job_id: JobID = gcs.call("next_job_id")
    gcs.close()

    cw = CoreWorker(mode="driver", job_id=job_id, gcs_address=gcs_address,
                    node_manager_address=nm_address,
                    store_address=store_address, node_id_hex=node_id_hex)
    if log_to_driver:
        try:
            cw.subscribe("worker_logs", _print_worker_logs)
        except Exception:  # noqa: BLE001 - init proceeds without the
            # stream, but the operator should know why their console
            # is silent
            logger.warning("could not subscribe to worker log stream; "
                           "worker output will not reach this driver",
                           exc_info=True)
    _global_worker = Worker(core_worker=cw, mode="driver",
                            gcs_address=gcs_address,
                            node_manager_address=nm_address, node=node,
                            namespace=namespace)
    atexit.register(shutdown)
    return _global_worker


def shutdown() -> None:
    global _global_worker
    if _client_context is not None:
        _client_context.disconnect()
        set_client_context(None)
    w = _global_worker
    if w is None:
        return
    _global_worker = None
    try:
        w.core_worker._shutdown = True
        if w.node is not None:
            w.node.shutdown()
        w.core_worker.shutdown()
    except Exception:  # noqa: BLE001 - teardown; components may already be gone
        pass
    # drop cluster-scoped chaos context/rules (a re-init may join a
    # different cluster with different node ids and policy)
    from ray_tpu._private import chaos as chaos_lib
    chaos_lib.client().reset()
    try:
        atexit.unregister(shutdown)
    except Exception:  # noqa: BLE001 - already unregistered
        pass


def is_initialized() -> bool:
    return _global_worker is not None or _client_context is not None
