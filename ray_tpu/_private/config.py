"""Runtime config flags, overridable via RAY_TPU_<NAME> env vars.

reference parity: src/ray/common/ray_config_def.h — a single X-macro list of
RAY_CONFIG(type, name, default) entries, each overridable by env var. Same
idea here with a plain registry.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict

_REGISTRY: Dict[str, Any] = {}


def _define(name: str, default: Any, cast: Callable[[str], Any]) -> Any:
    env = os.environ.get(f"RAY_TPU_{name}")
    value = cast(env) if env is not None else default
    _REGISTRY[name] = value
    return value


def _bool(s: str) -> bool:
    return s.lower() in ("1", "true", "yes")


class Config:
    # Object store
    object_store_capacity_bytes = _define(
        "object_store_capacity_bytes", 4 << 30, int)
    # Below this size task returns / puts are inlined into the owner's
    # in-process memory store (reference: max_direct_call_object_size 100KB).
    max_inline_object_size = _define("max_inline_object_size", 100 * 1024, int)
    # Worker pool
    max_workers_per_node = _define("max_workers_per_node", 32, int)
    worker_register_timeout_s = _define("worker_register_timeout_s", 60.0, float)
    idle_worker_kill_timeout_s = _define("idle_worker_kill_timeout_s", 300.0, float)
    # keep this many idle workers warm regardless of the timeout
    # (reference worker_pool soft limit ~ num_cpus)
    idle_worker_pool_floor = _define("idle_worker_pool_floor", 2, int)
    # Scheduling
    lease_request_timeout_s = _define("lease_request_timeout_s", 120.0, float)
    resource_report_period_s = _define("resource_report_period_s", 0.5, float)
    # Health (reference gcs_health_check_manager.h): probe period and the
    # number of CONSECUTIVE failed probes before a node is declared dead —
    # one chaos-delayed or GC-paused probe must never kill a healthy node.
    health_check_period_s = _define("health_check_period_s", 2.0, float)
    health_check_failure_threshold = _define(
        "health_check_failure_threshold", 3, int)
    # Task retries (reference: default max_retries=3 for tasks)
    default_task_max_retries = _define("default_task_max_retries", 3, int)
    # DEPRECATED (compat shim): random RPC handler delays up to this many
    # micros (reference RAY_testing_asio_delay_us, asio_chaos.cc). Now a
    # startup-installed `delay` rule in the chaos plane — use
    # ray_tpu.chaos.inject("delay", delay_ms=..., jitter=True, seed=...)
    # instead; see _private/chaos.py.
    testing_rpc_delay_us = _define("testing_rpc_delay_us", 0, int)
    # OOM defense (reference memory_usage_threshold, ray_config_def.h:77)
    memory_usage_threshold = _define("memory_usage_threshold", 0.95, float)
    memory_monitor_refresh_ms = _define("memory_monitor_refresh_ms",
                                        1000, int)
    # Cluster metrics plane (_private/metrics_plane.py): GCS harvest
    # cadence (0 disables the sampler; /metrics then harvests on
    # demand), in-memory history depth, and watchdog thresholds. All
    # runtime-tunable via the GCS `metrics_configure` RPC.
    metrics_sample_interval_s = _define(
        "metrics_sample_interval_s", 2.0, float)
    metrics_history_samples = _define("metrics_history_samples", 300, int)
    # Durable tiered history (_private/metrics_history.py): segment
    # directory (empty = derive from the GCS persist path, or stay
    # memory-only without one), total on-disk retention budget split
    # across the raw/30s/5min tiers, and how many buffered samples a
    # tier accumulates before writing one fsync'd segment.
    metrics_history_dir = _define("metrics_history_dir", "", str)
    metrics_history_retention_bytes = _define(
        "metrics_history_retention_bytes", 32 << 20, int)
    metrics_history_segment_samples = _define(
        "metrics_history_segment_samples", 32, int)
    # Goodput ledger (_private/goodput.py): the `goodput_regression`
    # probe alerts when a job's productive_step fraction of its
    # accounted wall time over the sliding window drops below the
    # floor, naming the dominant badput bucket. Both
    # metrics_configure-tunable at runtime.
    watchdog_goodput_floor = _define(
        "watchdog_goodput_floor", 0.5, float)
    watchdog_goodput_window_s = _define(
        "watchdog_goodput_window_s", 120.0, float)
    watchdog_cooldown_s = _define("watchdog_cooldown_s", 30.0, float)
    watchdog_wait_edge_age_s = _define(
        "watchdog_wait_edge_age_s", 120.0, float)
    watchdog_store_occupancy_frac = _define(
        "watchdog_store_occupancy_frac", 0.95, float)
    watchdog_queue_depth = _define("watchdog_queue_depth", 256, int)
    # Lockdep plane (ray_tpu/util/locks.py): the watchdog's
    # long-hold-with-waiters probe alerts when a traced lock has been
    # held longer than this while at least this many threads queue.
    watchdog_lock_hold_s = _define("watchdog_lock_hold_s", 5.0, float)
    watchdog_lock_waiters = _define("watchdog_lock_waiters", 1, int)
    # Serve request telemetry (serve/_telemetry.py): per-request handle
    # wait bound at the ingress proxies (timeouts surface as 504 /
    # DEADLINE_EXCEEDED), and the SLO watchdog probes over the
    # harvested RED metrics — p99 latency threshold (computed from
    # per-harvest histogram deltas) and error-rate threshold (5xx
    # fraction of the per-harvest request delta). Runtime-tunable via
    # the GCS `metrics_configure` RPC.
    serve_request_timeout_s = _define(
        "serve_request_timeout_s", 120.0, float)
    watchdog_serve_p99_s = _define("watchdog_serve_p99_s", 2.0, float)
    watchdog_serve_error_rate = _define(
        "watchdog_serve_error_rate", 0.1, float)
    # Serve ingress fleet (serve/_private/proxy_fleet/): admission
    # control + load shedding at the per-node asyncio proxies. A
    # deployment admits up to replicas x max_concurrent_queries
    # in-flight requests plus this many queued beyond capacity before
    # shedding (503 + Retry-After / RESOURCE_EXHAUSTED); -1 on the
    # deployment means "use this default". Rate limit is a per-proxy
    # per-deployment token bucket in requests/s (0 = unlimited).
    serve_max_queued_per_deployment = _define(
        "serve_max_queued_per_deployment", 128, int)
    serve_rate_limit_rps = _define("serve_rate_limit_rps", 0.0, float)
    # Retry-After seconds advertised on shed responses.
    serve_shed_retry_after_s = _define(
        "serve_shed_retry_after_s", 1.0, float)
    # Proxy drain: max wait for in-flight requests to finish before a
    # draining proxy gives up and reports itself drained anyway.
    serve_drain_timeout_s = _define("serve_drain_timeout_s", 30.0, float)
    # Proxy-side request coalescing into @serve.batch deployments: max
    # requests fused into one replica submit, and how long the first
    # request in a forming batch waits for stragglers.
    serve_coalesce_max_batch = _define("serve_coalesce_max_batch", 32, int)
    serve_coalesce_wait_s = _define("serve_coalesce_wait_s", 0.002, float)
    # SLO watchdog: shed fraction of a harvest window's admitted+shed
    # request delta above this sustains a `serve_shed_burn` alert.
    watchdog_serve_shed_rate = _define(
        "watchdog_serve_shed_rate", 0.5, float)
    # Elastic training plane (train/elastic.py): an in-flight gang
    # reconfiguration older than this raises `elastic_stuck_reconfig` —
    # a gang that can neither re-form nor fail looks exactly like
    # training, minus the progress. Size it past the WORST legitimate
    # reconfiguration, not the typical one: a learner gang stepping
    # down from target to min can spend elastic_reform_timeout_s
    # (default 60s) PER attempted world size, and a large-model
    # reshard adds its state-transfer time on top — raise this (it is
    # metrics_configure-tunable at runtime) for wide target-min gaps
    # rather than treating a slow-but-progressing recovery as stuck.
    watchdog_elastic_reconfig_s = _define(
        "watchdog_elastic_reconfig_s", 120.0, float)
    # Gang heartbeat plane (train/heartbeat.py): a rank whose
    # ray_tpu_gang_heartbeat_age_seconds exceeds this raises
    # `gang_rank_wedged` — the sidecar beats every ~0.5s even while the
    # main thread sits inside a collective, so ~20 missed beats means
    # the PROCESS is stopped (SIGSTOP, hard GIL stall), not merely a
    # slow step. The gang supervisor uses the same threshold as the
    # second factor of its wedge trip (step deadline expired AND a
    # heartbeat this stale). metrics_configure-tunable at runtime.
    watchdog_gang_heartbeat_s = _define(
        "watchdog_gang_heartbeat_s", 10.0, float)
    # JAX sentinel probes (util/jax_sentinel.py; static twins are
    # graftlint RT020/RT021): a step-region label whose kind=recompile
    # counter grows by >= watchdog_jit_recompiles within one harvest
    # window — after the label's first compile is older than the warmup
    # grace — raises `jit_recompile_storm`; host-transfer bytes
    # accounted INSIDE a step region growing by >=
    # watchdog_host_transfer_bytes per window raise
    # `unexpected_host_transfer` (hot steps sync at sanctioned forcing
    # points outside their jitted bodies). All three are
    # metrics_configure-tunable at runtime.
    watchdog_jit_recompiles = _define("watchdog_jit_recompiles", 3, int)
    watchdog_jit_recompile_warmup_s = _define(
        "watchdog_jit_recompile_warmup_s", 60.0, float)
    watchdog_host_transfer_bytes = _define(
        "watchdog_host_transfer_bytes", float(1 << 20), float)
    # Debug plane (_private/log_plane.py + log_monitor.py): per-worker
    # in-memory tail index depth, driver-stream flood control (per-source
    # token bucket), and crash-postmortem bundle sizes.
    log_tail_lines = _define("log_tail_lines", 2000, int)
    log_stream_rate_lps = _define("log_stream_rate_lps", 500.0, float)
    log_stream_burst = _define("log_stream_burst", 1000, int)
    postmortem_log_lines = _define("postmortem_log_lines", 100, int)
    postmortem_span_tail = _define("postmortem_span_tail", 200, int)
    postmortems_max = _define("postmortems_max", 256, int)
    # Task-path batching (ROADMAP item 1): coalesce per-key lease
    # requests into multi-grant nm_lease_request_batch RPCs, and batch
    # cw_task_done reports off the worker's report drainer (many
    # completions -> one flush-coalesced write). Both default on; the
    # flags exist for the measured ablation (tools/bench_ablate.py
    # --suite lease) and as kill switches.
    task_lease_batching = _define("task_lease_batching", True, _bool)
    task_done_batching = _define("task_done_batching", True, _bool)
    # Same-node shm fast path: a task pushed to a worker on the owner's
    # node rides an mmap'd SPSC byte-ring (_private/shm_channel.py)
    # instead of the loopback socket, with a doorbell one-way only when
    # the consumer ring is parked. Rings live next to the native store
    # arena; silently degrades to RPC without one. Geometry: payload
    # bytes per directed (producer -> consumer) ring.
    shm_task_channel = _define("shm_task_channel", True, _bool)
    shm_ring_bytes = _define("shm_ring_bytes", 1 << 20, int)
    # Spec-blob interning: owner-side LRU of hash-dedup'd pickled
    # function/arg blobs so 250k queued copies of the same closure cost
    # one blob, not 250k (scale envelope, ROADMAP item 1).
    spec_blob_cache_entries = _define("spec_blob_cache_entries", 256, int)
    # Transit pins on ObjectRefs embedded in task results: fallback TTL
    # used only when the owner's ack never arrives (the normal path
    # releases on ack — see _Executor._report_done).
    transit_pin_ttl_s = _define("transit_pin_ttl_s", 30.0, float)
    # Profiling plane (_private/profiler.py): default sampling rate for
    # `ray_tpu profile` and the cap on DISTINCT folded stacks one
    # sampler session aggregates (beyond it samples are counted into
    # the drop counter, never allocated — memory stays O(cap), not
    # O(duration)).
    profile_default_hz = _define("profile_default_hz", 100.0, float)
    profile_max_stacks = _define("profile_max_stacks", 2000, int)
    # Memory attribution plane (_private/memory_plane.py): per-snapshot
    # object cap for the full `ray_tpu memory` gather and the (smaller)
    # digest cap riding every metrics harvest. Callsite capture records
    # the put()/.remote() source line that created each owned object —
    # one stack walk per object creation (~a few µs), so it is opt-in.
    memory_callsite_capture = _define(
        "memory_callsite_capture", False, _bool)
    memory_snapshot_max_objects = _define(
        "memory_snapshot_max_objects", 4096, int)
    memory_digest_max_objects = _define(
        "memory_digest_max_objects", 512, int)


if Config.testing_rpc_delay_us:
    import warnings

    warnings.warn(
        "RAY_TPU_testing_rpc_delay_us/_seed are deprecated; use the chaos "
        "plane (ray_tpu.chaos.inject('delay', delay_ms=..., jitter=True, "
        "seed=...)) instead", DeprecationWarning, stacklevel=2)


def get(name: str) -> Any:
    return _REGISTRY[name]
