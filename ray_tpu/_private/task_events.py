"""Task event buffering: per-process event records flushed to the GCS.

reference parity: src/ray/core_worker/task_event_buffer.h:143,206 — every
core worker buffers task state transitions + profile timestamps and flushes
them periodically to the GCS task sink (gcs/gcs_server/gcs_task_manager.h:85),
which the state API (`ray list tasks`) and `ray timeline` read back.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional
from ray_tpu.util.locks import TracedLock

FLUSH_PERIOD_S = 1.0

# Bound on unflushed records: a slow or partitioned GCS (easy to hit
# under chaos partition rules) must not grow _pending without limit in
# every process — oldest deltas are dropped and counted instead.
PENDING_MAX = 8192


class TaskEventBuffer:
    """Accumulates partial task records; a background thread flushes deltas.

    Records are merge-dicts keyed by task id hex: the owner contributes
    SUBMITTED/FINISHED/FAILED transitions, the executing worker contributes
    RUNNING + execution timestamps; the GCS merges both halves.
    """

    def __init__(self, gcs_client: Any, pending_max: int = PENDING_MAX):
        self._gcs = gcs_client
        self._lock = TracedLock("task_events")
        self._pending: Dict[str, Dict[str, Any]] = {}
        self._pending_max = max(1, pending_max)
        self.dropped_total = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._flush_loop, daemon=True,
                                        name="task-events")
        self._thread.start()

    def record(self, task_id_hex: str, **fields: Any) -> None:
        dropped = 0
        with self._lock:
            rec = self._pending.setdefault(task_id_hex,
                                           {"task_id": task_id_hex})
            rec.update({k: v for k, v in fields.items() if v is not None})
            # drop-oldest (dict preserves insertion order): losing an old
            # task's delta beats unbounded memory while the GCS is away
            while len(self._pending) > self._pending_max:
                self._pending.pop(next(iter(self._pending)))
                dropped += 1
            self.dropped_total += dropped
        if dropped:
            try:
                from ray_tpu.util.metrics import Counter, get_or_create
                get_or_create(
                    Counter, "ray_tpu_task_events_dropped_total",
                    description="task-event deltas dropped because the "
                                "pending buffer hit its cap (GCS slow or "
                                "partitioned)").inc(dropped)
            except Exception:  # noqa: BLE001 - metrics are best-effort
                pass

    def _flush_loop(self) -> None:
        while not self._stop.wait(FLUSH_PERIOD_S):
            self.flush()

    def flush(self) -> None:
        with self._lock:
            if not self._pending:
                return
            batch = list(self._pending.values())
            self._pending = {}
        try:
            self._gcs.call("add_task_events", events=batch)
        except Exception:  # noqa: BLE001 - GCS down; drop rather than block
            pass

    def stop(self) -> None:
        self._stop.set()
        self.flush()


def now() -> float:
    return time.time()


def timeline_events(task_records: list,
                    node_names: Optional[Dict[str, str]] = None) -> list:
    """Convert GCS task records into Chrome-trace 'X' (complete) events
    (reference: `ray timeline`, scripts.py:1856 → chrome://tracing JSON)."""
    out = []
    for rec in task_records:
        start = rec.get("ts_running")
        end = rec.get("ts_exec_end")
        if start is None:
            continue
        if end is None:
            end = rec.get("ts_finished") or start
        pid = rec.get("node_id", "driver")[:12]
        if node_names and pid in node_names:
            pid = node_names[pid]
        out.append({
            "ph": "X", "cat": "task",
            "name": rec.get("name", rec.get("task_id", "?")[:12]),
            "pid": pid,
            "tid": rec.get("worker_id", "?")[:12],
            "ts": start * 1e6,
            "dur": max(end - start, 0.0) * 1e6,
            "args": {
                "task_id": rec.get("task_id"),
                "state": rec.get("state"),
                "type": rec.get("type"),
            },
        })
    return out
