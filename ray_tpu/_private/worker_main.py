"""Worker process entrypoint.

reference parity: python/ray/_private/workers/default_worker.py — spawned by
the node manager's worker pool; connects a CoreWorker in worker mode and
serves task pushes until killed.
"""

from __future__ import annotations

import os
import signal
import sys
import threading


def main() -> None:
    # stdout/stderr land in the per-worker log file: route every line
    # (prints, logging, native chatter) through the debug plane's
    # attribution stamper so the log monitor can index it by
    # task/actor/trace id (see _private/log_plane.py); the wrapper
    # flushes per complete line so tails stay live
    from ray_tpu._private import log_plane
    log_plane.init_worker_io("worker")
    import faulthandler
    # the raw fd, not the stamping wrapper: faulthandler runs in a
    # signal context and needs a real file (its dump lines parse as
    # RAW records)
    faulthandler.register(signal.SIGUSR1, file=log_plane.raw_stderr(),
                          all_threads=True)

    def parse_addr(s: str):
        host, port = s.rsplit(":", 1)
        return (host, int(port))

    gcs = parse_addr(os.environ["RAY_TPU_GCS"])
    nm = parse_addr(os.environ["RAY_TPU_NODE_MANAGER"])
    store = parse_addr(os.environ["RAY_TPU_STORE"])
    node_id_hex = os.environ["RAY_TPU_NODE_ID"]
    worker_id_hex = os.environ["RAY_TPU_WORKER_ID"]

    from ray_tpu._private import worker as worker_mod
    from ray_tpu._private.core_worker import CoreWorker
    from ray_tpu._private.ids import JobID, WorkerID
    from ray_tpu._private.rpc import RpcClient

    # Workers execute tasks from any job; job id is carried per-task.
    cw = CoreWorker(
        mode="worker", job_id=JobID.nil(), gcs_address=gcs,
        node_manager_address=nm, store_address=store,
        node_id_hex=node_id_hex, worker_id=WorkerID.from_hex(worker_id_hex))
    worker_mod.set_global_worker(worker_mod.Worker(
        core_worker=cw, mode="worker",
        gcs_address=gcs, node_manager_address=nm))

    nm_client = RpcClient(nm, timeout=60)
    nm_client.call("nm_register_worker", worker_id_hex=worker_id_hex,
                   address=cw.address)

    stop = threading.Event()

    def _term(signum, frame):  # noqa: ARG001
        stop.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    stop.wait()
    cw.shutdown()
    sys.exit(0)


if __name__ == "__main__":
    main()
