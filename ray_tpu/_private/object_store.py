"""Per-node shared-memory object store (plasma equivalent).

reference parity: src/ray/object_manager/plasma/store.h (PlasmaStore),
object_lifecycle_manager.h, eviction_policy.h (LRU), plus the node-to-node
chunked transfer of src/ray/object_manager/{push,pull}_manager.h.

Design: every node manager hosts a StoreServer. Object payloads live as
mmap-able files under /dev/shm/<session>/ so any process on the node maps
them zero-copy; the server coordinates create/seal/wait/delete metadata,
LRU-evicts unpinned sealed objects under memory pressure, and serves chunked
reads so a peer store can pull objects across nodes. A later C++ arena
allocator can replace the file-per-object layout behind the same client API.
"""

from __future__ import annotations

import mmap
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import rpc as rpc_lib

CHUNK_SIZE = 8 << 20  # 8 MiB transfer chunks (reference object_buffer_pool)


class ObjectStoreFullError(Exception):
    pass


@dataclass
class _Entry:
    path: str
    size: int
    sealed: bool = False
    pinned: int = 0          # pin count (owner pins while referenced)
    last_access: float = field(default_factory=time.time)
    creating: bool = True
    spilled: bool = False    # payload lives in the disk spill dir, not shm


class StoreServer:
    """Metadata + lifecycle authority for one node's shared-memory objects."""

    def __init__(self, session_dir: str, capacity_bytes: int,
                 host: str = "127.0.0.1",
                 spill_dir: Optional[str] = None):
        self.dir = os.path.join(session_dir, "objects")
        os.makedirs(self.dir, exist_ok=True)
        # Spill target must be real disk, not /dev/shm (spilling to RAM
        # frees nothing) — reference local_object_manager.cc:161-334 spills
        # to external storage via _private/external_storage.py.
        if spill_dir is None:
            import tempfile
            spill_dir = os.path.join(
                tempfile.gettempdir(),
                "ray_tpu_spill_" + os.path.basename(session_dir.rstrip("/")))
        self.spill_dir = spill_dir
        os.makedirs(self.spill_dir, exist_ok=True)
        self.capacity = capacity_bytes
        self.used = 0
        self.num_spilled = 0
        self.num_restored = 0
        self._objects: Dict[str, _Entry] = {}
        self._lock = threading.Lock()
        self._sealed_cv = threading.Condition(self._lock)
        self._pool = rpc_lib.ClientPool(timeout=60)
        self.server = rpc_lib.RpcServer({
            "store_create": self.create,
            "store_seal": self.seal,
            "store_wait": self.wait,
            "store_contains": self.contains,
            "store_delete": self.delete,
            "store_pin": self.pin,
            "store_unpin": self.unpin,
            "store_read_chunk": self.read_chunk,
            "store_pull": self.pull,
            "store_put_raw": self.put_raw,
            "store_stats": self.stats,
            "store_list": self.list_objects,
        }, host=host)
        self.address = self.server.address

    # -- lifecycle ---------------------------------------------------------

    def _evict_until(self, needed: int) -> None:
        """Free shm space: LRU-drop unpinned replicas first (reference
        eviction_policy.h), then LRU-spill pinned primaries to disk
        (reference local_object_manager.cc:161-334 SpillObjects)."""
        if self.used + needed <= self.capacity:
            return
        victims = sorted(
            ((e.last_access, oid) for oid, e in self._objects.items()
             if e.sealed and e.pinned == 0 and not e.spilled),
            key=lambda t: t[0])
        for _, oid in victims:
            if self.used + needed <= self.capacity:
                return
            self._delete_locked(oid)
        # Still short: spill pinned, sealed primaries to disk. Their data
        # survives and restores on next access; only shm space is released.
        spillable = sorted(
            ((e.last_access, oid) for oid, e in self._objects.items()
             if e.sealed and not e.spilled),
            key=lambda t: t[0])
        for _, oid in spillable:
            if self.used + needed <= self.capacity:
                return
            self._spill_locked(oid)
        if self.used + needed > self.capacity:
            raise ObjectStoreFullError(
                f"object store full: need {needed}, used {self.used}/{self.capacity}")

    def _spill_locked(self, object_id: str) -> None:
        e = self._objects.get(object_id)
        if e is None or not e.sealed or e.spilled:
            return
        spill_path = os.path.join(self.spill_dir, object_id)
        # Copy (not rename): spill dir is on a different filesystem than shm.
        with open(e.path, "rb") as src, open(spill_path, "wb") as dst:
            while True:
                chunk = src.read(CHUNK_SIZE)
                if not chunk:
                    break
                dst.write(chunk)
        try:
            os.unlink(e.path)
        except OSError:
            pass
        e.path = spill_path
        e.spilled = True
        self.used -= e.size
        self.num_spilled += 1

    def _restore_locked(self, object_id: str) -> None:
        """Bring a spilled object back into shm (reference
        RestoreSpilledObject)."""
        e = self._objects.get(object_id)
        if e is None or not e.spilled:
            return
        self._evict_until(e.size)
        shm_path = os.path.join(self.dir, object_id)
        spill_path = e.path
        with open(spill_path, "rb") as src, open(shm_path, "wb") as dst:
            while True:
                chunk = src.read(CHUNK_SIZE)
                if not chunk:
                    break
                dst.write(chunk)
        try:
            os.unlink(spill_path)
        except OSError:
            pass
        e.path = shm_path
        e.spilled = False
        e.last_access = time.time()
        self.used += e.size
        self.num_restored += 1

    def _delete_locked(self, object_id: str) -> None:
        e = self._objects.pop(object_id, None)
        if e is None:
            return
        if not e.spilled:
            self.used -= e.size
        try:
            os.unlink(e.path)
        except OSError:
            pass

    def create(self, object_id: str, size: int, pin: bool = True) -> str:
        """Allocate backing file; returns its path for the client to mmap.

        Primary (owner-written) copies are created pinned so LRU eviction
        can't drop an object the owner still references; delete() (driven by
        the owner's refcount) removes them. Pulled replica copies are created
        unpinned and evictable (the primary still exists elsewhere).
        """
        with self._lock:
            if object_id in self._objects:
                e = self._objects[object_id]
                if e.size == size and not e.spilled:
                    return e.path
                # Same id re-created with a different payload size (lineage
                # re-execution of a nondeterministic task) or a spilled
                # entry being rewritten: replace the backing file — mmap'ing
                # a larger size over the old file would SIGBUS past EOF.
                self._delete_locked(object_id)
            self._evict_until(size)
            path = os.path.join(self.dir, object_id)
            with open(path, "wb") as f:
                f.truncate(max(size, 1))
            self._objects[object_id] = _Entry(path=path, size=size,
                                              pinned=1 if pin else 0)
            self.used += size
            return path

    def put_raw(self, object_id: str, data: bytes, pin: bool = False) -> None:
        """Create + write + seal in one RPC (remote pushes, small writers)."""
        path = self.create(object_id, len(data), pin=pin)
        with open(path, "r+b") as f:
            f.write(data)
        self.seal(object_id)

    def seal(self, object_id: str) -> None:
        with self._sealed_cv:
            e = self._objects.get(object_id)
            if e is None:
                raise KeyError(f"seal of unknown object {object_id}")
            e.sealed = True
            e.creating = False
            e.last_access = time.time()
            self._sealed_cv.notify_all()

    def wait(self, object_ids: List[str], timeout: Optional[float] = None,
             num_required: Optional[int] = None) -> Dict[str, Tuple[str, int]]:
        """Block until objects are sealed locally; returns {id: (path, size)}.
        Objects not present locally are NOT fetched here (see pull)."""
        deadline = None if timeout is None else time.time() + timeout
        num_required = len(object_ids) if num_required is None else num_required
        with self._sealed_cv:
            while True:
                ready = {}
                for oid in object_ids:
                    e = self._objects.get(oid)
                    if e is not None and e.sealed:
                        if e.spilled:
                            self._restore_locked(oid)
                        e.last_access = time.time()
                        ready[oid] = (e.path, e.size)
                if len(ready) >= num_required:
                    return ready
                remaining = None if deadline is None else deadline - time.time()
                if remaining is not None and remaining <= 0:
                    return ready
                self._sealed_cv.wait(timeout=min(remaining or 1.0, 1.0))

    def contains(self, object_id: str) -> bool:
        with self._lock:
            e = self._objects.get(object_id)
            return e is not None and e.sealed

    def delete(self, object_ids: List[str]) -> None:
        with self._lock:
            for oid in object_ids:
                self._delete_locked(oid)

    def pin(self, object_id: str) -> None:
        with self._lock:
            e = self._objects.get(object_id)
            if e is not None:
                e.pinned += 1

    def unpin(self, object_id: str) -> None:
        with self._lock:
            e = self._objects.get(object_id)
            if e is not None and e.pinned > 0:
                e.pinned -= 1

    # -- node-to-node transfer --------------------------------------------

    def read_chunk(self, object_id: str, offset: int, length: int) -> bytes:
        with self._lock:
            e = self._objects.get(object_id)
            if e is None or not e.sealed:
                raise KeyError(f"read_chunk: {object_id} not sealed here")
            path, size = e.path, e.size
            e.last_access = time.time()
        with open(path, "rb") as f:
            f.seek(offset)
            return f.read(min(length, size - offset))

    def pull(self, object_id: str, from_store: Tuple[str, int],
             size: int) -> Tuple[str, int]:
        """Pull an object from a peer store into this one (chunked).
        reference parity: pull_manager.h / push_manager.h chunk streaming."""
        with self._lock:
            e = self._objects.get(object_id)
            if e is not None and e.sealed:
                return e.path, e.size
        path = self.create(object_id, size, pin=False)
        client = self._pool.get(tuple(from_store))
        with open(path, "r+b") as f:
            off = 0
            while off < size:
                chunk = client.call("store_read_chunk", object_id=object_id,
                                    offset=off, length=CHUNK_SIZE)
                f.write(chunk)
                off += len(chunk)
                if not chunk:
                    raise IOError(f"short read pulling {object_id}")
        self.seal(object_id)
        return path, size

    def list_objects(self) -> List[Dict[str, Any]]:
        """Object-level metadata for the state API (`ray list objects`)."""
        with self._lock:
            return [{"object_id": oid, "size": e.size, "sealed": e.sealed,
                     "pinned": e.pinned, "spilled": e.spilled}
                    for oid, e in self._objects.items()]

    def stats(self) -> Dict[str, float]:
        with self._lock:
            return {"used": self.used, "capacity": self.capacity,
                    "num_objects": len(self._objects),
                    "num_spilled": self.num_spilled,
                    "num_restored": self.num_restored}

    def shutdown(self) -> None:
        self.server.stop()
        with self._lock:
            for oid in list(self._objects):
                self._delete_locked(oid)
        import shutil as _shutil
        _shutil.rmtree(self.spill_dir, ignore_errors=True)


class StoreClient:
    """Per-process client: RPC for metadata, direct mmap for payload."""

    def __init__(self, store_address: Tuple[str, int]):
        self.address = tuple(store_address)
        self._rpc = rpc_lib.RpcClient(self.address, timeout=None)
        # object id -> (mmap, view, inode). The inode detects a deleted-and-
        # recreated object id (e.g. lineage re-execution after eviction):
        # the cached map then points at the dead unlinked inode and must be
        # replaced, or writes/reads silently hit stale data.
        self._maps: Dict[str, Tuple[mmap.mmap, memoryview, int]] = {}
        self._lock = threading.Lock()

    def create(self, object_id: str, size: int) -> memoryview:
        path = self._rpc.call("store_create", object_id=object_id, size=size)
        return self._map(object_id, path, size, writable=True)

    def _map(self, object_id: str, path: str, size: int,
             writable: bool = False) -> memoryview:
        with self._lock:
            inode = os.stat(path).st_ino
            cached = self._maps.get(object_id)
            if cached is not None:
                if cached[2] == inode:
                    return cached[1]
                self._release_locked(object_id)
            fd = os.open(path, os.O_RDWR if writable else os.O_RDONLY)
            try:
                mm = mmap.mmap(fd, max(size, 1),
                               prot=(mmap.PROT_READ | mmap.PROT_WRITE)
                               if writable else mmap.PROT_READ)
            finally:
                os.close(fd)
            view = memoryview(mm)[:size]
            self._maps[object_id] = (mm, view, inode)
            return view

    def seal(self, object_id: str) -> None:
        self._rpc.call("store_seal", object_id=object_id)

    def put_raw(self, object_id: str, data: bytes) -> None:
        if len(data) > CHUNK_SIZE:
            buf = self.create(object_id, len(data))
            buf[:] = data
            self.seal(object_id)
        else:
            self._rpc.call("store_put_raw", object_id=object_id, data=data)

    def get(self, object_ids: List[str], timeout: Optional[float] = None
            ) -> Dict[str, memoryview]:
        meta = self._rpc.call("store_wait", object_ids=object_ids,
                              timeout=timeout)
        return {oid: self._map(oid, path, size)
                for oid, (path, size) in meta.items()}

    def contains(self, object_id: str) -> bool:
        return self._rpc.call("store_contains", object_id=object_id)

    def pull(self, object_id: str, from_store: Tuple[str, int], size: int
             ) -> memoryview:
        path, size = self._rpc.call("store_pull", object_id=object_id,
                                    from_store=tuple(from_store), size=size)
        return self._map(object_id, path, size)

    def delete(self, object_ids: List[str]) -> None:
        self._release(object_ids)
        self._rpc.call("store_delete", object_ids=object_ids)

    def _release_locked(self, oid: str) -> None:
        m = self._maps.pop(oid, None)
        if m is not None:
            try:
                m[1].release()
                m[0].close()
            except (BufferError, ValueError):
                pass  # a live numpy view still references the map

    def _release(self, object_ids: List[str]) -> None:
        with self._lock:
            for oid in object_ids:
                self._release_locked(oid)

    def stats(self) -> Dict[str, float]:
        return self._rpc.call("store_stats")

    def close(self) -> None:
        self._rpc.close()
