"""Per-node shared-memory object store (plasma equivalent).

reference parity: src/ray/object_manager/plasma/store.h (PlasmaStore),
object_lifecycle_manager.h, eviction_policy.h (LRU), plasma_allocator.h
(the dlmalloc shm arena — here ray_tpu/native/store_arena.cpp, a C++
boundary-tag allocator over ONE mmap'd arena file), plus the node-to-node
chunked transfer of src/ray/object_manager/{push,pull}_manager.h.

Design: every node manager hosts a StoreServer. Payloads live in a
shared-memory arena that every process on the node maps once; objects
are (offset, size) slices handed out by the native allocator, so client
reads are zero-copy and object creation is an allocation, not a file
create + per-object mmap. When the native toolchain is unavailable the
server falls back to the original file-per-object layout transparently
(location descriptors carry the layout: ("arena", path, offset, size) or
("file", path, size)). The server LRU-evicts unpinned sealed objects
under pressure, spills pinned primaries to disk, and serves chunked
reads so a peer store can pull objects across nodes.
"""

from __future__ import annotations

import logging
import mmap
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import chaos as chaos_lib
from ray_tpu._private import ownership as _ownership
from ray_tpu._private import rpc as rpc_lib
from ray_tpu._private import spans as _spans
from ray_tpu.util.locks import TracedLock

logger = logging.getLogger(__name__)

CHUNK_SIZE = 8 << 20  # 8 MiB transfer chunks (reference object_buffer_pool)


class ObjectStoreFullError(Exception):
    pass


@dataclass
class _Entry:
    size: int
    offset: Optional[int] = None   # arena payload offset (arena layout)
    path: Optional[str] = None     # backing file (file layout / spilled)
    sealed: bool = False
    pinned: int = 0          # pin count (owner pins while referenced)
    # reader leases (store_pin / pin=True on wait/pull): while > 0 a
    # zero-copy view of this block is outstanding, so the entry is
    # neither dropped, spilled, nor chaos-evicted (eviction defers)
    leases: int = 0
    last_access: float = field(default_factory=time.time)
    # monotonic so ages never jump with wall-clock adjustments (RT010)
    created_mono: float = field(default_factory=time.monotonic)
    creating: bool = True
    spilled: bool = False    # payload lives in the disk spill dir, not shm


class StoreServer:
    """Metadata + lifecycle authority for one node's shared-memory objects."""

    def __init__(self, session_dir: str, capacity_bytes: int,
                 host: str = "127.0.0.1",
                 spill_dir: Optional[str] = None):
        self.dir = os.path.join(session_dir, "objects")
        os.makedirs(self.dir, exist_ok=True)
        # Spill target must be real disk, not /dev/shm (spilling to RAM
        # frees nothing) — reference local_object_manager.cc:161-334 spills
        # to external storage via _private/external_storage.py.
        if spill_dir is None:
            import tempfile
            spill_dir = os.path.join(
                tempfile.gettempdir(),
                "ray_tpu_spill_" + os.path.basename(session_dir.rstrip("/")))
        self.spill_dir = spill_dir
        os.makedirs(self.spill_dir, exist_ok=True)
        self.capacity = capacity_bytes
        self.used = 0
        self.num_spilled = 0
        self.num_restored = 0
        self._objects: Dict[str, _Entry] = {}
        # chaos evictions deferred because a reader lease was live; the
        # delete fires when the last lease releases (unpin)
        self._deferred_evict: set = set()
        # arena blocks of deleted/replaced entries that still had reader
        # leases: oid -> [[offset, remaining_leases], ...]. Releasing
        # them would rewrite memory under live zero-copy views, so they
        # are held until their leases drain through unpin().
        self._orphans: Dict[str, List[List[int]]] = {}
        self._quarantine: List[Tuple[float, int]] = []  # (freed_at, offset)
        # in-flight pull dedup: oid -> Event set when the transfer ends
        # (N concurrent pulls of one object must stream it ONCE)
        self._pulls_in_flight: Dict[str, threading.Event] = {}
        self._lock = TracedLock("object_store")
        self._sealed_cv = threading.Condition(self._lock)
        self._pool = rpc_lib.ClientPool(timeout=60)

        # Native arena (reference PlasmaAllocator); None → file layout.
        self.arena = None
        self.arena_path = os.path.join(self.dir, "arena")
        try:
            from ray_tpu.native import NativeArena
            self.arena = NativeArena(self.arena_path,
                                     capacity=capacity_bytes)
        except Exception as e:  # noqa: BLE001 - no toolchain: fall back
            logger.info("native arena unavailable (%s); using "
                        "file-per-object store", e)

        self.server = rpc_lib.RpcServer({
            "store_create": self.create,
            "store_seal": self.seal,
            "store_wait": self.wait,
            "store_contains": self.contains,
            "store_delete": self.delete,
            "store_pin": self.pin,
            "store_unpin": self.unpin,
            "store_read_chunk": self.read_chunk,
            "store_pull": self.pull,
            "store_put_raw": self.put_raw,
            "store_put_segments": self.put_segments,
            "store_register": self.register_sealed,
            "store_arena_info": self.arena_info,
            "store_chaos_evict": self.chaos_evict,
            "store_stats": self.stats,
            "store_list": self.list_objects,
        }, host=host)
        self.address = self.server.address

    # -- layout helpers ------------------------------------------------

    def _descriptor(self, e: _Entry) -> Tuple:
        if e.offset is not None:
            return ("arena", self.arena_path, e.offset, e.size)
        return ("file", e.path, e.size)

    def _payload_view(self, e: _Entry) -> memoryview:
        assert e.offset is not None
        return self.arena.view(e.offset, e.size)

    # -- space management ----------------------------------------------

    # Freed arena blocks sit in a time-quarantine before real reuse: a
    # reader may still hold a zero-copy view of the region (plasma solves
    # this with a client release protocol; the quarantine bounds the
    # hazard window instead). Holding the ObjectRef remains the
    # guaranteed-safe contract for long-lived zero-copy values.
    ARENA_FREE_DELAY_S = 10.0

    def _arena_release_locked(self, offset: int) -> None:
        self._quarantine.append((time.monotonic(), offset))

    def _drain_quarantine_locked(self, force: bool = False) -> None:
        now = time.monotonic()
        keep = []
        for t, off in self._quarantine:
            if force or now - t >= self.ARENA_FREE_DELAY_S:
                try:
                    self.arena.free(off)
                except ValueError:
                    pass
            else:
                keep.append((t, off))
        self._quarantine = keep

    def _eviction_order_locked(self) -> List[str]:
        """Victim order, computed ONCE per space request: LRU unpinned
        replicas first (dropped), then LRU pinned primaries (spilled).
        Leased entries are untouchable — a reader holds a zero-copy view
        of the block, so dropping OR spilling it (both release the arena
        offset) would rewrite memory under a live array."""
        unpinned = sorted(
            ((e.last_access, oid) for oid, e in self._objects.items()
             if e.sealed and e.pinned == 0 and e.leases == 0
             and not e.spilled))
        pinned = sorted(
            ((e.last_access, oid) for oid, e in self._objects.items()
             if e.sealed and e.pinned > 0 and e.leases == 0
             and not e.spilled))
        return [oid for _, oid in unpinned] + [oid for _, oid in pinned]

    def _evict_next_locked(self, order: List[str]) -> bool:
        while order:
            oid = order.pop(0)
            e = self._objects.get(oid)
            if e is None or e.spilled or not e.sealed:
                continue
            if e.pinned == 0:
                self._delete_locked(oid)
            else:
                self._spill_locked(oid)
            return True
        return False

    def _evict_until(self, needed: int,
                     order: Optional[List[str]] = None) -> None:
        """Free shm space (reference eviction_policy.h LRU +
        local_object_manager.cc:161-334 SpillObjects)."""
        if self.used + needed <= self.capacity:
            return
        if order is None:
            order = self._eviction_order_locked()
        while self.used + needed > self.capacity:
            if not self._evict_next_locked(order):
                raise ObjectStoreFullError(
                    f"object store full: need {needed}, used "
                    f"{self.used}/{self.capacity}")

    def _alloc_locked(self, size: int) -> int:
        """Arena allocation with eviction on both capacity pressure and
        fragmentation (alloc can fail below capacity when no contiguous
        block fits)."""
        self._drain_quarantine_locked()
        order = self._eviction_order_locked()
        self._evict_until(size, order)
        off = self.arena.alloc(size)
        while off == 0:
            if not self._evict_next_locked(order):
                # last resort: reclaim quarantined blocks early
                self._drain_quarantine_locked(force=True)
                off = self.arena.alloc(size)
                if off:
                    return off
                raise ObjectStoreFullError(
                    f"object store fragmented/full allocating {size} "
                    f"(used {self.used}/{self.capacity})")
            off = self.arena.alloc(size)
        return off

    def _spill_locked(self, object_id: str) -> None:
        e = self._objects.get(object_id)
        if e is None or not e.sealed or e.spilled:
            return
        spill_path = os.path.join(self.spill_dir, object_id)
        with open(spill_path, "wb") as dst:
            if e.offset is not None:
                dst.write(self._payload_view(e))
            else:
                with open(e.path, "rb") as src:
                    while True:
                        chunk = src.read(CHUNK_SIZE)
                        if not chunk:
                            break
                        dst.write(chunk)
        if e.offset is not None:
            self._arena_release_locked(e.offset)
            e.offset = None
        elif e.path:
            try:
                os.unlink(e.path)
            except OSError:
                pass
        e.path = spill_path
        e.spilled = True
        self.used -= e.size
        self.num_spilled += 1

    def _restore_locked(self, object_id: str) -> None:
        """Bring a spilled object back into shm (reference
        RestoreSpilledObject)."""
        e = self._objects.get(object_id)
        if e is None or not e.spilled:
            return
        spill_path = e.path
        if self.arena is not None:
            off = self._alloc_locked(e.size)
            with open(spill_path, "rb") as src:
                view = self.arena.view(off, e.size)
                src.readinto(view)  # type: ignore[arg-type]
            e.offset = off
            e.path = None
        else:
            self._evict_until(e.size)
            shm_path = os.path.join(self.dir, object_id)
            with open(spill_path, "rb") as src, \
                    open(shm_path, "wb") as dst:
                while True:
                    chunk = src.read(CHUNK_SIZE)
                    if not chunk:
                        break
                    dst.write(chunk)
            e.path = shm_path
        try:
            os.unlink(spill_path)
        except OSError:
            pass
        e.spilled = False
        e.last_access = time.time()
        self.used += e.size
        self.num_restored += 1

    def _delete_locked(self, object_id: str) -> None:
        e = self._objects.pop(object_id, None)
        self._deferred_evict.discard(object_id)
        if e is None:
            return
        if not e.spilled:
            self.used -= e.size
        if e.offset is not None:
            if e.leases > 0:
                # a reader still holds zero-copy views of this block
                # (owner freed before unpin, or the id was re-created):
                # orphan it until the leases drain rather than recycling
                # memory under live arrays
                self._orphans.setdefault(object_id, []).append(
                    [e.offset, e.leases])
            else:
                self._arena_release_locked(e.offset)
        elif e.path:
            try:
                os.unlink(e.path)
            except OSError:
                pass

    # -- lifecycle -------------------------------------------------------

    def create(self, object_id: str, size: int, pin: bool = True) -> Tuple:
        """Allocate backing space; returns the location descriptor.

        Primary (owner-written) copies are created pinned so LRU eviction
        can't drop an object the owner still references; delete() (driven
        by the owner's refcount) removes them. Pulled replica copies are
        created unpinned and evictable (the primary exists elsewhere).
        """
        # no dedicated span: RPC creates are visible as
        # rpc.server(store_create); fast-path client creates sit inside
        # cw.store_value — a third record would only add recorder cost
        chaos_lib.on_store_op("store_create", [object_id], self)
        with self._lock:
            if object_id in self._objects:
                e = self._objects[object_id]
                if e.size == size and not e.spilled:
                    return self._descriptor(e)
                # Same id re-created with a different payload size (lineage
                # re-execution of a nondeterministic task) or a spilled
                # entry being rewritten: replace the backing space.
                self._delete_locked(object_id)
            if self.arena is not None:
                off = self._alloc_locked(size)
                entry = _Entry(size=size, offset=off,
                               pinned=1 if pin else 0)
            else:
                self._evict_until(size)
                path = os.path.join(self.dir, object_id)
                with open(path, "wb") as f:
                    f.truncate(max(size, 1))
                entry = _Entry(size=size, path=path,
                               pinned=1 if pin else 0)
            self._objects[object_id] = entry
            self.used += size
            return self._descriptor(entry)

    def put_raw(self, object_id: str, data: bytes, pin: bool = False) -> None:
        """Create + write + seal in one RPC (remote pushes, small writers)."""
        self.create(object_id, len(data), pin=pin)
        with self._lock:
            e = self._objects.get(object_id)
            if e is not None and e.offset is not None:
                self._payload_view(e)[:len(data)] = data
            elif e is not None:
                with open(e.path, "r+b") as f:
                    f.write(data)
        self.seal(object_id)

    def put_segments(self, object_id: str, segments: List[bytes],
                     pin: bool = False) -> None:
        """Scatter variant of put_raw: the segments land back-to-back in
        one allocation without the caller ever joining them into a
        single bytes object."""
        total = sum(len(s) for s in segments)
        self.create(object_id, total, pin=pin)
        with self._lock:
            e = self._objects.get(object_id)
            if e is not None and e.offset is not None:
                view = self._payload_view(e)
                off = 0
                for s in segments:
                    view[off:off + len(s)] = s
                    off += len(s)
            elif e is not None:
                with open(e.path, "r+b") as f:
                    for s in segments:
                        f.write(s)
        self.seal(object_id)

    def seal(self, object_id: str) -> None:
        with self._sealed_cv:
            e = self._objects.get(object_id)
            if e is None:
                raise KeyError(f"seal of unknown object {object_id}")
            e.sealed = True
            e.creating = False
            e.last_access = time.time()
            self._sealed_cv.notify_all()

    def arena_info(self) -> Optional[str]:
        """Arena path for client-side fast-path allocation (None in the
        file-per-object fallback layout)."""
        return self.arena_path if self.arena is not None else None

    def register_sealed(self, object_id: str, offset: int, size: int,
                        pin: bool = True) -> None:
        """Adopt a client-allocated, already-written arena block as a
        sealed object (the scatter-write put fast path: the client
        allocs straight from the process-shared arena, writes the
        envelope, and this one-way notification replaces the
        create+seal round trips). The store_create chaos hook fires in
        the CLIENT for this path (see StoreClient.create) so error
        rules propagate to the writer and fire counts stay per-create."""
        with self._sealed_cv:
            e = self._objects.get(object_id)
            if e is not None:
                if e.offset == offset and e.size == size:
                    return  # duplicate register (oneway resend): no-op
                # re-created id (lineage re-execution): replace backing
                self._delete_locked(object_id)
            self._objects[object_id] = _Entry(
                size=size, offset=offset, pinned=1 if pin else 0,
                sealed=True, creating=False)
            self.used += size
            self._sealed_cv.notify_all()

    def wait(self, object_ids: List[str], timeout: Optional[float] = None,
             num_required: Optional[int] = None,
             pin: bool = False) -> Dict[str, Tuple]:
        """Block until objects are sealed locally; returns {id: descriptor}.
        Objects not present locally are NOT fetched here (see pull).
        pin=True takes one reader lease per returned object (release
        with unpin) so the descriptors stay valid as zero-copy views."""
        chaos_lib.on_store_op("store_wait", list(object_ids), self)
        deadline = None if timeout is None else time.monotonic() + timeout
        num_required = len(object_ids) if num_required is None else num_required
        # span only when the wait actually BLOCKED: that is the signal
        # this op exists to expose, and already-sealed lookups (the
        # trajectory-plane common case) stay recorder-free
        _t0 = _spans.begin()
        blocked = [False]
        try:
            return self._wait_impl(object_ids, deadline, num_required,
                                   pin, blocked)
        finally:
            if blocked[0]:
                _spans.end("store.wait", _t0, n=len(object_ids))

    def _wait_impl(self, object_ids: List[str],
                   deadline: Optional[float], num_required: int,
                   pin: bool, blocked: List[bool]) -> Dict[str, Tuple]:
        with self._sealed_cv:
            while True:
                ready = {}
                for oid in object_ids:
                    e = self._objects.get(oid)
                    if e is not None and e.sealed:
                        if e.spilled:
                            self._restore_locked(oid)
                        e.last_access = time.time()
                        ready[oid] = self._descriptor(e)
                if len(ready) >= num_required:
                    if pin:
                        for oid in ready:
                            _ownership.store_lease(self._objects[oid],
                                                   oid)
                    return ready
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    if pin:
                        for oid in ready:
                            _ownership.store_lease(self._objects[oid],
                                                   oid)
                    return ready
                blocked[0] = True
                self._sealed_cv.wait(timeout=min(remaining or 1.0, 1.0))

    def contains(self, object_id: str) -> bool:
        with self._lock:
            e = self._objects.get(object_id)
            return e is not None and e.sealed

    def delete(self, object_ids: List[str]) -> None:
        with self._lock:
            for oid in object_ids:
                self._delete_locked(oid)

    def chaos_evict(self, object_glob: Optional[str],
                    op_object_ids: List[str]) -> int:
        """Actuator for `evict_object` chaos rules: drop matching sealed
        objects from this store even if pinned (simulating loss of the
        primary, the case lineage reconstruction exists for). With no
        glob, the objects named in the triggering op are evicted."""
        import fnmatch as _fnmatch
        deferred = 0
        with self._lock:
            if object_glob:
                victims = [oid for oid in self._objects
                           if _fnmatch.fnmatchcase(oid, object_glob)]
            else:
                victims = [oid for oid in op_object_ids
                           if oid in self._objects]
            for oid in victims:
                e = self._objects.get(oid)
                if e is not None and e.leases > 0:
                    # a reader holds a zero-copy view: deleting now would
                    # rewrite memory under a live array. Defer the
                    # eviction to the last unpin (the fault still lands,
                    # just after the lease contract is honored).
                    self._deferred_evict.add(oid)
                    deferred += 1
                else:
                    self._delete_locked(oid)
        if victims:
            logger.warning("chaos: evicted %d object(s) (%d deferred to "
                           "unpin) from store %s",
                           len(victims) - deferred, deferred, self.address)
        return len(victims)

    def pin(self, object_id: str) -> None:
        """Take a reader lease: while held, the object is not dropped,
        spilled, or chaos-evicted (its zero-copy views stay valid)."""
        with self._lock:
            e = self._objects.get(object_id)
            if e is not None:
                _ownership.store_lease(e, object_id)

    def unpin(self, object_id: str, count: int = 1) -> None:
        """Release reader lease(s); fires any chaos eviction deferred
        while the object was leased. Leases on orphaned blocks (the
        entry was deleted or its id re-created while leased) drain
        first — the caller's leases were taken on that older block."""
        with self._lock:
            orph = self._orphans.get(object_id)
            while count > 0 and orph:
                rec = orph[0]
                take = min(count, rec[1])
                rec[1] -= take
                count -= take
                if rec[1] == 0:
                    self._arena_release_locked(rec[0])
                    orph.pop(0)
            if orph is not None and not orph:
                self._orphans.pop(object_id, None)
            if count <= 0:
                return
            e = self._objects.get(object_id)
            if e is None:
                self._deferred_evict.discard(object_id)
                return
            _ownership.store_unlease(e, object_id, count)
            if e.leases == 0 and object_id in self._deferred_evict:
                self._deferred_evict.discard(object_id)
                self._delete_locked(object_id)

    # -- node-to-node transfer --------------------------------------------

    def read_chunk(self, object_id: str, offset: int, length: int) -> bytes:
        with self._lock:
            e = self._objects.get(object_id)
            if e is None or not e.sealed:
                raise KeyError(f"read_chunk: {object_id} not sealed here")
            e.last_access = time.time()
            length = min(length, e.size - offset)
            if e.offset is not None:
                return bytes(self.arena.view(e.offset + offset, length))
            path = e.path
        with open(path, "rb") as f:
            f.seek(offset)
            return f.read(length)

    def pull(self, object_id: str, from_store: Tuple[str, int],
             size: int, lease: bool = False) -> Tuple:
        """Pull an object from a peer store into this one (chunked).
        lease=True takes a reader lease on the local replica so the
        returned descriptor is safe for zero-copy views until unpin.
        reference parity: pull_manager.h / push_manager.h chunk streaming."""
        chaos_lib.on_store_op("store_pull", [object_id], self)
        with _spans.span("store.pull", bytes=size):
            return self._pull_impl(object_id, from_store, size,
                                           lease)

    def _pull_impl(self, object_id: str,
                           from_store: Tuple[str, int], size: int,
                           lease: bool) -> Tuple:
        while True:
            with self._lock:
                e = self._objects.get(object_id)
                if e is not None and e.sealed:
                    if e.spilled:
                        # a complete local copy exists on disk: restore
                        # it instead of refetching (the peer may have
                        # evicted its copy)
                        self._restore_locked(object_id)
                        e = self._objects[object_id]
                    if lease:
                        _ownership.store_lease(e, object_id)
                    return self._descriptor(e)
                in_flight = self._pulls_in_flight.get(object_id)
                if in_flight is None:
                    self._pulls_in_flight[object_id] = threading.Event()
                    break
            # another thread is streaming this object: wait, then re-check
            in_flight.wait(timeout=300)
        try:
            return self._pull_stream(object_id, from_store, size,
                                     lease=lease)
        finally:
            with self._lock:
                ev = self._pulls_in_flight.pop(object_id, None)
            if ev is not None:
                ev.set()

    def _pull_stream(self, object_id: str, from_store: Tuple[str, int],
                     size: int, lease: bool = False) -> Tuple:
        expected = self.create(object_id, size, pin=False)
        client = self._pool.get(tuple(from_store))
        off = 0
        while off < size:
            chunk = client.call("store_read_chunk", object_id=object_id,
                                offset=off, length=CHUNK_SIZE)
            if not chunk:
                raise IOError(f"short read pulling {object_id}")
            with self._lock:
                e = self._objects.get(object_id)
                if e is None or self._descriptor(e) != expected:
                    # deleted or re-created (different allocation) while
                    # we streamed: writing at the old offsets would land
                    # inside other objects' blocks
                    raise KeyError(f"{object_id} replaced mid-pull")
                if e.offset is not None:
                    self.arena.view(e.offset + off, len(chunk))[:] = chunk
                else:
                    with open(e.path, "r+b") as f:
                        f.seek(off)
                        f.write(chunk)
            off += len(chunk)
        self.seal(object_id)
        with self._lock:
            e = self._objects[object_id]
            if lease:
                _ownership.store_lease(e, object_id)
            return self._descriptor(e)

    def list_objects(self) -> List[Dict[str, Any]]:
        """Object-level metadata for the state API (`ray list objects`
        and the memory plane's residency join, memory_plane.py)."""
        now = time.monotonic()
        with self._lock:
            return [{"object_id": oid, "size": e.size, "sealed": e.sealed,
                     "pinned": e.pinned, "leases": e.leases,
                     "spilled": e.spilled,
                     "age_s": max(0.0, now - e.created_mono)}
                    for oid, e in self._objects.items()]

    def stats(self) -> Dict[str, float]:
        with self._lock:
            return {"used": self.used, "capacity": self.capacity,
                    "num_objects": len(self._objects),
                    "num_leased": sum(1 for e in self._objects.values()
                                      if e.leases > 0),
                    # eviction-exempt bytes (pins + reader leases): the
                    # watchdog's occupancy probe compares these against
                    # used/capacity — pinned > used means the pin/lease
                    # accounting leaked
                    "pinned_bytes": sum(
                        e.size for e in self._objects.values()
                        if (e.pinned > 0 or e.leases > 0)
                        and not e.spilled),
                    "num_spilled": self.num_spilled,
                    "num_restored": self.num_restored,
                    "native_arena": self.arena is not None}

    def shutdown(self) -> None:
        self.server.stop()
        with self._lock:
            for oid in list(self._objects):
                self._delete_locked(oid)
        if self.arena is not None:
            self.arena.close()
            try:
                os.unlink(self.arena_path)
            except OSError:
                pass
        import shutil as _shutil
        _shutil.rmtree(self.spill_dir, ignore_errors=True)


class StoreClient:
    """Per-process client: RPC for lifecycle, direct shared memory for
    payloads (one arena mapping per store instead of one mmap per object)."""

    def __init__(self, store_address: Tuple[str, int]):
        self.address = tuple(store_address)
        self._rpc = rpc_lib.RpcClient(self.address, timeout=None)
        self._lock = TracedLock("store_client")
        self._arenas: Dict[str, Any] = {}     # arena path -> NativeArena
        # file-layout fallback: object id -> (mmap, view, inode)
        self._maps: Dict[str, Tuple[mmap.mmap, memoryview, int]] = {}
        # fast-path put state: the server's arena path ("" = file-layout
        # server, None = not asked yet) and blocks we allocated directly
        # from the process-shared arena but have not registered yet
        self._fast_arena_path: Optional[str] = None
        self._fast_pending: Dict[str, Tuple[int, int]] = {}

    # -- descriptor resolution ----------------------------------------

    def _arena(self, path: str):
        with self._lock:
            a = self._arenas.get(path)
            if a is None:
                from ray_tpu.native import NativeArena
                a = NativeArena(path)
                self._arenas[path] = a
            return a

    def _view(self, object_id: str, desc: Tuple,
              writable: bool = False) -> memoryview:
        if desc[0] == "arena":
            _, path, offset, size = desc
            view = self._arena(path).view(offset, size)
            # Readers get read-only views: a stored object is immutable,
            # and a writable alias would let one consumer corrupt the
            # arrays every other consumer (zero-copy) reads.
            return view if writable else view.toreadonly()
        _, path, size = desc
        return self._map_file(object_id, path, size, writable)

    def _map_file(self, object_id: str, path: str, size: int,
                  writable: bool = False) -> memoryview:
        with self._lock:
            # The inode detects a deleted-and-recreated object id (e.g.
            # lineage re-execution after eviction): a cached map would
            # point at the dead unlinked inode.
            inode = os.stat(path).st_ino
            cached = self._maps.get(object_id)
            if cached is not None:
                if cached[2] == inode:
                    return cached[1]
                self._release_locked(object_id)
            fd = os.open(path, os.O_RDWR if writable else os.O_RDONLY)
            try:
                mm = mmap.mmap(fd, max(size, 1),
                               prot=(mmap.PROT_READ | mmap.PROT_WRITE)
                               if writable else mmap.PROT_READ)
            finally:
                os.close(fd)
            view = memoryview(mm)[:size]
            self._maps[object_id] = (mm, view, inode)
            return view

    # -- lifecycle ------------------------------------------------------

    def _fast_arena(self):
        """The server's arena, attachable for client-side allocation
        (the allocator's lock is process-shared); None when the server
        runs the file-per-object fallback or the native lib is missing
        locally."""
        if self._fast_arena_path is None:
            try:
                # "" caches an authoritative no-arena answer; a transient
                # RPC failure leaves None so the next put re-probes
                # instead of silently pinning this process to the slow
                # path forever
                self._fast_arena_path = self._rpc.call(
                    "store_arena_info") or ""
            except Exception:  # noqa: BLE001 - transient: retry later
                return None
        if not self._fast_arena_path:
            return None
        try:
            return self._arena(self._fast_arena_path)
        except Exception:  # noqa: BLE001 - no local native toolchain
            self._fast_arena_path = ""
            return None

    def shared_arena(self):
        """Public handle on the node-local process-shared arena (or
        None): the same mapping the put fast path allocates from, reused
        by the shm task channel (_private/shm_channel.py) for same-node
        control messages — its allocator lock is process-shared, so any
        local process may alloc and any other may free."""
        return self._fast_arena()

    def create(self, object_id: str, size: int) -> memoryview:
        """Writable block for a new object. Fast path: allocate straight
        from the process-shared arena — no RPC; seal() then registers
        the block with one one-way message, so a put costs zero round
        trips. Falls back to the server's create RPC when the arena is
        unavailable or full (the server can evict/spill; we can't)."""
        arena = self._fast_arena()
        if arena is not None:
            off = arena.alloc(size)
            if off:
                # chaos parity with the server-side create hook, fired
                # exactly once per create (only after committing to this
                # path — an alloc failure falls through to the RPC
                # create, whose handler fires the hook instead). Evict
                # rules actuate on the server through this client's
                # chaos_evict proxy.
                try:
                    chaos_lib.on_store_op("store_create", [object_id],
                                          self)
                except Exception:
                    try:
                        arena.free(off)
                    except ValueError:
                        pass
                    raise
                with self._lock:
                    self._fast_pending[object_id] = (off, size)
                return arena.view(off, max(size, 1))
        desc = self._rpc.call("store_create", object_id=object_id,
                              size=size)
        return self._view(object_id, desc, writable=True)

    def seal(self, object_id: str) -> None:
        with self._lock:
            fast = self._fast_pending.pop(object_id, None)
        # One-way sends: sealing/registering only flips server metadata
        # + notifies waiters, and same-socket ordering guarantees our
        # own later store RPCs observe it — dropping the reply round
        # trip makes a put RPC-free on the fast path. Durability: a
        # send failure (including a chaos drop_connection, which raises
        # in the client hook before anything is sent) surfaces HERE as
        # an exception, so the put fails loudly; a frame accepted by
        # the kernel is only lost if the store process dies, which
        # loses the whole store and lands in the existing
        # ObjectLostError/lineage path anyway.
        if fast is not None:
            off, size = fast
            self._rpc.send_oneway("store_register", object_id=object_id,
                                  offset=off, size=size)
            return
        self._rpc.send_oneway("store_seal", object_id=object_id)

    def put_raw(self, object_id: str, data: bytes) -> None:
        self.put_segments(object_id, [data])

    def put_segments(self, object_id: str, segments: List[Any]) -> None:
        """Scatter-write pre-serialized parts as one object. Large
        payloads are written straight into the shm mapping (no joined
        intermediate bytes); small ones ride a single put_raw RPC."""
        total = sum(len(s) for s in segments)
        if total > CHUNK_SIZE:
            buf = self.create(object_id, total)
            try:
                off = 0
                for s in segments:
                    buf[off:off + len(s)] = s
                    off += len(s)
                self.seal(object_id)
            except BaseException:
                self.abort_create(object_id)
                raise
        elif len(segments) == 1:
            self._rpc.call("store_put_raw", object_id=object_id,
                           data=bytes(segments[0]))
        else:
            self._rpc.call("store_put_segments", object_id=object_id,
                           segments=[bytes(s) for s in segments])

    def get(self, object_ids: List[str], timeout: Optional[float] = None,
            pin: bool = False) -> Dict[str, memoryview]:
        """Zero-copy views of sealed local objects (ONE store_wait RPC
        for the whole batch). pin=True leases every returned object so
        the views outlive LRU pressure; release with unpin()."""
        descs = self._rpc.call("store_wait", object_ids=object_ids,
                               timeout=timeout, pin=pin)
        return {oid: self._view(oid, desc)
                for oid, desc in descs.items()}

    def contains(self, object_id: str) -> bool:
        return self._rpc.call("store_contains", object_id=object_id)

    def chaos_evict(self, object_glob: Optional[str],
                    op_object_ids: List[str]) -> int:
        """Actuator proxy for chaos rules that fire in THIS process
        (fast-path create): forwards the eviction to the store server,
        which owns the objects."""
        return self._rpc.call("store_chaos_evict",
                              object_glob=object_glob,
                              op_object_ids=list(op_object_ids))

    def abort_create(self, object_id: str) -> None:
        """Undo a create whose write/seal failed, so the backing space
        is reclaimed instead of leaking: fast-path blocks are freed
        straight back to the arena (the server never knew), RPC-created
        entries are deleted server-side."""
        with self._lock:
            fast = self._fast_pending.pop(object_id, None)
        if fast is not None:
            arena = self._fast_arena()
            if arena is not None:
                try:
                    arena.free(fast[0])
                except ValueError:
                    pass
            return
        try:
            self._rpc.call("store_delete", object_ids=[object_id])
        except Exception:  # noqa: BLE001 - server gone; nothing to free
            pass

    def pin(self, object_id: str) -> None:
        self._rpc.call("store_pin", object_id=object_id)

    def unpin(self, object_id: str, count: int = 1) -> None:
        self._rpc.call("store_unpin", object_id=object_id, count=count)

    def pull(self, object_id: str, from_store: Tuple[str, int], size: int,
             pin: bool = False) -> memoryview:
        """Zero-copy view of a replica pulled from a peer store. With
        pin=True the replica is leased (LRU/chaos eviction defer) until
        unpin() — the contract long-lived consumers must use. Unpinned
        callers rely on the arena free-quarantine bounding the reuse
        hazard (fine for transient reads like prefetch or immediate
        copies)."""
        desc = self._rpc.call("store_pull", object_id=object_id,
                              from_store=tuple(from_store), size=size,
                              lease=pin)
        return self._view(object_id, desc)

    def delete(self, object_ids: List[str]) -> None:
        self._release(object_ids)
        self._rpc.call("store_delete", object_ids=object_ids)

    def release_views(self, object_ids: List[str]) -> None:
        """Drop this client's mmap views only — a purely local cleanup
        with no RPC, safe to call under caller locks. The server-side
        delete is a separate (blocking) RPC; callers holding locks
        queue it onto an off-lock drainer instead (core_worker's
        borrow-release loop)."""
        self._release(object_ids)

    def _release_locked(self, oid: str) -> None:
        m = self._maps.pop(oid, None)
        if m is not None:
            try:
                m[1].release()
                m[0].close()
            except (BufferError, ValueError):
                pass  # a live numpy view still references the map

    def _release(self, object_ids: List[str]) -> None:
        with self._lock:
            for oid in object_ids:
                self._release_locked(oid)

    def stats(self) -> Dict[str, float]:
        return self._rpc.call("store_stats")

    def close(self) -> None:
        self._rpc.close()
        with self._lock:
            arenas = list(self._arenas.values())
            self._arenas.clear()
        for a in arenas:
            try:
                a.close()
            except Exception:  # noqa: BLE001 - arena already unmapped
                pass
