"""Cluster scheduling policies.

reference parity: src/ray/raylet/scheduling/policy/ — hybrid (pack with
spill-over past a utilization threshold, hybrid_scheduling_policy.cc), spread
(spread_scheduling_policy.cc), node-affinity
(node_affinity_scheduling_policy.h) and placement-group bundle placement
(bundle_scheduling_policy.cc). Operates on a {node_id: {resource: available}}
view synced through the GCS (reference syncs via RaySyncer).
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional

from ray_tpu._private.state import (DefaultSchedulingStrategy,
                                    NodeAffinitySchedulingStrategy,
                                    NodeLabelSchedulingStrategy,
                                    PlacementGroupSchedulingStrategy,
                                    ResourceSet, SchedulingStrategy,
                                    SpreadSchedulingStrategy)

# reference ray_config_def.h: scheduler_spread_threshold (0.5): prefer the
# local/first node until its utilization crosses this, then best-fit spill.
SPREAD_THRESHOLD = 0.5


def _feasible(avail: Dict[str, float], required: ResourceSet) -> bool:
    return required.is_subset_of(ResourceSet(avail))


def _utilization(total: ResourceSet, avail: Dict[str, float]) -> float:
    util = 0.0
    for k, tot in total.to_dict().items():
        if tot > 0:
            util = max(util, 1.0 - min(ResourceSet(avail).get(k) / tot, 1.0))
    return util


def _labels_match(node_labels: Dict[str, str],
                  constraints: Dict[str, List[str]]) -> bool:
    for key, allowed in constraints.items():
        if key not in node_labels:
            return False
        if allowed and "" not in allowed and \
                node_labels[key] not in allowed:
            return False
    return True


def pick_node(view: Dict[str, Dict[str, float]], required: ResourceSet,
              strategy: SchedulingStrategy,
              local_node_id: Optional[str] = None,
              totals: Optional[Dict[str, Dict[str, float]]] = None,
              rng: Optional[random.Random] = None,
              locality_hints: Optional[Dict[str, float]] = None,
              labels: Optional[Dict[str, Dict[str, str]]] = None
              ) -> Optional[str]:
    """Return the chosen node id hex, or None if nothing feasible now."""
    feasible = [nid for nid, avail in view.items() if _feasible(avail, required)]
    if not feasible:
        return None
    feasible.sort()  # determinism

    if isinstance(strategy, NodeLabelSchedulingStrategy):
        # reference node_label_scheduling_policy.h: hard constraints
        # filter; soft constraints prefer.
        labels = labels or {}
        hard_ok = [n for n in feasible
                   if _labels_match(labels.get(n, {}), strategy.hard)]
        if not hard_ok:
            return None
        soft_ok = [n for n in hard_ok
                   if _labels_match(labels.get(n, {}), strategy.soft)]
        return (soft_ok or hard_ok)[0]

    # Object locality (reference lease_policy.h:56 LocalityAwareLeasePolicy
    # + scorer.h): among feasible nodes, prefer the one already holding
    # the most argument bytes — object-heavy pipelines (RL trajectories)
    # then read args from local shm instead of pulling across nodes.
    if locality_hints and isinstance(strategy, DefaultSchedulingStrategy):
        best = max(feasible, key=lambda n: locality_hints.get(n, 0.0))
        if locality_hints.get(best, 0.0) > 0.0:
            return best

    if isinstance(strategy, NodeAffinitySchedulingStrategy):
        if strategy.node_id in view and _feasible(view[strategy.node_id],
                                                  required):
            return strategy.node_id
        return feasible[0] if strategy.soft else None

    if isinstance(strategy, SpreadSchedulingStrategy):
        # round-robin-ish: least utilized first (reference spreads over
        # top-k least loaded)
        if totals:
            feasible.sort(key=lambda nid: _utilization(
                ResourceSet(totals.get(nid, view[nid])), view[nid]))
        else:
            (rng or random).shuffle(feasible)
        return feasible[0]

    if isinstance(strategy, PlacementGroupSchedulingStrategy):
        # Bundle-constrained placement resolved by the caller (bundle
        # resources appear as custom resources on the reserving node).
        return feasible[0]

    # Default/hybrid: prefer local while under the spread threshold, else
    # pick the best (most packed but feasible) node — reference
    # hybrid_scheduling_policy.cc.
    if local_node_id in feasible and totals is not None:
        local_util = _utilization(
            ResourceSet(totals.get(local_node_id, {})), view[local_node_id])
        if local_util < SPREAD_THRESHOLD:
            return local_node_id
    elif local_node_id in feasible:
        return local_node_id
    if totals:
        feasible.sort(key=lambda nid: (-_utilization(
            ResourceSet(totals.get(nid, view[nid])), view[nid]), nid))
        for nid in feasible:
            if _utilization(ResourceSet(totals.get(nid, view[nid])),
                            view[nid]) < 1.0 - 1e-9:
                return nid
    return feasible[0]


def pack_bundles(view: Dict[str, Dict[str, float]],
                 bundles: List[Dict[str, float]],
                 strategy: str) -> Optional[List[str]]:
    """Assign each bundle to a node; returns node id per bundle or None.

    reference parity: bundle_scheduling_policy.cc — PACK tries to co-locate,
    SPREAD distributes, STRICT_PACK requires one node, STRICT_SPREAD requires
    distinct nodes.
    """
    work = {nid: dict(avail) for nid, avail in view.items()}
    nids = sorted(work)

    def fits(nid: str, bundle: Dict[str, float]) -> bool:
        return ResourceSet(bundle).is_subset_of(ResourceSet(work[nid]))

    def take(nid: str, bundle: Dict[str, float]) -> None:
        avail = ResourceSet(work[nid])
        avail.subtract(ResourceSet(bundle))
        work[nid] = avail.to_dict()

    placement: List[Optional[str]] = [None] * len(bundles)

    if strategy == "STRICT_PACK":
        for nid in nids:
            if all(ResourceSet(_sum_bundles(bundles)).is_subset_of(
                    ResourceSet(work[nid])) for _ in (0,)):
                return [nid] * len(bundles)
        return None

    if strategy == "STRICT_SPREAD":
        if len(bundles) > len(nids):
            return None
        used: set = set()
        for i, b in enumerate(bundles):
            cand = [n for n in nids if n not in used and fits(n, b)]
            if not cand:
                return None
            placement[i] = cand[0]
            used.add(cand[0])
            take(cand[0], b)
        return placement  # type: ignore[return-value]

    # PACK / SPREAD: best effort
    order = nids if strategy == "PACK" else list(nids)
    for i, b in enumerate(bundles):
        if strategy == "SPREAD":
            order = sorted(nids, key=lambda n: -sum(work[n].values()))
        chosen = next((n for n in order if fits(n, b)), None)
        if chosen is None:
            return None
        placement[i] = chosen
        take(chosen, b)
    return placement  # type: ignore[return-value]


def _sum_bundles(bundles: List[Dict[str, float]]) -> Dict[str, float]:
    total = ResourceSet({})
    for b in bundles:
        total.add(ResourceSet(b))
    return total.to_dict()
