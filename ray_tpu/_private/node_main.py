"""Standalone node-manager process: `python -m ray_tpu._private.node_main`.

The multi-node entry point: joins an existing cluster by GCS address and
hosts a NodeManager (worker pool + local scheduler + shared-memory object
store) until terminated. The reference's equivalent is the raylet binary
spawned by services.py (reference: python/ray/_private/services.py:1485,
src/ray/raylet/main.cc:119); here the daemon is this Python process.

Used by ray_tpu.cluster_utils.Cluster (the reference
python/ray/cluster_utils.py:108 testing ladder: many node managers as
local processes sharing one GCS) and usable directly to join a real
second host:

    python -m ray_tpu._private.node_main \
        --gcs-address <head-ip>:<port> --resources '{"CPU": 8}'
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import tempfile
import time


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--gcs-address", required=True,
                        help="host:port of the cluster's GCS")
    parser.add_argument("--session-dir", default=None,
                        help="session directory (default: a fresh tmp dir)")
    parser.add_argument("--resources", default="{}",
                        help='JSON resource dict, e.g. \'{"CPU": 4}\'')
    parser.add_argument("--labels", default="{}",
                        help="JSON node-label dict")
    parser.add_argument("--object-store-memory", type=int, default=None)
    parser.add_argument("--host", default="127.0.0.1")
    args = parser.parse_args(argv)

    host, port = args.gcs_address.rsplit(":", 1)
    session_dir = args.session_dir
    if session_dir is None:
        base = "/dev/shm" if os.path.isdir("/dev/shm") \
            else tempfile.gettempdir()
        session_dir = os.path.join(
            base, f"ray_tpu_node_{int(time.time() * 1000)}_{os.getpid()}")
    os.makedirs(session_dir, exist_ok=True)

    from ray_tpu._private.node_manager import NodeManager

    nm = NodeManager(
        gcs_address=(host, int(port)), session_dir=session_dir,
        resources=json.loads(args.resources) or None,
        labels=json.loads(args.labels) or None, host=args.host,
        object_store_capacity=args.object_store_memory)

    # Handshake line for cluster_utils / operators (single line, parseable).
    print(json.dumps({  # graftlint: disable=RT012
        "node_id": nm.node_id.hex(),
        "node_manager_address": f"{nm.address[0]}:{nm.address[1]}",
        "store_address": nm.store.address,
        "session_dir": session_dir,
    }), flush=True)

    stopping = []
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stopping.append(1))
    try:
        while not stopping:
            time.sleep(0.1)
    finally:
        nm.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
