"""Per-node manager daemon: worker pool, local scheduler, object store host.

reference parity: src/ray/raylet/ — NodeManager (node_manager.h:125) with
ClusterTaskManager/LocalTaskManager lease scheduling
(scheduling/cluster_task_manager.cc:44, local_task_manager.cc:105),
WorkerPool (worker_pool.cc:1150), placement-group bundle resources
(placement_group_resource_manager.h), and the in-raylet plasma store host
(object_manager/plasma/store_runner.h). Leases are granted asynchronously
via a callback to the requesting core worker, mirroring the reference's
RequestWorkerLease reply flow (node_manager.proto:361).
"""

from __future__ import annotations

import logging
import os
import subprocess
import sys
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import ownership as _ownership
from ray_tpu._private import rpc as rpc_lib
from ray_tpu._private.config import Config
from ray_tpu._private.ids import NodeID, WorkerID, rand_bytes
from ray_tpu._private.object_store import StoreServer
from ray_tpu._private.scheduler import _labels_match, pick_node
from ray_tpu._private.state import (NodeAffinitySchedulingStrategy, NodeInfo,
                                    NodeLabelSchedulingStrategy,
                                    PlacementGroupSchedulingStrategy,
                                    ResourceSet, TaskSpec, TaskType)
from ray_tpu.util.locks import TracedLock

logger = logging.getLogger(__name__)


def pg_resource_name(resource: str, pg_id_hex: str, bundle_index: int = -1) -> str:
    """Bundle-scoped resource names (reference bundle_spec.h: e.g.
    CPU_group_0_<pgid> and CPU_group_<pgid>)."""
    if bundle_index >= 0:
        return f"{resource}_group_{bundle_index}_{pg_id_hex}"
    return f"{resource}_group_{pg_id_hex}"


def rewrite_resources_for_pg(resources: Dict[str, float], pg_id_hex: str,
                             bundle_index: int) -> Dict[str, float]:
    out = {}
    for r, v in resources.items():
        out[pg_resource_name(r, pg_id_hex, bundle_index)] = v
    # Always require a sliver of the wildcard resource so tasks can only run
    # on nodes holding a committed bundle of this group — and of the
    # *indexed* bundle resource when a bundle index was requested, so
    # zero-resource tasks/actors still pin to their bundle's node
    # (reference bundle_spec.h adds the indexed `bundle` resource too).
    out.setdefault(pg_resource_name("bundle", pg_id_hex), 0.001)
    if bundle_index >= 0:
        out.setdefault(pg_resource_name("bundle", pg_id_hex, bundle_index),
                       0.001)
    return out


@dataclass
class _WorkerHandle:
    worker_id: WorkerID
    proc: Optional[subprocess.Popen]
    address: Optional[Tuple[str, int]] = None
    runtime_env_key: str = ""
    idle_since: float = field(default_factory=time.monotonic)
    # Set while leased/executing
    lease_id: Optional[str] = None
    current_task: Optional[TaskSpec] = None
    task_started_at: float = 0.0
    is_actor: bool = False
    actor_id_hex: Optional[str] = None
    registered: bool = False
    blocked: bool = False  # released its resources while blocked in get


@dataclass
class _PendingLease:
    lease_id: str
    spec: TaskSpec
    reply_to: Tuple[str, int]    # requesting core worker's RPC address
    acquired: Optional[ResourceSet] = None
    submitted_at: float = field(default_factory=time.monotonic)
    # grant replies that failed transiently; bounded re-grants keep a
    # momentary connection blip from stranding the owner's parked
    # request forever (an owner that stays unreachable is dropped)
    grant_failures: int = 0


class NodeManager:
    def __init__(self, gcs_address: Tuple[str, int], session_dir: str,
                 resources: Optional[Dict[str, float]] = None,
                 is_head: bool = False, host: str = "127.0.0.1",
                 labels: Optional[Dict[str, str]] = None,
                 object_store_capacity: Optional[int] = None):
        self.node_id = NodeID.from_random()
        self.session_dir = session_dir
        self.gcs_address = tuple(gcs_address)
        from ray_tpu._private.runtime_env import RuntimeEnvManager
        self._runtime_env_mgr = RuntimeEnvManager()
        self._pool = rpc_lib.ClientPool(timeout=60)
        self._gcs = rpc_lib.RpcClient(self.gcs_address, timeout=60)
        self._lock = TracedLock("node_manager")
        self._dead = False

        if resources is None:
            resources = {}
        resources.setdefault("CPU", float(os.cpu_count() or 1))
        resources.setdefault("memory", float(64 << 30))
        resources.setdefault("object_store_memory",
                             float(Config.object_store_capacity_bytes))
        # Accelerator autodetection (TPU chips as `TPU` resource).
        from ray_tpu._private.accelerators import detect_node_accelerators
        for k, v in detect_node_accelerators().items():
            resources.setdefault(k, v)
        self.resources_total = ResourceSet(resources)
        # change-triggered resource sync (reference RaySyncer,
        # common/ray_syncer/ray_syncer.h:88 — raylets push resource
        # deltas to the GCS the moment they change over a streaming
        # channel, instead of the GCS discovering them at the next
        # poll): every add/subtract sets the dirty event the report
        # loop waits on; versioning makes stale reports droppable.
        self._resync_event = threading.Event()
        self._resource_version = 0

        class _SyncedResources(ResourceSet):
            __slots__ = ("_nm",)

            def add(rs, other):  # noqa: N805
                ResourceSet.add(rs, other)
                rs._nm._resync_event.set()

            def subtract(rs, other):  # noqa: N805
                ResourceSet.subtract(rs, other)
                rs._nm._resync_event.set()

        self.available = _SyncedResources(resources)
        self.available._nm = self

        node_store_dir = os.path.join(session_dir, self.node_id.hex()[:12])
        os.makedirs(node_store_dir, exist_ok=True)
        self.store = StoreServer(
            node_store_dir,
            object_store_capacity or Config.object_store_capacity_bytes,
            host=host)

        self.workers: Dict[str, _WorkerHandle] = {}     # worker id hex -> handle
        # worker id hex -> pre-kill flight data (span tail, rss) captured
        # by daemon-initiated kill paths while the victim still answers
        self._prekill_dumps: Dict[str, Dict[str, Any]] = {}
        # pids currently SIGSTOPped by chaos_stall_worker: keeps a rule
        # that keeps firing from stacking stalls on the same victim
        self._stalled: set = set()
        self.idle: Dict[str, List[str]] = {}            # runtime env key -> ids
        self.pending: List[_PendingLease] = []
        # lease id -> worker id hex; grant/release funnel through the
        # ownership protocol module so every NM-side lease transition
        # lands in the ring (`ray_tpu ownership`)
        self.leases = _ownership.NMLeases()
        self._starting = 0
        self._starting_by_key: Dict[str, int] = {}
        self.num_args_prefetched = 0
        self._prepared: Dict[Tuple[str, int], Dict[str, float]] = {}
        self._committed: Dict[Tuple[str, int], Tuple] = {}

        self.server = rpc_lib.RpcServer({
            "nm_ping": lambda: "pong",
            # chaos-policy pubsub lands here too (the GCS publishes to
            # subscriber addresses via this one method name)
            "cw_pubsub_push": self._on_pubsub_push,
            "nm_chaos_kill_worker": self.chaos_kill_worker,
            "nm_chaos_stall_worker": self.chaos_stall_worker,
            "nm_kill_worker_pid": self.kill_worker_pid,
            "nm_register_worker": self.register_worker,
            "nm_request_lease": self.request_lease,
            "nm_lease_request_batch": self.request_lease_batch,
            "nm_cancel_lease": self.cancel_lease,
            "nm_return_worker": self.return_worker,
            "nm_schedule_actor_creation": self.schedule_actor_creation,
            "nm_worker_blocked": self.worker_blocked,
            "nm_worker_unblocked": self.worker_unblocked,
            "nm_prepare_bundle": self.prepare_bundle,
            "nm_commit_bundle": self.commit_bundle,
            "nm_return_bundle": self.return_bundle,
            "nm_get_info": self.get_info,
            "nm_list_workers": self.list_workers,
            "nm_spans_snapshot": self.spans_snapshot,
            "nm_metrics_snapshot": self.metrics_snapshot,
            "nm_logs_snapshot": self.logs_snapshot,
            "nm_profile_worker": self.profile_worker,
            "nm_profile_workers": self.profile_workers,
            "nm_profile_collect": self.profile_collect,
            "nm_memory_snapshot": self.memory_snapshot,
            "nm_ownership_snapshot": self.ownership_snapshot,
            "nm_locks_snapshot": self.locks_snapshot,
            "nm_drain": self.drain,
        }, host=host)
        self.address = self.server.address

        from ray_tpu._private import spans as _spans_lib
        _spans_lib.set_process_label(f"raylet-{self.node_id.hex()[:8]}",
                                     node_id=self.node_id.hex())
        # node-level gauges (store occupancy, worker pool, lease queue)
        # exported at metrics-harvest time (_private/metrics_plane.py)
        from ray_tpu._private import metrics_plane as _metrics_plane
        _metrics_plane.register_sampler("node_manager",
                                        self._sample_metric_gauges)
        # held-alive store entries ride every metrics harvest so the
        # watchdog's leak probes can compare residency against live
        # owners' claims (memory_plane.py)
        from ray_tpu._private import memory_plane as _memory_plane
        _metrics_plane.register_snapshot_extra(
            _memory_plane.STORE_DIGEST_KEY, self._store_objects_digest)
        self.info = NodeInfo(
            node_id=self.node_id, address=self.address,
            store_address=self.store.address,
            resources_total=self.resources_total.to_dict(),
            labels=labels or {}, is_head=is_head)
        self._gcs.call("register_node", info=self.info)
        self._report_thread = threading.Thread(
            target=self._resource_report_loop, daemon=True,
            name=f"nm-report-{self.node_id.hex()[:6]}")
        self._report_thread.start()
        # OOM defense (reference memory_monitor.h + worker killing
        # policies): above the usage threshold, kill the newest retriable
        # normal task's worker — its owner retries it, and the node
        # survives instead of the kernel OOM-killing the daemon.
        from ray_tpu._private.memory_monitor import MemoryMonitor
        self.memory_monitor = MemoryMonitor(
            self._kill_worker_for_memory,
            threshold=Config.memory_usage_threshold,
            period_s=Config.memory_monitor_refresh_ms / 1000.0)
        # tail worker logs -> GCS "worker_logs" channel -> drivers
        # (reference _private/log_monitor.py)
        from ray_tpu._private.log_monitor import LogMonitor
        self.log_monitor = LogMonitor(
            os.path.join(self.session_dir, "logs"), self.gcs_address,
            self.node_id.hex())
        # Chaos plane (_private/chaos.py): this daemon is the kill_worker
        # actuator for rules targeting this node, and must track policy
        # updates (fetch now + follow the "chaos" pubsub channel).
        from ray_tpu._private import chaos as chaos_lib
        chaos_lib.client().set_context(node_id=self.node_id.hex(),
                                       gcs_address=self.gcs_address)
        chaos_lib.client().set_kill_actuator(self.chaos_kill_worker)
        chaos_lib.client().set_stall_actuator(self.chaos_stall_worker)
        chaos_lib.fetch_policy(self._gcs.call)
        self._chaos_token = uuid.uuid4().hex
        try:
            self._gcs.call("subscribe", channel="chaos",
                           address=self.address, token=self._chaos_token)
        except Exception:  # noqa: BLE001 - chaos updates degrade to fetch
            pass

    # ---- resource sync ---------------------------------------------------

    def _resource_report_loop(self) -> None:
        while not self._dead:
            try:
                # clear BEFORE snapshotting: a change landing during the
                # report re-sets the event and re-wakes immediately
                self._resync_event.clear()
                with self._lock:
                    avail = self.available.to_dict()
                    self._resource_version += 1
                    version = self._resource_version
                resp = self._gcs.call(
                    "report_resources",
                    node_id_hex=self.node_id.hex(), available=avail,
                    version=version)
                if resp == "unknown_node" and not self._dead:
                    # the GCS restarted (or declared us dead during a
                    # blip): re-register so scheduling resumes — but
                    # never resurrect a node that is itself shutting
                    # down. Follow with a fresh report so the GCS sees
                    # true availability, not resources_total.
                    logger.warning(
                        "GCS does not know node %s — re-registering",
                        self.node_id.hex()[:12])
                    self._gcs.call("register_node", info=self.info)
                    with self._lock:
                        avail = self.available.to_dict()
                    self._gcs.call(
                        "report_resources",
                        node_id_hex=self.node_id.hex(), available=avail)
            except Exception:  # noqa: BLE001 - the loop retries every
                # period; debug level because a down GCS would repeat
                # this every report tick
                logger.debug("resource report to GCS failed",
                             exc_info=True)
            try:
                self._respill_pending()
            except Exception:  # noqa: BLE001
                logger.warning("respill round failed", exc_info=True)
            try:
                self._reap_idle_workers()
            except Exception:  # noqa: BLE001
                logger.warning("idle reap failed", exc_info=True)
            # syncer semantics: wake IMMEDIATELY when availability
            # changes (lease grant/return, worker death), else
            # heartbeat at the poll period; the short sleep after a
            # wake coalesces bursts into one report
            if self._resync_event.wait(
                    timeout=Config.resource_report_period_s):
                time.sleep(0.02)

    def _reap_idle_workers(self) -> None:
        """Kill workers idle past idle_worker_kill_timeout_s while the
        pool exceeds its floor (reference worker_pool.cc
        TryKillingIdleWorkers: kill down to the soft limit only). Each
        candidate is asked first (cw_can_exit) — a worker that OWNS
        objects someone still references must not die, or those objects
        are lost with it."""
        timeout = Config.idle_worker_kill_timeout_s
        if timeout <= 0:
            return
        floor = max(0, int(Config.idle_worker_pool_floor))
        now = time.monotonic()
        candidates: List[_WorkerHandle] = []
        with self._lock:
            n_idle = sum(len(ids) for ids in self.idle.values())
            for ids in self.idle.values():
                for wid in list(ids):
                    if n_idle - len(candidates) <= floor:
                        break
                    h = self.workers.get(wid)
                    if h is not None and h.address is not None and \
                            now - h.idle_since > timeout:
                        candidates.append(h)
        for h in candidates:
            try:
                can_exit = self._pool.get(h.address).call("cw_can_exit")
            except Exception:  # noqa: BLE001 - unreachable == already dead
                can_exit = True
            if not can_exit:
                continue
            with self._lock:
                # it may have been leased since the scan; only reap if
                # still idle (remove from idle so it can't be re-leased,
                # then let _monitor_worker -> _on_worker_death do the
                # full cleanup every other kill path uses)
                ids = self.idle.get(h.runtime_env_key, [])
                if h.worker_id.hex() not in ids:
                    continue
                ids.remove(h.worker_id.hex())
            logger.info("reaping idle worker %s", h.worker_id.hex()[:12])
            if h.proc is not None:
                try:
                    h.proc.terminate()
                except OSError:
                    pass

    def _respill_pending(self) -> None:
        """Re-route queued leases that became feasible on another node
        (reference: ClusterTaskManager::ScheduleAndDispatchTasks re-runs
        cluster scheduling for queued work each round; without this, a
        lease queued before e.g. a PG bundle committed elsewhere would
        wait forever)."""
        with self._lock:
            candidates = [pl for pl in self.pending if pl.acquired is None]
        if not candidates:
            return
        avail, totals, nodes, labels = self._cluster_view()
        dispatch_local = False
        for pl in candidates:
            strategy = pl.spec.scheduling_strategy
            if isinstance(strategy, NodeAffinitySchedulingStrategy) \
                    and not strategy.soft:
                continue  # hard affinity: must stay here
            required = self._effective_resources(pl.spec)
            chosen = pick_node(avail, required, strategy,
                               local_node_id=self.node_id.hex(),
                               totals=totals,
                               locality_hints=pl.spec.locality_hints,
                               labels=labels)
            logger.debug("respill: %s required=%s chosen=%s",
                         pl.spec.function_name, required.to_dict(),
                         chosen and chosen[:12])
            if chosen is None or chosen == self.node_id.hex() \
                    or chosen not in nodes:
                # locally feasible again (e.g. resources appeared via a
                # path with no dispatch trigger of its own): grant it
                # here rather than leaving the queue to wedge
                if chosen == self.node_id.hex():
                    dispatch_local = True
                continue
            with self._lock:
                if pl not in self.pending or pl.acquired is not None:
                    continue
                self.pending.remove(pl)
            try:
                self._pool.get(pl.reply_to).call(
                    "cw_lease_respill", task_id=pl.spec.task_id,
                    nm_address=nodes[chosen],
                    # name ourselves so the owner unparks its request
                    # slot from the RIGHT node manager (entry state may
                    # have moved on if another grant picked the task up)
                    from_address=self.address)
            except Exception:  # noqa: BLE001
                with self._lock:
                    self.pending.append(pl)
        if dispatch_local:
            self._dispatch()

    def _cluster_view(self) -> Tuple[Dict[str, Dict[str, float]],
                                     Dict[str, Dict[str, float]],
                                     Dict[str, Tuple[str, int]],
                                     Dict[str, Dict[str, str]]]:
        labels: Dict[str, Dict[str, str]] = {}
        try:
            view = self._gcs.call("get_cluster_resources")
            nodes = {}
            for n in self._gcs.call("get_all_nodes"):
                if n.alive:
                    nodes[n.node_id.hex()] = n.address
                    labels[n.node_id.hex()] = dict(n.labels)
        except Exception:  # noqa: BLE001
            view, nodes = {}, {}
        avail = {nid: v["available"] for nid, v in view.items()}
        totals = {nid: v["total"] for nid, v in view.items()}
        with self._lock:
            avail[self.node_id.hex()] = self.available.to_dict()
            totals[self.node_id.hex()] = self.resources_total.to_dict()
        nodes.setdefault(self.node_id.hex(), self.address)
        labels.setdefault(self.node_id.hex(),
                          dict(self.info.labels))
        return avail, totals, nodes, labels

    # ---- worker pool (reference worker_pool.cc) -------------------------

    def _runtime_env_key(self, spec: TaskSpec) -> str:
        """Worker-pool bucket key (reference worker_pool runtime-env-keyed
        caching): a worker started for one env must not serve tasks whose
        env_vars/working_dir/py_modules differ."""
        renv = spec.runtime_env or {}
        from ray_tpu._private.runtime_env import (conda_spec, conda_uri,
                                                  container_spec,
                                                  pip_spec, pip_uri)
        pspec = pip_spec(renv)
        cspec = conda_spec(renv)
        ctr = container_spec(renv)
        return repr((sorted((renv.get("env_vars") or {}).items()),
                     renv.get("working_dir"),
                     tuple(renv.get("py_modules") or ()),
                     pip_uri(pspec) if pspec else None,
                     conda_uri(cspec) if cspec else None,
                     (ctr["image"], tuple(ctr["run_options"]))
                     if ctr else None))

    def _spawn_worker(self, runtime_env_key: str,
                      runtime_env: Optional[Dict[str, Any]]
                      ) -> Optional[_WorkerHandle]:
        if (runtime_env or {}).get("pip") or \
                (runtime_env or {}).get("conda"):
            # env setup can take minutes (pip/conda install): run the
            # whole spawn on a setup thread so the dispatch path (and
            # the lease-request RPC behind it) never blocks on it — the
            # reference keeps env setup in an async per-node agent for
            # the same reason (runtime_env_agent).
            threading.Thread(
                target=self._spawn_worker_sync,
                args=(runtime_env_key, runtime_env),
                daemon=True, name="worker-env-setup").start()
            return None
        return self._spawn_worker_sync(runtime_env_key, runtime_env)

    def _spawn_worker_sync(self, runtime_env_key: str,
                           runtime_env: Optional[Dict[str, Any]]
                           ) -> Optional[_WorkerHandle]:
        worker_id = WorkerID.from_random()
        env = dict(os.environ)
        # Make sure workers can import ray_tpu regardless of cwd.
        import ray_tpu
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.abspath(ray_tpu.__file__)))
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        env["RAY_TPU_WORKER_ID"] = worker_id.hex()
        env["RAY_TPU_NODE_ID"] = self.node_id.hex()
        env["RAY_TPU_NODE_MANAGER"] = f"{self.address[0]}:{self.address[1]}"
        env["RAY_TPU_GCS"] = f"{self.gcs_address[0]}:{self.gcs_address[1]}"
        env["RAY_TPU_STORE"] = f"{self.store.address[0]}:{self.store.address[1]}"
        env["RAY_TPU_SESSION_DIR"] = self.session_dir
        for k, v in ((runtime_env or {}).get("env_vars") or {}).items():
            env[str(k)] = str(v)
        # working_dir/py_modules (reference _private/runtime_env/
        # working_dir.py, py_modules plugin): the worker starts in
        # working_dir with it importable, and each py_module's parent on
        # the path so `import <module>` works.
        renv = runtime_env or {}
        extra_paths = []
        if renv.get("working_dir"):
            extra_paths.append(os.path.abspath(renv["working_dir"]))
        for mod in renv.get("py_modules") or ():
            extra_paths.append(os.path.dirname(os.path.abspath(mod)))
        if renv.get("pip"):
            # cached per-URI install; only the first worker of a given
            # pip spec pays the install (reference pip.py + URI cache).
            # Failure must not leak the _starting counters (that would
            # wedge every future spawn for this env key) nor kill the
            # dispatch loop — fail the env's queued leases instead
            # (reference: runtime-env agent setup failure fails the
            # lease with RuntimeEnvSetupError).
            try:
                site = self._runtime_env_mgr.setup_pip(renv)
            except Exception as e:  # noqa: BLE001
                logger.error("runtime_env setup failed for %s: %s",
                             runtime_env_key, e)
                self._fail_env_leases(runtime_env_key, str(e))
                return None
            if site:
                extra_paths.append(site)
        python_exe = sys.executable
        if renv.get("conda"):
            # conda env (reference runtime_env/conda.py): the worker
            # runs with the materialized prefix's interpreter
            try:
                prefix = self._runtime_env_mgr.setup_conda(renv)
            except Exception as e:  # noqa: BLE001
                logger.error("runtime_env conda setup failed for %s: %s",
                             runtime_env_key, e)
                self._fail_env_leases(runtime_env_key, str(e))
                return None
            if prefix:
                env["CONDA_PREFIX"] = prefix
                env["PATH"] = (os.path.join(prefix, "bin") + os.pathsep
                               + env.get("PATH", ""))
                cand = os.path.join(prefix, "bin", "python")
                if os.path.exists(cand):
                    python_exe = cand
        if extra_paths:
            env["PYTHONPATH"] = os.pathsep.join(
                extra_paths + [env.get("PYTHONPATH", "")])
        log_dir = os.path.join(self.session_dir, "logs")
        os.makedirs(log_dir, exist_ok=True)
        out = open(os.path.join(log_dir, f"worker-{worker_id.hex()[:12]}.log"),
                   "ab")
        cmd = [python_exe, "-m", "ray_tpu._private.worker_main"]
        if renv.get("container"):
            # container env (reference runtime_env/container.py): the
            # worker command runs inside the image via the wrap hook
            try:
                cmd = self._runtime_env_mgr.wrap_container(renv, cmd,
                                                           env=env)
            except Exception as e:  # noqa: BLE001
                logger.error("runtime_env container wrap failed: %s", e)
                self._fail_env_leases(runtime_env_key, str(e))
                return None
        proc = subprocess.Popen(
            cmd, env=env, stdout=out, stderr=subprocess.STDOUT,
            cwd=(runtime_env or {}).get("working_dir") or None)
        handle = _WorkerHandle(worker_id=worker_id, proc=proc,
                               runtime_env_key=runtime_env_key)
        with self._lock:
            self.workers[worker_id.hex()] = handle
        threading.Thread(target=self._monitor_worker, args=(handle,),
                         daemon=True).start()
        return handle

    def _fail_env_leases(self, runtime_env_key: str, message: str) -> None:
        """Runtime-env setup failed: release the spawn slot and fail
        every queued lease whose env resolves to this key so callers
        see the error instead of hanging. Covers leases that ALREADY
        acquired resources (the lease that triggered the spawn holds
        its reservation) by returning them to the pool."""
        with self._lock:
            self._starting = max(0, self._starting - 1)
            self._starting_by_key[runtime_env_key] = max(
                0, self._starting_by_key.get(runtime_env_key, 1) - 1)
            doomed = [pl for pl in self.pending
                      if self._runtime_env_key(pl.spec) == runtime_env_key]
            self.pending = [pl for pl in self.pending
                            if pl not in doomed]
            for pl in doomed:
                if pl.acquired is not None:
                    self.available.add(pl.acquired)
        for pl in doomed:
            try:
                self._pool.get(pl.reply_to).call(
                    "cw_task_failed", task_id=pl.spec.task_id,
                    error_type="RUNTIME_ENV_SETUP_FAILED",
                    message=message)
            except Exception:  # noqa: BLE001 - owner gone; nothing to fail
                pass

    def _monitor_worker(self, handle: _WorkerHandle) -> None:
        proc = handle.proc
        if proc is None:
            return
        proc.wait()
        if self._dead:
            return
        self._on_worker_death(handle, f"worker process exited "
                                      f"with code {proc.returncode}")

    def _on_worker_death(self, handle: _WorkerHandle, reason: str) -> None:
        with self._lock:
            wid = handle.worker_id.hex()
            if wid not in self.workers:
                return
            del self.workers[wid]
            if not handle.registered:
                self._starting = max(0, self._starting - 1)
                key = handle.runtime_env_key
                self._starting_by_key[key] = max(
                    0, self._starting_by_key.get(key, 1) - 1)
            for ids in self.idle.values():
                if wid in ids:
                    ids.remove(wid)
            running = handle.current_task
            lease_id = handle.lease_id
            if lease_id is not None:
                self.leases.release(lease_id, event="worker_died")
            if running is not None and not handle.blocked:
                # blocked workers already released their resources
                self.available.add(self._effective_resources(running))
        # consume pre-kill flight data unconditionally: a kill of an
        # idle worker takes no postmortem, and leaving its entry (or
        # sidecar dump) behind would leak per kill under a recurring
        # chaos schedule
        from ray_tpu._private import log_plane as _log_plane
        prekill = self._prekill_dumps.pop(wid, None) or {}
        if running is not None or handle.is_actor:
            # a death that loses work gets a crash postmortem (idle
            # pool churn — reaps, clean exits — stays silent); the
            # bundle id rides the error the owner raises so the user
            # can pull it (`ray_tpu logs --postmortem <id>`)
            pm_id = self._capture_postmortem(handle, reason, prekill)
            reason = f"{reason} [postmortem {pm_id}]"
        else:
            _log_plane.consume_flight_dump(
                os.path.join(self.session_dir, "logs"), wid)
        if handle.is_actor and handle.actor_id_hex:
            try:
                self._gcs.call("report_actor_death",
                               actor_id_hex=handle.actor_id_hex,
                               reason=reason, restart=True)
            except Exception:  # noqa: BLE001 - GCS down; health check sees the death
                pass
        if running is not None and not handle.is_actor:
            try:
                # lease_id rides along: with owner-side lease reuse the
                # task RUNNING at death may differ from the task the
                # lease was granted for — the owner maps lease->running.
                self._pool.get(running.owner_address).call(
                    "cw_task_failed", task_id=running.task_id,
                    error_type="WORKER_DIED", message=reason,
                    lease_id=lease_id)
            except Exception:  # noqa: BLE001 - owner gone; its tasks died with it
                pass
        self._dispatch()

    def register_worker(self, worker_id_hex: str,
                        address: Tuple[str, int]) -> Dict[str, Any]:
        with self._lock:
            handle = self.workers.get(worker_id_hex)
            if handle is None:
                raise KeyError(f"unknown worker {worker_id_hex}")
            handle.address = tuple(address)
            handle.registered = True
            handle.idle_since = time.monotonic()
            self._starting = max(0, self._starting - 1)
            key = handle.runtime_env_key
            self._starting_by_key[key] = max(
                0, self._starting_by_key.get(key, 1) - 1)
            self.idle.setdefault(key, []).append(worker_id_hex)
        self._dispatch()
        return {"node_id": self.node_id.hex()}

    def _pop_worker_locked(self, key: str) -> Optional[_WorkerHandle]:
        """Reference WorkerPool::PopWorker: reuse idle w/ same runtime env."""
        ids = self.idle.get(key, [])
        while ids:
            wid = ids.pop()
            handle = self.workers.get(wid)
            if handle is not None and handle.address is not None:
                return handle
        return None

    def _pop_worker(self, spec: TaskSpec,
                    spawn_if_needed: bool = True) -> Optional[_WorkerHandle]:
        key = self._runtime_env_key(spec)
        with self._lock:
            handle = self._pop_worker_locked(key)
            if handle is not None:
                return handle
            can_spawn = (spawn_if_needed
                         and self._starting_by_key.get(key, 0) == 0
                         and len(self.workers) + self._starting
                         < Config.max_workers_per_node)
            if can_spawn:
                self._starting += 1
                self._starting_by_key[key] = \
                    self._starting_by_key.get(key, 0) + 1
        if can_spawn:
            self._spawn_worker(key, spec.runtime_env)
        return None

    # ---- leases (reference lease protocol, node_manager.proto:361) ------

    # After this many redirects a lease request must settle somewhere: a
    # stale resource view can otherwise ping-pong a request between busy
    # node managers indefinitely (the reference caps spillbacks via the
    # lease client's budget + queueing at the selected raylet).
    LEASE_SPILL_BUDGET = 4

    def _route_lease(self, spec: TaskSpec,
                     spill_count: int) -> Optional[Tuple[str, Any]]:
        """Cluster-routing front half of request_lease. Returns
        ("spill", node_mgr_addr) | ("infeasible", message), or None when
        the request should queue locally."""
        required = self._effective_resources(spec)
        strategy = spec.scheduling_strategy
        if isinstance(strategy, NodeAffinitySchedulingStrategy) \
                and not strategy.soft \
                and strategy.node_id != self.node_id.hex():
            # Hard affinity to another node: route there; it queues or
            # rejects. Never silently run elsewhere (reference
            # node_affinity_scheduling_policy.h semantics).
            _, _, nodes, _ = self._cluster_view()
            target = nodes.get(strategy.node_id)
            if target is None:
                return ("infeasible",
                        f"hard-affinity node {strategy.node_id[:12]} is dead")
            return ("spill", target)
        avail, totals, nodes, labels = self._cluster_view()
        chosen = pick_node(avail, required, strategy,
                           local_node_id=self.node_id.hex(), totals=totals,
                           locality_hints=spec.locality_hints,
                           labels=labels)
        if isinstance(strategy, NodeAffinitySchedulingStrategy) \
                and not strategy.soft:
            chosen = self.node_id.hex()  # queue here (we are the target)
        if chosen is not None and chosen != self.node_id.hex() \
                and spill_count < self.LEASE_SPILL_BUDGET:
            return ("spill", nodes[chosen])
        if chosen is None or chosen != self.node_id.hex():
            # Nothing available right now (or out of redirect budget):
            # queue at a node whose TOTAL resources can ever run the task.
            if not required.is_subset_of(self.resources_total):
                for nid in sorted(totals):
                    if nid != self.node_id.hex() and nodes.get(nid) and \
                            required.is_subset_of(ResourceSet(totals[nid])):
                        return ("spill", nodes[nid])
                # Cluster-wide infeasible: stay pending here like the
                # reference (resources may yet appear, e.g. autoscaling);
                # the owner's get() timeout is the backstop.
        logger.debug("request_lease: %s queued locally (spill_count=%d)",
                     spec.function_name, spill_count)
        return None

    def request_lease(self, spec: TaskSpec,
                      reply_to: Tuple[str, int],
                      spill_count: int = 0) -> Tuple[str, Any]:
        """Returns ("spill", node_mgr_addr) | ("queued", lease_id) |
        ("infeasible", message)."""
        routed = self._route_lease(spec, spill_count)
        if routed is not None:
            return routed
        lease_id = rand_bytes(16).hex()
        pl = _PendingLease(lease_id=lease_id, spec=spec,
                           reply_to=tuple(reply_to))
        with self._lock:
            self.pending.append(pl)
        self._dispatch()
        return ("queued", lease_id)

    def request_lease_batch(self, specs: List[TaskSpec],
                            reply_to: Tuple[str, int],
                            spill_count: int = 0) -> List[Tuple[str, Any]]:
        """Multi-grant lease request: N specs route in one RPC, all
        locally-queued entries land under ONE lock pass and ONE dispatch
        (reference direct_task_transport pipelines RequestWorkerLease for
        the same reason — the per-request round trip is the task-path
        ceiling). Returns a reply per spec, aligned with the input:
        ("queued", lease_id) | ("spill", addr) | ("infeasible", msg).
        The owner retries spilled/infeasible entries on the singleton
        path; duplicate delivery of the whole batch (client resend after
        a send failure) just queues fresh lease ids whose extra grants
        the owner's note_grant dedup returns."""
        replies: List[Tuple[str, Any]] = []
        queued: List[_PendingLease] = []
        for spec in specs:
            routed = self._route_lease(spec, spill_count)
            if routed is not None:
                replies.append(routed)
                continue
            lease_id = rand_bytes(16).hex()
            queued.append(_PendingLease(lease_id=lease_id, spec=spec,
                                        reply_to=tuple(reply_to)))
            replies.append(("queued", lease_id))
        if queued:
            with self._lock:
                self.pending.extend(queued)
            self._dispatch()
        return replies

    def _effective_resources(self, spec: TaskSpec) -> ResourceSet:
        strategy = spec.scheduling_strategy
        if (isinstance(strategy, PlacementGroupSchedulingStrategy)
                and spec.placement_group_id is not None):
            return ResourceSet(rewrite_resources_for_pg(
                spec.resources, spec.placement_group_id.hex(),
                spec.placement_group_bundle_index))
        return spec.required_resources()

    def _dispatch(self) -> None:
        """Grant queued leases while resources + workers allow (reference
        LocalTaskManager::DispatchScheduledTasksToWorkers)."""
        granted: List[Tuple[_PendingLease, _WorkerHandle]] = []
        spawns: List[Tuple[str, Optional[Dict[str, Any]]]] = []
        with self._lock:
            remaining: List[_PendingLease] = []
            want_spawn: Dict[str, int] = {}
            # Per-pass failure memo: once a resource shape fails to
            # acquire, every later identical shape in this pass fails
            # too (resources only shrink within the loop) — keeps a
            # dispatch pass O(shapes) instead of O(pending) subset
            # checks when tens of thousands of same-shape leases queue
            # (SURVEY §6 single-node envelope: 1M queued tasks).
            failed_shapes: set = set()
            for pl in self.pending:
                # hard label constraints must hold on THIS node before a
                # queued lease may dispatch locally (the cluster-level
                # pick already respects them; local dispatch must too)
                strategy = pl.spec.scheduling_strategy
                if isinstance(strategy, NodeLabelSchedulingStrategy) \
                        and strategy.hard and not _labels_match(
                            self.info.labels, strategy.hard):
                    remaining.append(pl)
                    continue
                if pl.acquired is None:
                    required = self._effective_resources(pl.spec)
                    shape = tuple(sorted(required.to_dict().items()))
                    if shape in failed_shapes:
                        remaining.append(pl)
                        continue
                    if required.is_subset_of(self.available):
                        self.available.subtract(required)
                        pl.acquired = required
                    else:
                        failed_shapes.add(shape)
                        remaining.append(pl)
                        continue
                key = self._runtime_env_key(pl.spec)
                handle = self._pop_worker_locked(key)
                if handle is None:
                    remaining.append(pl)
                    want_spawn[key] = want_spawn.get(key, 0) + 1
                    if want_spawn[key] > self._starting_by_key.get(key, 0) \
                            and len(self.workers) + self._starting \
                            < Config.max_workers_per_node:
                        self._starting += 1
                        self._starting_by_key[key] = \
                            self._starting_by_key.get(key, 0) + 1
                        spawns.append((key, pl.spec.runtime_env))
                    continue
                handle.lease_id = pl.lease_id
                handle.current_task = pl.spec
                handle.task_started_at = time.time()
                self.leases.grant(pl.lease_id, handle.worker_id.hex())
                granted.append((pl, handle))
            self.pending = remaining
        for key, renv in spawns:
            self._spawn_worker(key, renv)
        if granted:
            self._prefetch_args([pl.spec for pl, _ in granted])
        # Group grant replies per owner: one dispatch pass over a deep
        # backlog grants many leases to the same core worker, and each
        # cw_lease_granted round trip costs ~300µs on this box — a
        # grouped cw_lease_granted_batch collapses them into one call
        # (the owner loops _on_lease_granted per element; note_grant's
        # dedup ring makes a replayed batch harmless).
        by_owner: Dict[Tuple[str, int], List[Tuple[_PendingLease,
                                                   _WorkerHandle]]] = {}
        for pl, handle in granted:
            by_owner.setdefault(pl.reply_to, []).append((pl, handle))
        for reply_to, group in by_owner.items():
            grants = [dict(lease_id=pl.lease_id, task_id=pl.spec.task_id,
                           worker_address=handle.address,
                           worker_id=handle.worker_id.hex(),
                           node_id=self.node_id.hex(),
                           nm_address=self.address)
                      for pl, handle in group]
            try:
                if len(grants) == 1:
                    self._pool.get(reply_to).call(
                        "cw_lease_granted", **grants[0])
                else:
                    self._pool.get(reply_to).call(
                        "cw_lease_granted_batch", grants=grants)
            except Exception:  # noqa: BLE001
                requeued = False
                for pl, _handle in group:
                    pl.grant_failures += 1
                    if pl.grant_failures <= 2:
                        # transient reply loss: the owner still holds a
                        # request slot parked here and would stall
                        # forever if we silently dropped the lease —
                        # reclaim the worker and re-queue the lease for
                        # a fresh grant
                        logger.warning(
                            "lease reply to %s failed (attempt %d); "
                            "re-queueing", reply_to, pl.grant_failures)
                        self.return_worker(pl.lease_id)
                        with self._lock:
                            pl.acquired = None
                            self.pending.append(pl)
                        requeued = True
                    else:
                        logger.warning(
                            "lease reply to %s failed; reclaiming",
                            reply_to)
                        self.return_worker(pl.lease_id)
                if requeued:
                    self._dispatch()

    def _prefetch_args(self, specs: List[TaskSpec]) -> None:
        """Pull the batch's remote args into the local store while the
        lease replies are in flight (reference raylet DependencyManager +
        PullManager: args land on the node before dispatch; without it
        the worker stalls pulling them serially at execution time). One
        thread per dispatch batch; the store dedups concurrent pulls of
        the same object."""
        remote_args = {}
        for spec in specs:
            for oid, (addr, size) in spec.arg_locations.items():
                if tuple(addr) != self.store.address:
                    remote_args[oid] = (tuple(addr), size)
        if not remote_args:
            return

        def pull_all() -> None:
            for oid, (addr, size) in remote_args.items():
                try:
                    self.store.pull(oid, addr, size)
                    with self._lock:
                        self.num_args_prefetched += 1
                except Exception:  # noqa: BLE001 - worker's own pull (or
                    pass  # lineage recovery) is the fallback path

        threading.Thread(target=pull_all, daemon=True,
                         name="arg-prefetch").start()

    def cancel_lease(self, lease_id: str) -> None:
        with self._lock:
            for pl in list(self.pending):
                if pl.lease_id == lease_id:
                    self.pending.remove(pl)
                    if pl.acquired is not None:
                        self.available.add(pl.acquired)
                    return
        self.return_worker(lease_id)

    def return_worker(self, lease_id: str, reuse: bool = True) -> None:
        with self._lock:
            wid = self.leases.release(lease_id)
            if wid is None:
                return
            handle = self.workers.get(wid)
            if handle is None:
                return
            if handle.current_task is not None and not handle.blocked:
                self.available.add(
                    self._effective_resources(handle.current_task))
            handle.blocked = False
            handle.current_task = None
            handle.lease_id = None
            handle.idle_since = time.monotonic()
            if reuse:
                self.idle.setdefault(handle.runtime_env_key, []).append(wid)
        if not reuse and handle.proc is not None:
            handle.proc.terminate()
        self._dispatch()

    # ---- actors ----------------------------------------------------------

    def schedule_actor_creation(self, spec: TaskSpec) -> bool:
        """Called by GCS actor scheduler. Reserves resources for actor
        lifetime and pushes the creation task to a dedicated worker."""
        required = self._effective_resources(spec)
        with self._lock:
            if not required.is_subset_of(self.available):
                return False
            self.available.subtract(required)
        deadline = time.monotonic() + Config.worker_register_timeout_s
        handle: Optional[_WorkerHandle] = None
        while handle is None and time.monotonic() < deadline:
            handle = self._pop_worker(spec)
            if handle is None:
                time.sleep(0.02)
        if handle is None:
            with self._lock:
                self.available.add(required)
            return False
        with self._lock:
            handle.is_actor = True
            handle.actor_id_hex = spec.actor_id.hex()
            handle.current_task = spec
        self._prefetch_args([spec])
        try:
            self._pool.get(handle.address).call("w_push_task", spec=spec)
            return True
        except Exception as e:  # noqa: BLE001
            self._on_worker_death(
                handle, "actor creation push failed: "
                f"{type(e).__name__}: {e}")
            return False

    def worker_blocked(self, worker_id_hex: str) -> None:
        """Worker blocked in ray.get: release its cpu-ish resources so other
        work can run (reference NotifyDirectCallTaskBlocked)."""
        with self._lock:
            handle = self.workers.get(worker_id_hex)
            if handle is not None and handle.current_task is not None \
                    and not handle.is_actor and not handle.blocked:
                handle.blocked = True
                self.available.add(self._effective_resources(
                    handle.current_task))
        self._dispatch()

    def worker_unblocked(self, worker_id_hex: str) -> None:
        with self._lock:
            handle = self.workers.get(worker_id_hex)
            if handle is not None and handle.current_task is not None \
                    and not handle.is_actor and handle.blocked:
                handle.blocked = False
                # may oversubscribe transiently; reference re-acquires
                self.available.subtract(self._effective_resources(
                    handle.current_task))

    # ---- placement group bundles (2-phase; reference
    #      placement_group_resource_manager.h) ---------------------------

    def prepare_bundle(self, pg_id_hex: str, bundle_index: int,
                       resources: Dict[str, float]) -> bool:
        required = ResourceSet(resources)
        with self._lock:
            if not required.is_subset_of(self.available):
                return False
            self.available.subtract(required)
            self._prepared[(pg_id_hex, bundle_index)] = resources
            return True

    def commit_bundle(self, pg_id_hex: str, bundle_index: int) -> bool:
        with self._lock:
            resources = self._prepared.pop((pg_id_hex, bundle_index), None)
            if resources is None:
                return False
            add: Dict[str, float] = {}
            for r, v in resources.items():
                add[pg_resource_name(r, pg_id_hex, bundle_index)] = v
                add[pg_resource_name(r, pg_id_hex)] = v
            add[pg_resource_name("bundle", pg_id_hex, bundle_index)] = 1000
            add[pg_resource_name("bundle", pg_id_hex)] = 1000
            self.resources_total.add(ResourceSet(add))
            self.available.add(ResourceSet(add))
            self._committed[(pg_id_hex, bundle_index)] = (resources, add)
        # a lease that raced ahead of this commit (pg.ready() is
        # submitted the moment placement_group() returns) sits queued
        # un-acquired: its bundle resources exist only NOW, and on an
        # otherwise-idle node no other event re-runs dispatch — without
        # this kick it wedges until the owner's get() times out
        self._dispatch()
        return True

    def return_bundle(self, pg_id_hex: str, bundle_index: int) -> None:
        with self._lock:
            resources = self._prepared.pop((pg_id_hex, bundle_index), None)
            if resources is not None:
                self.available.add(ResourceSet(resources))
                return
            entry = self._committed.pop((pg_id_hex, bundle_index), None)
            if entry is not None:
                resources, add = entry
                self.resources_total.subtract(ResourceSet(add))
                self.available.subtract(ResourceSet(add))
                self.available.add(ResourceSet(resources))

    # ---- chaos plane (_private/chaos.py) --------------------------------

    def _on_pubsub_push(self, channel: str, token: str,
                        message: Any) -> None:
        """GCS pubsub delivery into this daemon (currently only the
        chaos-policy channel subscribes with the NM's address)."""
        if channel == "chaos":
            from ray_tpu._private import chaos as chaos_lib
            chaos_lib.on_policy_message(message)

    def chaos_kill_worker(self, actor_class: str = "") -> bool:
        """kill_worker actuator: SIGKILL one live local worker whose
        hosted actor class matches the glob (empty glob prefers busy
        task workers, then anything registered). Simulates a preempted
        TPU worker — death detection, task retries, and actor restarts
        proceed through the normal machinery. Returns True if a worker
        was killed."""
        import fnmatch as _fnmatch
        with self._lock:
            live = [h for h in self.workers.values()
                    if h.proc is not None and h.registered]
            if actor_class:
                pool = [h for h in live if h.is_actor
                        and h.current_task is not None
                        and _fnmatch.fnmatchcase(
                            h.current_task.function_name, actor_class)]
            else:
                pool = sorted(live, key=lambda h: not bool(h.current_task))
            victim = pool[0] if pool else None
        if victim is None:
            return False
        logger.warning("chaos: killing worker %s (%s)",
                       victim.worker_id.hex()[:12],
                       actor_class or "any")
        # the victim still answers: grab its span tail for the
        # postmortem before the SIGKILL destroys it
        self._capture_prekill(victim)
        try:
            victim.proc.kill()
        except OSError:
            return False
        return True

    def chaos_stall_worker(self, actor_class: str = "",
                           duration_ms: float = 0.0) -> bool:
        """stall_worker actuator: SIGSTOP one live local worker whose
        hosted actor class matches the glob (empty glob prefers busy
        task workers). Freezes EVERY thread — the exact signature of a
        hung XLA collective: the main thread stops making progress AND
        the heartbeat sidecar stops beating, so the supervisor's
        staleness check (train/heartbeat.py) is the only signal left.
        After duration_ms a daemon timer SIGCONTs the victim (stray
        resume: by then the supervisor has usually SIGKILLed it —
        tolerated via the OSError guard); duration_ms=0 stalls until
        something kills the process. Returns True if a worker was
        stalled."""
        import fnmatch as _fnmatch
        import signal as _signal
        with self._lock:
            live = [h for h in self.workers.values()
                    if h.proc is not None and h.registered
                    and h.proc.pid not in self._stalled]
            if actor_class:
                pool = [h for h in live if h.is_actor
                        and h.current_task is not None
                        and _fnmatch.fnmatchcase(
                            h.current_task.function_name, actor_class)]
            else:
                pool = sorted(live, key=lambda h: not bool(h.current_task))
            victim = pool[0] if pool else None
            if victim is not None:
                self._stalled.add(victim.proc.pid)
        if victim is None:
            return False
        pid = victim.proc.pid
        logger.warning("chaos: stalling worker %s pid=%d for %s",
                       victim.worker_id.hex()[:12], pid,
                       f"{duration_ms:.0f}ms" if duration_ms > 0
                       else "ever (until killed)")
        try:
            os.kill(pid, _signal.SIGSTOP)
        except OSError:
            with self._lock:
                self._stalled.discard(pid)
            return False
        if duration_ms > 0:
            def _resume() -> None:
                time.sleep(duration_ms / 1000.0)
                with self._lock:
                    self._stalled.discard(pid)
                try:
                    os.kill(pid, _signal.SIGCONT)
                except OSError:
                    pass  # victim was killed while stopped
            threading.Thread(target=_resume, daemon=True,
                             name=f"chaos-stall-resume-{pid}").start()
        return True

    def kill_worker_pid(self, pid: int, reason: str = "") -> bool:
        """SIGKILL one local worker by OS pid. The wedge-recovery
        actuator (train/heartbeat.py hard_kill_ranks): a SIGSTOPped
        worker cannot run `cw_kill_self` — only an outside SIGKILL,
        which works on stopped processes, removes it. Returns True when
        the pid named a live registered worker and the kill landed."""
        with self._lock:
            victim = next((h for h in self.workers.values()
                           if h.proc is not None and h.proc.pid == pid),
                          None)
        if victim is None:
            return False
        logger.warning("killing worker %s pid=%d (%s)",
                       victim.worker_id.hex()[:12], pid,
                       reason or "requested by pid")
        # 1s pull timeout inside tolerates a stopped victim: the span
        # pull just times out and the postmortem ships without it
        self._capture_prekill(victim)
        try:
            victim.proc.kill()
        except OSError:
            return False
        with self._lock:
            self._stalled.discard(pid)
        return True

    # ---- misc ------------------------------------------------------------

    def get_info(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "node_id": self.node_id.hex(),
                "address": self.address,
                "store_address": self.store.address,
                "resources_total": self.resources_total.to_dict(),
                "available": self.available.to_dict(),
                "num_workers": len(self.workers),
                "num_pending_leases": len(self.pending),
                # resource shape per unplaced lease: the autoscaler's
                # demand scheduler bin-packs these into candidate node
                # types (reference resource_demand_scheduler.py)
                "pending_resource_shapes": [
                    dict(pl.spec.resources) if isinstance(
                        pl.spec.resources, dict)
                    else pl.spec.resources.to_dict()
                    for pl in self.pending if pl.acquired is None],
                "num_args_prefetched": self.num_args_prefetched,
            }

    def _kill_worker_for_memory(self) -> bool:
        """Retriable-FIFO policy (worker_killing_policy_retriable_fifo.h):
        prefer the newest-started retriable NORMAL task; fall back to the
        newest actor. Returns True when something was killed."""
        with self._lock:
            busy = [h for h in self.workers.values()
                    if h.current_task is not None and h.proc is not None]
            normal = [h for h in busy if not h.is_actor
                      and h.current_task.max_retries != 0]
            pool = normal or [h for h in busy if h.is_actor]
            if not pool:
                return False
            victim = max(pool, key=lambda h: h.task_started_at)
        fn = victim.current_task.function_name \
            if victim.current_task else "?"
        logger.warning(
            "memory pressure: killing worker %s running %s",
            victim.worker_id.hex()[:12], fn)
        self._capture_prekill(victim)
        try:
            victim.proc.kill()
        except OSError:
            return False
        # record AFTER the successful kill, off-thread, on a DEDICATED
        # short-timeout connection: the shared GCS client serializes
        # calls, so a slow control plane here would otherwise stall the
        # resource-report heartbeat and get the node marked dead
        def _oom_event() -> None:
            from ray_tpu._private import rpc as rpc_lib
            from ray_tpu._private.events import emit_via
            client = rpc_lib.RpcClient(self.gcs_address, timeout=5)
            try:
                emit_via(client.call, "node_manager", "OOM_KILL",
                         f"killed worker running {fn} under memory "
                         "pressure", severity="WARNING",
                         node_id=self.node_id.hex(),
                         worker_id=victim.worker_id.hex())
            finally:
                client.close()

        threading.Thread(target=_oom_event, daemon=True,
                         name="oom-event").start()
        return True

    def profile_worker(self, worker_id_hex: str,
                       timeout: float = 3.0) -> Dict[str, Any]:
        """Live stack dump of one worker process (reference: dashboard
        reporter module's py-spy stack dumps,
        dashboard/modules/reporter/profile_manager.py:11-19). Workers
        register faulthandler on SIGUSR1 (worker_main.py): the signal
        makes the worker append all-thread tracebacks to its log; this
        returns the bytes the dump added."""
        import signal as _signal
        with self._lock:
            handle = self.workers.get(worker_id_hex)
        if handle is None or handle.proc is None:
            raise KeyError(f"no live worker {worker_id_hex[:12]} "
                           f"on this node")
        log_path = os.path.join(
            self.session_dir, "logs",
            f"worker-{worker_id_hex[:12]}.log")
        before = os.path.getsize(log_path) \
            if os.path.exists(log_path) else 0
        os.kill(handle.proc.pid, _signal.SIGUSR1)
        deadline = time.monotonic() + timeout
        stack = ""
        while time.monotonic() < deadline:
            time.sleep(0.1)
            if os.path.exists(log_path) and \
                    os.path.getsize(log_path) > before:
                time.sleep(0.2)  # let the full dump flush
                with open(log_path, "rb") as f:
                    f.seek(before)
                    stack = f.read().decode(errors="replace")
                break
        return {"worker_id": worker_id_hex,
                "pid": handle.proc.pid,
                "node_id": self.node_id.hex(),
                "stack": stack}

    def profile_workers(self, timeout: float = 3.0) -> Dict[str, Any]:
        """Batched `ray stack`: dump EVERY live worker on this node in
        one RPC — the signals go out together and the log-tail waits
        run on parallel threads, so the reply lands in ~one worker's
        dump time instead of num_workers serial round trips."""
        with self._lock:
            worker_ids = [wid for wid, h in self.workers.items()
                          if h.proc is not None]
        dumps: List[Dict[str, Any]] = []
        lock = threading.Lock()

        def _one(wid: str) -> None:
            try:
                d = self.profile_worker(wid, timeout=timeout)
            except Exception as e:  # noqa: BLE001 - worker died mid-dump
                d = {"worker_id": wid, "node_id": self.node_id.hex(),
                     "pid": None, "stack": "", "error": str(e)}
            with lock:
                dumps.append(d)

        threads = [threading.Thread(target=_one, args=(wid,),
                                    daemon=True) for wid in worker_ids]
        for t in threads:
            t.start()
        deadline = time.monotonic() + timeout + 2.0
        for t in threads:
            t.join(timeout=max(0.1, deadline - time.monotonic()))
        return {"node_id": self.node_id.hex(), "dumps": dumps}

    PROFILE_WORKER_GRACE_S = 5.0

    def profile_collect(self, duration_s: float = 5.0, hz: float = 100.0,
                        device: bool = False) -> Dict[str, Any]:
        """Profiling-plane gather for this node: sample the daemon's own
        process (the store server lives here too) AND every registered
        worker CONCURRENTLY for the same window — the workers'
        cw_profile_collect calls block for duration_s, so the daemon's
        own session runs on this handler thread in parallel with the
        fan-out. Device mode skips the daemon (no jax here) and asks
        workers for xplane traces instead."""
        from ray_tpu._private import profiler as profiler_lib
        from ray_tpu._private import spans as spans_lib
        with self._lock:
            worker_addrs = [h.address for h in self.workers.values()
                            if h.registered and h.address is not None]
        kwargs = {"duration_s": duration_s, "hz": hz, "device": device}
        own_box: List[Optional[Dict[str, Any]]] = [None]

        def _own() -> None:
            try:
                own_box[0] = profiler_lib.collect_local(duration_s, hz)
            except Exception:  # noqa: BLE001 - daemon profile is a
                pass           # bonus, not a reason to fail the node

        own_thread = None
        if not device:
            own_thread = threading.Thread(target=_own, daemon=True,
                                          name="nm-profile-own")
            own_thread.start()
        pulled = spans_lib.pull_snapshots(
            worker_addrs, "cw_profile_collect",
            timeout=duration_s + self.PROFILE_WORKER_GRACE_S,
            call_kwargs=kwargs)
        if own_thread is not None:
            own_thread.join(timeout=duration_s + 5.0)
        profiles = [p for p in (own_box[0],) if p is not None]
        profiles.extend(snap for _a, snap, _t0, _t1 in pulled)
        # worker_addrs lets the GCS's concurrent direct pull dedupe by
        # proc uid without transferring twice being a correctness issue
        # (the collect singleflight already shares one session)
        return {"node_id": self.node_id.hex(), "profiles": profiles,
                "worker_addrs": [list(a) for a, _r, _t0, _t1 in pulled]}

    MEMORY_WORKER_TIMEOUT_S = 3.0

    def memory_snapshot(self, max_objects: Optional[int] = None
                        ) -> Dict[str, Any]:
        """Memory-plane gather for this node: the store's residency
        table plus every registered worker's reference-table snapshot,
        one RPC hop below the GCS `memory_collect` fan-out
        (memory_plane.py builds the cluster object table from these)."""
        from ray_tpu._private import spans as spans_lib
        with self._lock:
            worker_addrs = [h.address for h in self.workers.values()
                            if h.registered and h.address is not None]
        pulled = spans_lib.pull_snapshots(
            worker_addrs, "cw_memory_snapshot",
            timeout=self.MEMORY_WORKER_TIMEOUT_S,
            call_kwargs={"max_objects": max_objects}
            if max_objects is not None else None)
        return {"node_id": self.node_id.hex(),
                "store_addr": list(self.store.address),
                "store": self.store.list_objects(),
                "worker_snaps": [snap for _a, snap, _t0, _t1 in pulled],
                "worker_addrs": [list(a) for a, _r, _t0, _t1 in pulled]}

    OWNERSHIP_WORKER_TIMEOUT_S = 3.0

    def ownership_snapshot(self, object_id: Optional[str] = None,
                           limit: int = 200) -> Dict[str, Any]:
        """Ownership-protocol gather for this node: the daemon's own
        transition ring (NM lease grants + store reader leases live in
        this process), the NM's held leases, the store's leased/pinned
        entries, plus every registered worker's cw_ownership_snapshot —
        one RPC hop below the GCS `ownership_collect` fan-out."""
        from ray_tpu._private import spans as spans_lib
        ring_snap = _ownership.ring().snapshot(
            key_prefix=object_id or None, limit=limit)
        with self._lock:
            worker_addrs = [h.address for h in self.workers.values()
                            if h.registered and h.address is not None]
            nm_leases = {lid: wid[:12] for lid, wid in
                         self.leases.items()}
        store_held = [e for e in self.store.list_objects()
                      if (e.get("pinned") or 0) > 0
                      or (e.get("leases") or 0) > 0]
        if object_id:
            store_held = [e for e in store_held
                          if e["object_id"].startswith(object_id)]
        kwargs = {"limit": limit}
        if object_id is not None:
            kwargs["object_id"] = object_id
        pulled = spans_lib.pull_snapshots(
            worker_addrs, "cw_ownership_snapshot",
            timeout=self.OWNERSHIP_WORKER_TIMEOUT_S, call_kwargs=kwargs)
        return {"proc_uid": spans_lib.PROC_UID,
                "node_id": self.node_id.hex(),
                "store_addr": list(self.store.address),
                "nm_leases": nm_leases,
                "store_held": store_held,
                "transitions": ring_snap["transitions"],
                "anomalies": ring_snap["anomalies"],
                "worker_snaps": [snap for _a, snap, _t0, _t1 in pulled],
                "worker_addrs": [list(a) for a, _r, _t0, _t1 in pulled]}

    def _store_objects_digest(self) -> Dict[str, Any]:
        """Held-alive (pinned/leased) store entries for the harvest's
        leak probes (memory_plane.store_digest). `registered_workers`
        lets the probe verify WORKER-granularity coverage: one stalled
        worker missing from the harvest must disable this node's
        absence-based checks for the round, not read as a dead owner."""
        from ray_tpu._private import memory_plane as memory_plane_lib
        entries, truncated = memory_plane_lib.store_digest(
            self.store.list_objects(),
            cap=Config.memory_digest_max_objects)
        with self._lock:
            registered = sum(1 for h in self.workers.values()
                             if h.registered and h.address is not None)
        return {"entries": entries, "truncated": truncated,
                "registered_workers": registered,
                "node_id": self.node_id.hex()}

    SPANS_WORKER_TIMEOUT_S = 3.0

    def spans_snapshot(self) -> Dict[str, Any]:
        """Flight-recorder gather for this node: the daemon's own span
        ring (which includes the store server — same process) plus every
        registered worker's, each annotated with the RPC-midpoint
        estimate of worker_wall_clock - nm_wall_clock. The reply's
        top-level wall_time lets the GCS chain its own offset estimate
        on top (see gcs.spans_collect)."""
        from ray_tpu._private import spans as spans_lib
        # stamp the reply's wall clock BEFORE the worker gather: the GCS
        # estimates this node's clock offset as wall_time - rpc_midpoint,
        # and a slow gather (one hung worker burns its full timeout)
        # stamped at the end would skew every snapshot from this node by
        # half the gather duration
        reply_wall = time.time()
        own = spans_lib.snapshot()
        own["clock_offset_s"] = 0.0
        with self._lock:
            worker_addrs = [h.address for h in self.workers.values()
                            if h.registered and h.address is not None]
        pulled = spans_lib.pull_snapshots(
            worker_addrs, "cw_spans_snapshot",
            timeout=self.SPANS_WORKER_TIMEOUT_S)
        snapshots: List[Dict[str, Any]] = [own]
        for _addr, snap, t0, t1 in pulled:
            snap["clock_offset_s"] = snap["wall_time"] - (t0 + t1) / 2.0
            snapshots.append(snap)
        # worker_addrs lets the GCS skip its direct-subscriber pull for
        # workers this reply already covers (they also subscribe to
        # pubsub, so without this every worker ring would ship twice).
        # Only successfully-pulled workers count: one the NM couldn't
        # reach may still be reachable from the GCS directly.
        return {"wall_time": reply_wall, "snapshots": snapshots,
                "worker_addrs": [list(a) for a, _r, _t0, _t1 in pulled]}

    def _sample_metric_gauges(self) -> None:
        """Node-level gauges for the metrics harvest: object-store
        occupancy (incl. eviction-exempt pinned/leased bytes — the
        watchdog's store probes), worker-pool size, and queued leases.
        The gauge names match the Grafana panel exprs shipped by
        dashboard/metrics.py."""
        from ray_tpu.util.metrics import Gauge, get_or_create
        stats = self.store.stats()
        for name, desc, value in (
                ("ray_tpu_object_store_used_bytes",
                 "bytes resident in this node's object store",
                 stats["used"]),
                ("ray_tpu_object_store_capacity_bytes",
                 "this node's object store capacity",
                 stats["capacity"]),
                ("ray_tpu_object_store_pinned_bytes",
                 "eviction-exempt bytes (owner pins + reader leases)",
                 stats["pinned_bytes"]),
                ("ray_tpu_object_store_objects",
                 "objects resident in this node's store",
                 stats["num_objects"])):
            get_or_create(Gauge, name, description=desc).set(float(value))
        with self._lock:
            num_workers = len(self.workers)
            pending = len(self.pending)
        get_or_create(
            Gauge, "ray_tpu_num_workers",
            description="worker processes on this node"
        ).set(float(num_workers))
        get_or_create(
            Gauge, "ray_tpu_pending_leases",
            description="lease requests queued at this node manager"
        ).set(float(pending))

    METRICS_WORKER_TIMEOUT_S = 3.0

    def metrics_snapshot(self) -> Dict[str, Any]:
        """Metrics-plane gather for this node: the daemon's own registry
        snapshot plus every registered worker's, one RPC hop below the
        GCS fan-out (structure mirrors spans_snapshot; metrics carry
        their own wall_time so no clock-offset chaining is needed)."""
        from ray_tpu._private import metrics_plane as _metrics_plane
        from ray_tpu._private import spans as spans_lib
        with self._lock:
            worker_addrs = [h.address for h in self.workers.values()
                            if h.registered and h.address is not None]
        pulled = spans_lib.pull_snapshots(
            worker_addrs, "cw_metrics_snapshot",
            timeout=self.METRICS_WORKER_TIMEOUT_S)
        snapshots = [_metrics_plane.snapshot_process()]
        snapshots.extend(snap for _a, snap, _t0, _t1 in pulled)
        # worker_addrs lets the GCS skip its direct-subscriber pull for
        # workers this reply already covers (only successfully-pulled
        # ones: a worker the NM missed may answer the GCS directly)
        return {"snapshots": snapshots,
                "worker_addrs": [list(a) for a, _r, _t0, _t1 in pulled]}

    def locks_snapshot(self) -> Dict[str, Any]:
        """Lockdep-plane gather for this node: the daemon's own traced
        locks plus every registered worker's, one hop below the GCS
        `locks_collect` fan-out (structure mirrors metrics_snapshot)."""
        from ray_tpu._private import spans as spans_lib
        from ray_tpu.util import locks as locks_lib
        with self._lock:
            worker_addrs = [h.address for h in self.workers.values()
                            if h.registered and h.address is not None]
        pulled = spans_lib.pull_snapshots(
            worker_addrs, "cw_locks_snapshot",
            timeout=self.METRICS_WORKER_TIMEOUT_S)
        snapshots = [locks_lib.snapshot()]
        snapshots.extend(snap for _a, snap, _t0, _t1 in pulled)
        return {"snapshots": snapshots,
                "worker_addrs": [list(a) for a, _r, _t0, _t1 in pulled]}

    def logs_snapshot(self, filters: Optional[Dict[str, Any]] = None,
                      tail: int = 500) -> Dict[str, Any]:
        """Debug-plane gather for this node: a fresh scan + the filtered
        tail index of every worker log file, one RPC hop below the GCS
        `logs_query` fan-out. Filtering runs HERE so the fan-out ships
        matching records, not every node's whole tail. worker_addrs lets
        the GCS skip its direct-subscriber pull for workers this node's
        files already cover."""
        try:
            self.log_monitor.scan_now()
        except Exception:  # noqa: BLE001 - index may lag one poll tick
            pass
        records = self.log_monitor.query(filters, tail=tail)
        with self._lock:
            worker_addrs = [h.address for h in self.workers.values()
                            if h.registered and h.address is not None]
        return {"node_id": self.node_id.hex(),
                "records": records,
                "worker_addrs": [list(a) for a in worker_addrs]}

    # ---- crash postmortems (debug plane; see _private/log_plane.py) -----

    def _capture_prekill(self, handle: _WorkerHandle) -> None:
        """Daemon-initiated kill paths call this while the victim still
        answers RPCs: pull its span-ring tail + rss so the postmortem
        can include the flight data a SIGKILL would otherwise destroy."""
        out: Dict[str, Any] = {}
        try:
            from ray_tpu._private import spans as spans_lib
            got = spans_lib.pull_snapshot(
                handle.address, "cw_spans_snapshot", timeout=1.0)
            if got is not None:
                k = Config.postmortem_span_tail
                out["span_tail"] = [list(r) for r in
                                    got[0].get("spans", [])[-k:]]
        except Exception:  # noqa: BLE001 - victim already unresponsive
            pass
        try:
            from ray_tpu._private.log_plane import read_rss_bytes
            if handle.proc is not None:
                out["rss_bytes"] = read_rss_bytes(handle.proc.pid)
        except Exception:  # noqa: BLE001 - /proc gone; rss is optional in the bundle
            pass
        self._prekill_dumps[handle.worker_id.hex()] = out

    def _capture_postmortem(self, handle: _WorkerHandle, reason: str,
                            prekill: Optional[Dict[str, Any]] = None
                            ) -> str:
        """Bundle a dead worker's black box: last log lines (after a
        final synchronous scan so lines written just before death are
        indexed), span-ring tail (from the daemon's pre-kill pull or
        the worker's own flight dump), and node gauges. Ships to the
        GCS's bounded postmortem ring off-thread on a dedicated client
        (the shared GCS client serializes calls; a slow control plane
        must not stall worker-death handling)."""
        from ray_tpu._private import log_plane
        pm_id = f"pm-{uuid.uuid4().hex[:12]}"
        wid = handle.worker_id.hex()
        prekill = prekill or self._prekill_dumps.pop(wid, None) or {}
        log_dir = os.path.join(self.session_dir, "logs")
        flight = log_plane.consume_flight_dump(log_dir, wid) or {}
        log_tail: List[Dict[str, Any]] = []
        try:
            self.log_monitor.scan_now()
            log_tail = self.log_monitor.tail_records(
                f"worker-{wid[:12]}", Config.postmortem_log_lines)
        except Exception:  # noqa: BLE001 - scan failed; flight-dump fallback below
            pass
        if not log_tail:
            log_tail = flight.get("log_tail") or []
        stats: Dict[str, Any] = {}
        try:
            stats = self.store.stats()
        except Exception:  # noqa: BLE001 - store gone; gauges are optional
            pass
        with self._lock:
            num_workers = len(self.workers)
        bundle = {
            "postmortem_id": pm_id,
            "kind": "worker_death",
            "worker_id": wid,
            "node_id": self.node_id.hex(),
            "is_actor": handle.is_actor,
            "actor_id": handle.actor_id_hex,
            "task": (handle.current_task.function_name
                     if handle.current_task is not None else None),
            "reason": reason,
            "flight_reason": flight.get("reason"),
            "ts": time.time(),
            "log_tail": log_tail,
            "span_tail": (prekill.get("span_tail")
                          or flight.get("span_tail") or []),
            "gauges": {
                "rss_bytes": (prekill.get("rss_bytes")
                              or flight.get("rss_bytes")),
                "store_used_bytes": stats.get("used"),
                "store_capacity_bytes": stats.get("capacity"),
                "store_pinned_bytes": stats.get("pinned_bytes"),
                "num_workers": num_workers,
            },
        }

        def _send() -> None:
            client = rpc_lib.RpcClient(self.gcs_address, timeout=10)
            try:
                client.call("postmortem_report", bundle=bundle)
            except Exception:  # noqa: BLE001 - GCS away; bundle lost
                logger.debug("postmortem report failed", exc_info=True)
            finally:
                client.close()

        threading.Thread(target=_send, daemon=True,
                         name="postmortem-report").start()
        return pm_id

    def list_workers(self) -> List[Dict[str, Any]]:
        """Worker-level metadata for the state API (`ray list workers`)."""
        with self._lock:
            return [{
                "worker_id": wid,
                "node_id": self.node_id.hex(),
                "pid": h.proc.pid if h.proc is not None else None,
                "is_actor": h.is_actor,
                "actor_id": h.actor_id_hex,
                "idle": h.current_task is None,
                "current_task": (h.current_task.function_name
                                 if h.current_task is not None else None),
            } for wid, h in self.workers.items()]

    def drain(self) -> None:
        self.shutdown()

    def shutdown(self) -> None:
        if self._dead:
            return
        self._dead = True
        from ray_tpu._private import memory_plane as _memory_plane
        from ray_tpu._private import metrics_plane as _metrics_plane
        _metrics_plane.unregister_sampler("node_manager")
        _metrics_plane.unregister_snapshot_extra(
            _memory_plane.STORE_DIGEST_KEY)
        try:
            self.memory_monitor.stop()
        except AttributeError:
            pass
        try:
            self.log_monitor.stop()
        except AttributeError:
            pass
        with self._lock:
            workers = list(self.workers.values())
        for handle in workers:
            if handle.proc is not None:
                try:
                    handle.proc.terminate()
                except OSError:
                    pass
        for handle in workers:
            if handle.proc is not None:
                try:
                    handle.proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    handle.proc.kill()
        try:
            self._gcs.call("unregister_node", node_id_hex=self.node_id.hex())
        except Exception:  # noqa: BLE001 - GCS gone; health check expires us
            pass
        self.store.shutdown()
        self.server.stop()
        self._pool.close_all()
        self._gcs.close()
