"""Parameter schedules (epsilon, lr, entropy coeff ... as f(timestep)).

reference parity: rllib/utils/schedules/ — ConstantSchedule,
LinearSchedule (schedules/linear_schedule.py), PiecewiseSchedule
(piecewise_schedule.py, endpoints + interpolation), ExponentialSchedule
(exponential_schedule.py). Pure host-side floats: schedules drive
exploration and optimizer hyperparams from the driver loop; anything that
must live *inside* a jitted update is threaded through
Learner.extra_inputs instead.
"""

from __future__ import annotations

from typing import Callable, List, Sequence, Tuple


class Schedule:
    """value(t) for a global timestep t >= 0."""

    def value(self, t: float) -> float:
        raise NotImplementedError

    def __call__(self, t: float) -> float:
        return self.value(t)


class ConstantSchedule(Schedule):
    def __init__(self, value: float):
        self._v = float(value)

    def value(self, t: float) -> float:
        return self._v


class LinearSchedule(Schedule):
    """Linear from initial_p to final_p over schedule_timesteps, then
    clamped at final_p (reference linear_schedule.py)."""

    def __init__(self, schedule_timesteps: int, final_p: float,
                 initial_p: float = 1.0):
        assert schedule_timesteps > 0
        self.schedule_timesteps = schedule_timesteps
        self.initial_p = float(initial_p)
        self.final_p = float(final_p)

    def value(self, t: float) -> float:
        frac = min(max(float(t), 0.0) / self.schedule_timesteps, 1.0)
        return self.initial_p + frac * (self.final_p - self.initial_p)


class PiecewiseSchedule(Schedule):
    """Endpoint list [(t, v), ...] with interpolation between adjacent
    endpoints; outside the range returns outside_value (reference
    piecewise_schedule.py)."""

    def __init__(self, endpoints: Sequence[Tuple[float, float]],
                 interpolation: Callable[[float, float, float], float]
                 = None,
                 outside_value: float = None):
        ends: List[Tuple[float, float]] = sorted(
            (float(t), float(v)) for t, v in endpoints)
        assert len(ends) >= 1
        self.endpoints = ends
        self.interpolation = interpolation or (
            lambda l, r, alpha: l + alpha * (r - l))
        self.outside_value = outside_value

    def value(self, t: float) -> float:
        t = float(t)
        for (lt, lv), (rt, rv) in zip(self.endpoints[:-1],
                                      self.endpoints[1:]):
            if lt <= t < rt:
                alpha = (t - lt) / (rt - lt)
                return self.interpolation(lv, rv, alpha)
        if self.outside_value is not None:
            return self.outside_value
        # clamp to nearest endpoint
        if t < self.endpoints[0][0]:
            return self.endpoints[0][1]
        return self.endpoints[-1][1]


class ExponentialSchedule(Schedule):
    """initial_p * decay_rate ** (t / schedule_timesteps)."""

    def __init__(self, schedule_timesteps: int, initial_p: float = 1.0,
                 decay_rate: float = 0.1):
        assert schedule_timesteps > 0
        self.schedule_timesteps = schedule_timesteps
        self.initial_p = float(initial_p)
        self.decay_rate = float(decay_rate)

    def value(self, t: float) -> float:
        return self.initial_p * self.decay_rate ** (
            float(t) / self.schedule_timesteps)
