"""Advantage estimation (GAE), jax + numpy.

reference parity: rllib/evaluation/postprocessing.py:89
(compute_advantages) / :158 (compute_gae_for_sample_batch).
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np


def compute_gae(rewards: np.ndarray, values: np.ndarray,
                dones: np.ndarray, bootstrap_value: np.ndarray,
                gamma: float, lambda_: float):
    """GAE over a fragment batch [T, N]; returns (advantages,
    value_targets), both [T, N]. `dones` marks episode ends (truncation
    bootstrap already folded into rewards by the runner)."""
    t_len = rewards.shape[0]
    adv = np.zeros_like(rewards)
    last = np.zeros_like(bootstrap_value)
    next_values = bootstrap_value
    for t in range(t_len - 1, -1, -1):
        not_done = 1.0 - dones[t].astype(rewards.dtype)
        delta = rewards[t] + gamma * next_values * not_done - values[t]
        last = delta + gamma * lambda_ * not_done * last
        adv[t] = last
        next_values = values[t]
    return adv, adv + values


def postprocess_fragment(batch: Dict[str, Any], gamma: float,
                         lambda_: float) -> Dict[str, np.ndarray]:
    """Fragment [T, N, ...] -> flat transition batch with advantages +
    value targets (reference compute_gae_for_sample_batch)."""
    dones = batch["terminateds"] | batch["truncateds"]
    adv, targets = compute_gae(
        batch["rewards"], batch["vf_preds"], dones,
        batch["bootstrap_value"], gamma, lambda_)

    def flat(x):
        return np.reshape(x, (-1,) + x.shape[2:])

    return {
        "obs": flat(batch["obs"]),
        "actions": flat(batch["actions"]),
        "action_logp": flat(batch["action_logp"]),
        "vf_preds": flat(batch["vf_preds"]),
        "advantages": flat(adv),
        "value_targets": flat(targets),
    }


def standardize(x: np.ndarray) -> np.ndarray:
    """reference rollout_ops standardize_fields on advantages."""
    return (x - x.mean()) / max(1e-4, x.std())
