"""Replay buffers: uniform ring + prioritized (sum-tree).

reference parity: rllib/utils/replay_buffers/replay_buffer.py
(ReplayBuffer: capacity in timesteps, add/sample over SampleBatch) and
prioritized_replay_buffer.py (PrioritizedReplayBuffer: proportional
prioritization per Schaul 2015 — sum-tree sampling, importance weights
with beta annealing, update_priorities). The reference stores pickled
SampleBatch objects per slot; the TPU build stores *columns* in
preallocated numpy rings so sample() is a vectorized gather producing a
jit-ready minibatch with stable shapes/dtypes.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np


class ReplayBuffer:
    """Uniform-sampling ring buffer over column batches of transitions."""

    def __init__(self, capacity: int = 100_000, seed: Optional[int] = None):
        assert capacity > 0
        self.capacity = int(capacity)
        self._cols: Dict[str, np.ndarray] = {}
        self._next = 0          # next write slot
        self._size = 0          # filled slots
        self._added = 0         # lifetime timesteps added
        self._evicted = 0       # lifetime slots overwritten
        self._rng = np.random.default_rng(seed)
        # Per-slot write generation: bumped on every (over)write. Sampled
        # batches carry it as `item_epochs` so a priority update that
        # arrives after the slot was recycled can be detected and dropped
        # instead of silently re-prioritizing an unrelated transition.
        self._epoch = np.zeros(self.capacity, np.int64)
        self.unmatched_priority_updates = 0

    def __len__(self) -> int:
        return self._size

    @property
    def num_added(self) -> int:
        return self._added

    def _ensure_storage(self, batch: Dict[str, np.ndarray]) -> None:
        for k, v in batch.items():
            if k not in self._cols:
                v = np.asarray(v)
                self._cols[k] = np.zeros((self.capacity, *v.shape[1:]),
                                         v.dtype)

    def add(self, batch: Dict[str, np.ndarray]) -> None:
        """Add a column batch of N transitions (row axis 0)."""
        batch = {k: np.asarray(v) for k, v in batch.items()
                 if not np.asarray(v).dtype.hasobject}
        n = len(next(iter(batch.values())))
        if n > self.capacity:  # keep only the newest capacity rows
            batch = {k: v[-self.capacity:] for k, v in batch.items()}
            n = self.capacity
        self._ensure_storage(batch)
        idx = (self._next + np.arange(n)) % self.capacity
        for k, v in batch.items():
            self._cols[k][idx] = v
        self._evicted += max(0, self._size + n - self.capacity)
        self._epoch[idx] += 1
        self._next = int((self._next + n) % self.capacity)
        self._size = min(self._size + n, self.capacity)
        self._added += n
        self._on_added(idx)

    def _on_added(self, idx: np.ndarray) -> None:
        pass

    def sample(self, num_items: int) -> Dict[str, np.ndarray]:
        assert self._size > 0, "sampling from an empty buffer"
        idx = self._rng.integers(self._size, size=num_items)
        out = {k: v[idx] for k, v in self._cols.items()}
        out["batch_indexes"] = idx
        out["item_epochs"] = self._epoch[idx].copy()
        return out

    def get_state(self) -> Dict[str, np.ndarray]:
        return {"cols": {k: v[:self._size].copy()
                         for k, v in self._cols.items()},
                "next": self._next, "size": self._size,
                "added": self._added}

    def set_state(self, state) -> None:
        self._cols = {}
        self._size = 0
        self._next = 0
        if state["size"]:
            self.add(state["cols"])
        self._next = state["next"] % self.capacity
        self._added = state["added"]


class _SumTree:
    """Binary indexed sum-tree over `capacity` leaves for O(log n)
    proportional sampling and updates (reference segment_tree.py)."""

    def __init__(self, capacity: int):
        self.capacity = capacity
        size = 1
        while size < capacity:
            size *= 2
        self.size = size
        self.tree = np.zeros(2 * size, np.float64)

    def set(self, idx: np.ndarray, values: np.ndarray) -> None:
        pos = np.asarray(idx) + self.size
        self.tree[pos] = values
        pos //= 2
        while np.any(pos >= 1):
            uniq = np.unique(pos[pos >= 1])
            self.tree[uniq] = self.tree[2 * uniq] + self.tree[2 * uniq + 1]
            pos = uniq // 2

    @property
    def total(self) -> float:
        return float(self.tree[1])

    def find(self, prefix_sums: np.ndarray) -> np.ndarray:
        """For each prefix sum, the leaf index whose cumulative range
        contains it."""
        idx = np.ones(len(prefix_sums), np.int64)
        s = np.asarray(prefix_sums, np.float64).copy()
        while idx[0] < self.size:  # all leaves at equal depth
            left = 2 * idx
            left_sum = self.tree[left]
            go_right = s > left_sum
            s = np.where(go_right, s - left_sum, s)
            idx = np.where(go_right, left + 1, left)
        return idx - self.size

    def get(self, idx: np.ndarray) -> np.ndarray:
        return self.tree[np.asarray(idx) + self.size]


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritized replay (reference
    prioritized_replay_buffer.py): p_i = (|delta_i| + eps)^alpha,
    P(i) = p_i / sum_j p_j, IS weight w_i = (N * P(i))^-beta / max w."""

    def __init__(self, capacity: int = 100_000, alpha: float = 0.6,
                 seed: Optional[int] = None):
        super().__init__(capacity, seed)
        assert alpha > 0
        self.alpha = float(alpha)
        self._tree = _SumTree(self.capacity)
        self._max_priority = 1.0
        self._eps = 1e-6

    def _on_added(self, idx: np.ndarray) -> None:
        # new transitions get max priority so everything is seen once
        self._tree.set(idx, np.full(len(idx),
                                    self._max_priority ** self.alpha))

    def sample(self, num_items: int,
               beta: float = 0.4) -> Dict[str, np.ndarray]:
        assert self._size > 0, "sampling from an empty buffer"
        total = self._tree.total
        # stratified proportional sampling
        bounds = np.linspace(0.0, total, num_items + 1)
        targets = self._rng.uniform(bounds[:-1], bounds[1:])
        idx = self._tree.find(np.minimum(targets, total * (1 - 1e-12)))
        idx = np.minimum(idx, self._size - 1)
        probs = self._tree.get(idx) / max(total, 1e-12)
        weights = (self._size * np.maximum(probs, 1e-12)) ** (-beta)
        weights = weights / weights.max()
        out = {k: v[idx] for k, v in self._cols.items()}
        out["batch_indexes"] = idx
        out["item_epochs"] = self._epoch[idx].copy()
        out["weights"] = weights.astype(np.float32)
        return out

    def update_priorities(self, idx: np.ndarray, priorities: np.ndarray,
                          epochs: Optional[np.ndarray] = None) -> int:
        """Re-prioritize sampled slots; returns the number applied.

        `epochs` (the `item_epochs` ticket from sample()) guards against
        the APEX staleness class: an update racing an overwrite of the
        same slot would otherwise land on a different transition. Stale
        tickets are dropped and counted, never applied.
        """
        idx = np.asarray(idx)
        p = np.abs(np.asarray(priorities, np.float64)) + self._eps
        if epochs is not None:
            live = self._epoch[idx] == np.asarray(epochs)
            self.unmatched_priority_updates += int((~live).sum())
            idx, p = idx[live], p[live]
            if not len(idx):
                return 0
        self._max_priority = max(self._max_priority, float(p.max()))
        # duplicate slots in one update batch: last write wins in the
        # tree either way, but dedupe keeps set() idempotent
        self._tree.set(idx, p ** self.alpha)
        return int(len(idx))

    def get_state(self):
        state = super().get_state()
        state["priorities"] = self._tree.get(np.arange(self._size))
        state["max_priority"] = self._max_priority
        return state

    def set_state(self, state) -> None:
        super().set_state(state)
        if state["size"]:
            self._tree.set(np.arange(state["size"]),
                           state["priorities"])
        self._max_priority = state["max_priority"]
