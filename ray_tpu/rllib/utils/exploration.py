"""Exploration noise processes beyond epsilon-greedy/gaussian.

reference parity: rllib/utils/exploration/ — ornstein_uhlenbeck_noise.py
(temporally-correlated action noise for continuous control) and
parameter_noise.py (Plappert et al. adaptive param-space noise: perturb
the policy WEIGHTS per episode, adapt sigma so the induced action
divergence tracks a target). Curiosity et al. stay out of scope for the
north star.

These are host-side numpy processes: the noise state lives with the
EnvRunner (one process per runner, vectorized over lanes), and
perturbed weight pytrees feed the same jitted forwards unperturbed
weights do — nothing here touches the jit boundary.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np


class OrnsteinUhlenbeckNoise:
    """dx = theta * (mu - x) * dt + sigma * sqrt(dt) * N(0,1), one state
    row per vector lane (reference ornstein_uhlenbeck_noise.py)."""

    def __init__(self, shape, theta: float = 0.15, sigma: float = 0.2,
                 mu: float = 0.0, dt: float = 1.0, seed: int = 0):
        self.theta = theta
        self.sigma = sigma
        self.mu = mu
        self.dt = dt
        self._shape = tuple(shape)
        self._rng = np.random.default_rng(seed)
        self._x = np.zeros(self._shape, np.float32)

    def reset(self, lanes=None) -> None:
        """Zero the process state (per-lane on episode end: the noise
        correlation must not bridge episodes)."""
        if lanes is None:
            self._x[:] = 0.0
        else:
            self._x[lanes] = 0.0

    def sample(self) -> np.ndarray:
        self._x = (self._x
                   + self.theta * (self.mu - self._x) * self.dt
                   + self.sigma * np.sqrt(self.dt)
                   * self._rng.standard_normal(self._shape)
                   .astype(np.float32))
        return self._x.copy()


class ParameterNoise:
    """Adaptive parameter-space noise (reference parameter_noise.py,
    Plappert et al. 2017): gaussian-perturb every weight leaf with one
    shared stddev; after each sampling round, compare the actions the
    perturbed and clean policies produce and scale sigma to keep their
    distance at `target_action_dist`."""

    def __init__(self, initial_sigma: float = 0.05,
                 target_action_dist: float = 0.1,
                 adapt_coeff: float = 1.01, seed: int = 0):
        self.sigma = float(initial_sigma)
        self.target = float(target_action_dist)
        self.coeff = float(adapt_coeff)
        self._rng = np.random.default_rng(seed)

    def perturb(self, params: Any) -> Any:
        """params pytree -> perturbed copy (host numpy)."""
        import jax

        def one(leaf):
            arr = np.asarray(leaf)
            if not np.issubdtype(arr.dtype, np.floating):
                return arr
            return arr + self._rng.normal(
                0.0, self.sigma, arr.shape).astype(arr.dtype)

        return jax.tree.map(one, params)

    def adapt(self, clean_actions: np.ndarray,
              perturbed_actions: np.ndarray) -> float:
        """Update sigma from the measured action divergence; returns the
        new sigma."""
        dist = float(np.sqrt(np.mean(
            (np.asarray(clean_actions, np.float64)
             - np.asarray(perturbed_actions, np.float64)) ** 2)))
        if dist > self.target:
            self.sigma /= self.coeff
        else:
            self.sigma *= self.coeff
        return self.sigma

    def get_state(self) -> Dict[str, float]:
        return {"sigma": self.sigma}

    def set_state(self, state: Dict[str, float]) -> None:
        self.sigma = float(state.get("sigma", self.sigma))
