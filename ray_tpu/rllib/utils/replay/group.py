"""ReplayGroup: driver-side coordinator for the replay shard fleet.

reference parity: rllib/algorithms/apex_dqn/apex_dqn.py APEX's
`training_step` owns the replay actors directly and blocks per sample;
here the coordinator runs a puller thread that keeps
`sample_inflight_per_shard` requests pipelined against every healthy
shard through FaultTolerantActorManager (`foreach_actor_async` +
`fetch_ready_async_reqs`), stages each arriving batch through HostStage
(so the learner's chip-feed sees pooled, fused segments — never a fresh
np.concatenate), and parks it in a bounded queue the learner thread
drains. Backpressure is the queue bound: when the learner falls behind,
the puller blocks before submitting more sample RPCs.

Elasticity: a shard actor death demotes it in the manager; the puller
replaces it inline with a fresh empty shard of the same shard_id
(generation bumped in the named-actor registry), bumps
`reshard_version`, and keeps pulling from the survivors meanwhile —
training never halts, matching the elastic-runner contract from PR 14.
"""

from __future__ import annotations

import queue
import threading
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu.rllib.utils.device_feed import HostStage
from ray_tpu.rllib.utils.replay.shard import (REPLAY_NAMESPACE,
                                              ReplayShardActor,
                                              shard_actor_name)
from ray_tpu.util.actor_manager import FaultTolerantActorManager


class ReplayGroup:
    """Spawns and coordinates N replay shards for one training job.

    The learner side consumes `group.queue` (items are
    `(StagedBatch, meta)` with `meta["shard_id"]` naming the ticket
    issuer) either directly via `get_batch()` or through a DeviceFeed,
    and routes TD-error priorities back with `update_priorities()` —
    one-way, fire-and-forget, reaped in the background.
    """

    def __init__(self, num_shards: int, capacity: int, *,
                 prioritized: bool = True, alpha: float = 0.6,
                 beta: float = 0.4, batch_size: int = 32,
                 min_size_to_sample: int = 1, seed: Optional[int] = None,
                 name: str = "default", sample_inflight_per_shard: int = 2,
                 queue_depth: int = 4, shard_num_cpus: float = 0.25):
        assert num_shards > 0
        self.name = name
        self.num_shards = num_shards
        self.capacity = int(capacity)
        self.prioritized = prioritized
        self.alpha = alpha
        self.beta = beta
        self.batch_size = int(batch_size)
        self.min_size_to_sample = int(min_size_to_sample)
        self._seed = seed
        self._shard_num_cpus = shard_num_cpus
        self._gen: Dict[int, int] = {}          # shard_id -> generation
        self._aid_to_sid: Dict[int, int] = {}   # manager id -> shard_id
        self._mgr = FaultTolerantActorManager(
            max_remote_requests_in_flight_per_actor=(
                sample_inflight_per_shard),
            health_probe_method="ping")
        for sid in range(num_shards):
            self._spawn_shard(sid)
        self._stage = HostStage(slots=queue_depth + 4)
        self.queue: "queue.Queue[Tuple[Any, Dict[str, Any]]]" = \
            queue.Queue(maxsize=queue_depth)
        self.reshard_version = 0
        self.shard_replacements = 0
        self.batches_pulled = 0
        self.updates_sent = 0
        self.updates_dropped = 0
        self._update_refs: deque = deque()
        self._stop = threading.Event()
        self._puller: Optional[threading.Thread] = None

    # ---- shard lifecycle -------------------------------------------------

    def _spawn_shard(self, shard_id: int) -> int:
        gen = self._gen.get(shard_id, -1) + 1
        self._gen[shard_id] = gen
        cls = ray_tpu.remote(ReplayShardActor)
        actor = cls.options(
            num_cpus=self._shard_num_cpus,
            name=shard_actor_name(self.name, shard_id, gen),
            namespace=REPLAY_NAMESPACE,
        ).remote(shard_id, self.capacity, prioritized=self.prioritized,
                 alpha=self.alpha, seed=self._seed, group=self.name)
        aid = self._mgr.add_actor(actor)
        self._aid_to_sid[aid] = shard_id
        return aid

    def _replace_dead_shards(self) -> None:
        """Elastic re-add: every unhealthy shard is removed and respawned
        empty under the same shard_id (new generation). The replay data
        it held is lost — acceptable for replay (it refills from the
        runners), unacceptable would be halting training."""
        dead = [aid for aid in list(self._aid_to_sid)
                if not self._mgr.is_actor_healthy(aid)]
        for aid in dead:
            sid = self._aid_to_sid.pop(aid)
            self._mgr.remove_actor(aid)
            self._spawn_shard(sid)
            self.shard_replacements += 1
            self.reshard_version += 1

    def shard_handles(self) -> List[Tuple[int, Any]]:
        """(shard_id, handle) pairs for the current generation — the
        writer spec shipped to env runners (handles are picklable)."""
        actors = self._mgr.actors()
        return sorted(
            ((self._aid_to_sid[aid], actors[aid])
             for aid in actors if aid in self._aid_to_sid),
            key=lambda t: t[0])

    # ---- pull pipeline ---------------------------------------------------

    def start(self) -> None:
        if self._puller is not None:
            return
        self._puller = threading.Thread(
            target=self._pull_loop, name=f"replay-pull-{self.name}",
            daemon=True)
        self._puller.start()

    def _pull_loop(self) -> None:
        sample_call = ("sample",
                       (self.batch_size, self.beta,
                        self.min_size_to_sample), None)
        while not self._stop.is_set():
            self._mgr.foreach_actor_async(sample_call, tag="sample")
            results = self._mgr.fetch_ready_async_reqs(
                timeout_seconds=0.2)
            produced = 0
            saw_failure = False
            for res in results:
                if not res.ok:
                    saw_failure = True
                    continue
                if res.value is None:  # shard below learning-starts gate
                    continue
                staged = self._stage.assemble([res.value], lambda k: 0)
                meta = {"shard_id": self._aid_to_sid.get(res.actor_id)}
                while not self._stop.is_set():
                    try:  # bounded queue IS the backpressure valve
                        self.queue.put((staged, meta), timeout=0.5)
                        produced += 1
                        self.batches_pulled += 1
                        break
                    except queue.Full:
                        continue
                else:
                    staged.release()
            if saw_failure:
                self._replace_dead_shards()
            if not produced and not results:
                self._stop.wait(0.02)
        # drain staged batches the learner will never take
        while True:
            try:
                staged, _ = self.queue.get_nowait()
                staged.release()
            except queue.Empty:
                break

    def get_batch(self, timeout: float = 1.0
                  ) -> Optional[Tuple[Any, Dict[str, Any]]]:
        """One (StagedBatch, meta) from the pull pipeline, or None on
        timeout. Caller owns the StagedBatch and must release() it."""
        try:
            return self.queue.get(timeout=timeout)
        except queue.Empty:
            return None

    # ---- priority feedback (one-way) -------------------------------------

    def update_priorities(self, shard_id: int, idx: np.ndarray,
                          priorities: np.ndarray,
                          epochs: Optional[np.ndarray] = None) -> bool:
        """Route TD-error priorities back to the issuing shard.
        Fire-and-forget: the ref is reaped later, never awaited on the
        training path. Returns False when the shard is gone (its
        replacement is empty — the tickets are meaningless there)."""
        while len(self._update_refs) > 64:  # hard cap, never block
            self._update_refs.popleft()
        if self._update_refs:
            done, _ = ray_tpu.wait(list(self._update_refs),
                                   num_returns=len(self._update_refs),
                                   timeout=0)
            for ref in done:
                self._update_refs.remove(ref)
        handle = None
        actors = self._mgr.actors()
        for aid, sid in self._aid_to_sid.items():
            if sid == shard_id and self._mgr.is_actor_healthy(aid):
                handle = actors.get(aid)
                break
        if handle is None:
            self.updates_dropped += 1
            return False
        self._update_refs.append(
            handle.update_priorities.remote(
                np.asarray(idx), np.asarray(priorities),
                None if epochs is None else np.asarray(epochs)))
        self.updates_sent += 1
        return True

    # ---- health / introspection ------------------------------------------

    def probe_unhealthy(self) -> None:
        self._mgr.probe_unhealthy_actors(timeout_seconds=5.0)
        self._replace_dead_shards()

    def shard_stats(self, timeout: float = 10.0) -> List[Dict[str, Any]]:
        res = self._mgr.foreach_actor("stats",
                                      timeout_seconds=timeout)
        return [r.value for r in res if r.ok]

    def stats(self) -> Dict[str, Any]:
        return {
            "num_shards": self.num_shards,
            "healthy_shards": self._mgr.num_healthy_actors(),
            "reshard_version": self.reshard_version,
            "shard_replacements": self.shard_replacements,
            "batches_pulled": self.batches_pulled,
            "queue_depth": self.queue.qsize(),
            "priority_updates_sent": self.updates_sent,
            "priority_updates_dropped": self.updates_dropped,
        }

    def stop(self) -> None:
        self._stop.set()
        if self._puller is not None:
            self._puller.join(timeout=5.0)
            self._puller = None
        self._mgr.clear()
