"""ReplayShardActor: one shard of the distributed replay plane.

reference parity: rllib/algorithms/apex_dqn/apex_dqn.py ReplayActor —
a plain actor wrapping one (Prioritized)ReplayBuffer. Differences that
matter here: sampled batches carry (batch_indexes, item_epochs) tickets
so late priority updates for recycled slots are dropped instead of
re-prioritizing an unrelated transition, and every op is metered
(`ray_tpu_replay_*_total{shard}`) and spanned (`replay.push/sample/
update`) so the merged timeline and the `replay_shard_stall` watchdog
probe see the shard from day one.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from ray_tpu._private import spans as _spans
from ray_tpu.rllib.utils.replay_buffers import (PrioritizedReplayBuffer,
                                                ReplayBuffer)

REPLAY_NAMESPACE = "_replay"


def shard_actor_name(group: str, shard_id: int, generation: int) -> str:
    """Named-actor key for one shard generation; the generation bumps on
    every elastic replacement so a dead shard's registry entry never
    collides with its successor."""
    return f"RAY_TPU_REPLAY_SHARD:{group}:{shard_id}:{generation}"


def _shard_metrics():
    from ray_tpu.util.metrics import Counter, get_or_create
    mk = {}
    for op in ("added", "sampled", "evicted", "priority_updates",
               "unmatched_priority_updates"):
        mk[op] = get_or_create(
            Counter, f"ray_tpu_replay_{op}_total",
            description=f"replay plane: {op.replace('_', ' ')} per shard",
            tag_keys=("shard",))
    return mk


class ReplayShardActor:
    """One bounded replay shard with local priorities.

    Runs as a plain actor; the plain (uniform) and prioritized
    (sum-tree) variants share this class — `prioritized` picks the
    buffer. Pushes arrive as already-resolved store values: the writer
    passes a top-level ObjectRef so the payload rides the scatter-put
    envelope into the shared store once and is mapped here zero-copy,
    never re-pickled through actor args (core_worker arg resolution).
    """

    def __init__(self, shard_id: int, capacity: int, *,
                 prioritized: bool = True, alpha: float = 0.6,
                 seed: Optional[int] = None, group: str = "default"):
        self.shard_id = int(shard_id)
        self.group = group
        shard_seed = None if seed is None else seed + shard_id * 7919
        if prioritized:
            self.buffer: ReplayBuffer = PrioritizedReplayBuffer(
                capacity, alpha=alpha, seed=shard_seed)
        else:
            self.buffer = ReplayBuffer(capacity, seed=shard_seed)
        self.prioritized = prioritized
        self._tags = {"shard": str(self.shard_id)}
        self._metrics = _shard_metrics()
        self._evicted_seen = 0
        self._push_rpcs = 0
        self._sample_rpcs = 0
        self._update_rpcs = 0
        self._sampled_items = 0
        # occupancy rides the harvest as a register_sampler gauge (like
        # serve/_telemetry): point-in-time, no hot-path instrumentation
        from ray_tpu._private import metrics_plane
        from ray_tpu.util.metrics import Gauge, get_or_create
        occupancy = get_or_create(
            Gauge, "ray_tpu_replay_occupancy",
            description="replay shard: filled slots", tag_keys=("shard",))

        def _sample_gauges(buf=self.buffer, tags=dict(self._tags)):
            occupancy.set(float(len(buf)), tags=tags)

        metrics_plane.register_sampler(
            f"replay_shard_{group}_{shard_id}", _sample_gauges)

    def ping(self) -> str:
        """Health probe (FaultTolerantActorManager contract)."""
        return "pong"

    # ---- write path --------------------------------------------------
    def push(self, batch: Dict[str, np.ndarray],
             priorities: Optional[np.ndarray] = None) -> Dict[str, int]:
        """Append a transition column batch; `priorities` optionally
        seeds the new slots (APEX worker-computed initial priorities),
        else new items get max priority (Schaul init)."""
        n = len(next(iter(batch.values())))
        with _spans.span("replay.push", shard=self.shard_id, n=n):
            if priorities is not None and self.prioritized:
                # slots the ring is about to write, before add() moves
                # the cursor — lets the explicit priorities overwrite
                # the max-priority default right after insert
                m = min(n, self.buffer.capacity)
                idx = (self.buffer._next + np.arange(m)) \
                    % self.buffer.capacity
                self.buffer.add(batch)
                self.buffer.update_priorities(
                    idx, np.asarray(priorities)[-m:])
            else:
                self.buffer.add(batch)
        self._push_rpcs += 1
        self._metrics["added"].inc(n, tags=self._tags)
        ev = self.buffer._evicted - self._evicted_seen
        if ev:
            self._metrics["evicted"].inc(ev, tags=self._tags)
            self._evicted_seen = self.buffer._evicted
        return {"added": n, "size": len(self.buffer)}

    # ---- read path ---------------------------------------------------
    def sample(self, num_items: int, beta: float = 0.4,
               min_size: int = 1) -> Optional[Dict[str, np.ndarray]]:
        """One sample batch with (batch_indexes, item_epochs) tickets
        and IS weights, or None while the shard holds fewer than
        max(num_items, min_size) items (learning-starts gate)."""
        self._sample_rpcs += 1
        if len(self.buffer) < max(num_items, min_size):
            return None
        with _spans.span("replay.sample", shard=self.shard_id,
                         n=num_items):
            if self.prioritized:
                out = self.buffer.sample(num_items, beta=beta)
            else:
                out = self.buffer.sample(num_items)
        self._sampled_items += num_items
        self._metrics["sampled"].inc(num_items, tags=self._tags)
        return out

    # ---- priority feedback (one-way from the learner) ----------------
    def update_priorities(self, idx: np.ndarray, priorities: np.ndarray,
                          epochs: Optional[np.ndarray] = None) -> int:
        """Apply TD-error priorities for previously sampled tickets;
        stale tickets (slot recycled since the sample) are dropped and
        counted. Returns the number applied."""
        self._update_rpcs += 1
        if not self.prioritized:
            return 0
        with _spans.span("replay.update", shard=self.shard_id,
                         n=len(np.asarray(idx))):
            before = self.buffer.unmatched_priority_updates
            applied = self.buffer.update_priorities(
                idx, priorities, epochs=epochs)
            unmatched = self.buffer.unmatched_priority_updates - before
        if applied:
            self._metrics["priority_updates"].inc(applied,
                                                  tags=self._tags)
        if unmatched:
            self._metrics["unmatched_priority_updates"].inc(
                unmatched, tags=self._tags)
        return applied

    # ---- introspection (state surface / CLI / dashboard) -------------
    def stats(self) -> Dict[str, Any]:
        return {
            "shard_id": self.shard_id,
            "group": self.group,
            "prioritized": self.prioritized,
            "size": len(self.buffer),
            "capacity": self.buffer.capacity,
            "added": self.buffer.num_added,
            "evicted": self.buffer._evicted,
            "sampled": self._sampled_items,
            "push_rpcs": self._push_rpcs,
            "sample_rpcs": self._sample_rpcs,
            "update_rpcs": self._update_rpcs,
            "unmatched_priority_updates":
                self.buffer.unmatched_priority_updates,
            "max_priority": getattr(self.buffer, "_max_priority", None),
        }
