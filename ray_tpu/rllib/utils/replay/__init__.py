"""Distributed replay plane: sharded (prioritized) replay actor service.

reference parity: rllib/algorithms/apex_dqn/apex_dqn.py — the APEX/R2D2
pattern where N replay-shard actors each own a bounded local buffer with
local priorities, env runners hash-route trajectory fragments to shards
through the zero-copy object plane, the learner pulls sample batches
concurrently from every shard, and TD-error priority updates flow back
one-way. The reference builds this from ReplayActor +
ActorHandle round-robin; here the three roles are explicit:

  - `ReplayShardActor` (shard.py): one shard — buffer + local sum-tree
    priorities, epoch-ticketed sampling, per-shard metrics/spans.
  - `ReplayWriter` (writer.py): runner-side pusher — crc32 hash
    routing, scatter-put refs (payload never re-pickles through actor
    args), bounded per-shard inflight with shed counters.
  - `ReplayGroup` (group.py): driver-side coordinator — shard spawn /
    placement, pipelined concurrent pulls (fetch_ready_async_reqs
    style) staged through HostStage, one-way priority-update routing,
    and resharding on shard death (elastic re-add of an empty shard).
"""

from ray_tpu.rllib.utils.replay.group import ReplayGroup
from ray_tpu.rllib.utils.replay.shard import (REPLAY_NAMESPACE,
                                              ReplayShardActor,
                                              shard_actor_name)
from ray_tpu.rllib.utils.replay.writer import ReplayWriter, route_shard

__all__ = ["ReplayGroup", "ReplayShardActor", "ReplayWriter",
           "REPLAY_NAMESPACE", "route_shard", "shard_actor_name"]
