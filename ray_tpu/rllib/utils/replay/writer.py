"""ReplayWriter: runner-side push client for the replay plane.

reference parity: rllib/algorithms/apex_dqn/apex_dqn.py pushes whole
SampleBatches through `ReplayActor.add.remote(batch)` round-robin,
re-pickling every fragment through actor-arg serialization. Here the
fragment goes through the scatter-put envelope once (`ray_tpu.put`) and
only the ObjectRef rides the RPC — the shard maps the columns out of
shared memory zero-copy (visible as flat `ray_tpu_transport_*` counters,
not per-push copies). Routing is a stable crc32 hash (python `hash()`
is salted per process and would break routing determinism), and pushes
are bounded per shard: when a shard's inflight window is full the
fragment is shed and counted rather than queueing unboundedly behind a
slow or dying shard.
"""

from __future__ import annotations

import weakref
import zlib
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import ray_tpu

_WRITERS: "weakref.WeakSet[ReplayWriter]" = weakref.WeakSet()
_SAMPLER_REGISTERED = False


def route_shard(key: str, num_shards: int) -> int:
    """Deterministic fragment→shard routing (stable across processes)."""
    return zlib.crc32(key.encode()) % max(1, num_shards)


def _ensure_inflight_sampler() -> None:
    """One process-wide gauge sampler covering every live writer (the
    serve/_telemetry WeakSet pattern)."""
    global _SAMPLER_REGISTERED
    if _SAMPLER_REGISTERED:
        return
    _SAMPLER_REGISTERED = True
    from ray_tpu._private import metrics_plane
    from ray_tpu.util.metrics import Gauge, get_or_create
    gauge = get_or_create(
        Gauge, "ray_tpu_replay_push_inflight",
        description="replay writer: un-acked pushes per shard",
        tag_keys=("shard",))

    def _sample():
        totals: Dict[str, int] = {}
        for w in list(_WRITERS):
            for sid, dq in w._inflight.items():
                totals[str(sid)] = totals.get(str(sid), 0) + len(dq)
        for sid, n in totals.items():
            gauge.set(float(n), tags={"shard": sid})

    metrics_plane.register_sampler("replay_push_inflight", _sample)


class ReplayWriter:
    """Pushes transition batches from one env runner to the shard set.

    `shards` is a list of (shard_id, ActorHandle) pairs (handles are
    picklable, so the driver ships them inside the writer spec). The
    inflight window is reaped opportunistically on every push; a push
    that would exceed `max_inflight_per_shard` is shed — backpressure
    surfaces as `ray_tpu_replay_push_shed_total{shard}` instead of an
    unbounded driver-side queue.
    """

    def __init__(self, shards: Sequence[Tuple[int, Any]],
                 max_inflight_per_shard: int = 4):
        self._shards: List[Tuple[int, Any]] = list(shards)
        self._max_inflight = int(max_inflight_per_shard)
        self._inflight: Dict[int, deque] = {
            sid: deque() for sid, _ in self._shards}
        self._seq = 0
        self.pushes = 0
        self.shed = 0
        self.push_errors = 0
        from ray_tpu.util.metrics import Counter, get_or_create
        self._shed_metric = get_or_create(
            Counter, "ray_tpu_replay_push_shed_total",
            description="replay writer: pushes shed by backpressure",
            tag_keys=("shard",))
        _WRITERS.add(self)
        _ensure_inflight_sampler()

    def set_shards(self, shards: Sequence[Tuple[int, Any]]) -> None:
        """Swap in fresh handles after a reshard; inflight refs against
        replaced shards are dropped (the acks would error anyway)."""
        new = {sid: h for sid, h in shards}
        for sid, _ in self._shards:
            if sid not in new:
                self._inflight.pop(sid, None)
        self._shards = list(shards)
        for sid, _ in self._shards:
            self._inflight.setdefault(sid, deque())

    def _reap(self, sid: int) -> None:
        dq = self._inflight[sid]
        if not dq:
            return
        ready, _ = ray_tpu.wait(list(dq), num_returns=len(dq),
                                timeout=0)
        for ref in ready:
            dq.remove(ref)
            try:
                ray_tpu.get(ref)  # graftlint: disable=RT002
            except Exception:
                self.push_errors += 1

    def push(self, batch: Dict[str, np.ndarray],
             priorities: Optional[np.ndarray] = None,
             route_key: Optional[str] = None) -> Optional[int]:
        """Route one column batch to its shard. Returns the shard id the
        batch went to, or None if it was shed."""
        if not self._shards:
            return None
        if route_key is None:
            route_key = str(self._seq)
        self._seq += 1
        pos = route_shard(route_key, len(self._shards))
        sid, handle = self._shards[pos]
        self._reap(sid)
        dq = self._inflight[sid]
        if len(dq) >= self._max_inflight:
            self.shed += 1
            self._shed_metric.inc(1, tags={"shard": str(sid)})
            return None
        # scatter-put the payload once; the shard resolves the top-level
        # ref from the store — the batch never re-pickles through args
        ref = ray_tpu.put(batch)
        dq.append(handle.push.remote(ref, priorities))
        self.pushes += 1
        return sid

    def flush(self, timeout: float = 10.0) -> None:
        """Block until all inflight pushes ack (bench/test teardown)."""
        refs = [r for dq in self._inflight.values() for r in dq]
        if refs:
            ray_tpu.wait(refs, num_returns=len(refs), timeout=timeout)
        for sid in list(self._inflight):
            self._reap(sid)

    def stats(self) -> Dict[str, int]:
        return {
            "pushes": self.pushes,
            "shed": self.shed,
            "push_errors": self.push_errors,
            "inflight": sum(len(d) for d in self._inflight.values()),
        }
